"""Control-flow layers: DynamicRNN, StaticRNN.

≙ reference python/paddle/fluid/layers/control_flow.py (DynamicRNN:1313,
StaticRNN:383). The reference interprets sub-blocks per timestep through
recurrent_op's StepScopes (recurrent_op.cc:53-222); here the sub-block is
*traced* once into a lax.scan body (ops/rnn_ops.py dynamic_rnn) — compiled,
fused, differentiable through scan's native VJP.
"""

from __future__ import annotations

from typing import List, Optional

from ..core.program import VarDesc, default_main_program, unique_name
from ..layer_helper import LayerHelper
from .sequence import _mark_seq

__all__ = ["DynamicRNN", "StaticRNN", "While", "Switch", "IfElse",
           "Pipeline",
           "increment", "array_write", "array_read", "create_array",
           "array_length", "max_sequence_len", "Print",
           "less_than", "less_equal", "greater_than", "greater_equal",
           "equal", "not_equal", "logical_and", "logical_or", "logical_not"]


def _compare_layer(op_type):
    def layer(x, y, cond=None, **kwargs):
        helper = LayerHelper(op_type)
        if cond is None:
            cond = helper.create_tmp_variable("bool")
        helper.append_op(op_type, {"X": x, "Y": y}, {"Out": cond}, {})
        return cond

    layer.__name__ = op_type
    layer.__doc__ = (f"{op_type} comparison (≙ layers/control_flow.py); "
                     "pass cond= to rebind an existing bool var (the While "
                     "idiom for updating the loop condition).")
    return layer


less_than = _compare_layer("less_than")
less_equal = _compare_layer("less_equal")
greater_than = _compare_layer("greater_than")
greater_equal = _compare_layer("greater_equal")
equal = _compare_layer("equal")
not_equal = _compare_layer("not_equal")


def _logical_layer(op_type, unary=False):
    def layer(x, y=None, out=None, **kwargs):
        helper = LayerHelper(op_type)
        if out is None:
            out = helper.create_tmp_variable("bool")
        ins = {"X": x} if unary else {"X": x, "Y": y}
        helper.append_op(op_type, ins, {"Out": out}, {})
        return out

    layer.__name__ = op_type
    return layer


logical_and = _logical_layer("logical_and")
logical_or = _logical_layer("logical_or")
logical_not = _logical_layer("logical_not", unary=True)


def _written_outer_vars(sub_block) -> List[str]:
    """Outer-block names a sub-block's ops rebind — the carry/written set
    (≙ while_op.cc's input/output var scanning)."""
    seen = []
    for op in sub_block.ops:
        for n in op.output_names():
            if n not in sub_block.vars and n not in seen:
                seen.append(n)
    return seen


def _read_outer_vars(sub_block) -> List[str]:
    """Outer-block names a sub-block's ops read. Declared as the flow op's
    inputs so Program.prune keeps their producers (the reference's while op
    declares X inputs for the same reason, while_op.cc)."""
    seen = []
    for op in sub_block.ops:
        for n in op.input_names():
            if n not in sub_block.vars and n not in seen:
                seen.append(n)
    return seen


def increment(x, value=1.0, in_place=True):
    """layers/control_flow.py increment: x += value (dtype-preserving)."""
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_tmp_variable(x.dtype)
    helper.append_op("increment", {"X": x}, {"Out": out}, {"step": value})
    return out


def create_array(dtype, max_len, element_shape=()):
    """Dense tensor array (≙ create_array + LOD_TENSOR_ARRAY var, re-read
    as a preallocated [max_len, ...] buffer for static shapes)."""
    from .tensor import fill_constant
    arr = fill_constant([max_len] + list(element_shape), dtype, 0.0)
    # arrays collect differentiable per-step outputs; fill_constant's
    # stop_gradient=True would sever grads at every array_write rebind
    arr.stop_gradient = False
    return arr


def array_write(x, i, array):
    """write_to_array: array[i] = x; returns the array (rebinding its
    name, ≙ the reference's in-place array mutation)."""
    helper = LayerHelper("array_write")
    helper.append_op("array_write", {"Array": array, "X": x, "I": i},
                     {"Out": array}, {})
    return array


def array_read(array, i):
    """read_from_array: returns array[i]."""
    helper = LayerHelper("array_read")
    out = helper.create_tmp_variable(array.dtype)
    out.shape = tuple(array.shape[1:])
    helper.append_op("array_read", {"Array": array, "I": i}, {"Out": out}, {})
    return out


class While:
    """General while loop (≙ layers/control_flow.py:608 While +
    while_op.cc). The body mutates outer vars (increment, assign,
    less_than(..., cond=cond), array_write); every outer var the body
    writes becomes loop carry, and the op rebinds them on exit.

    max_iters: when given, lowers to a fixed-length masked lax.scan —
    bounded AND reverse-differentiable (a free lax.while_loop is not).

        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            ...
            layers.increment(i)
            layers.less_than(i, n, cond=cond)
    """

    def __init__(self, cond, max_iters: Optional[int] = None, name=None):
        if cond.dtype != "bool":
            raise TypeError("While condition must be a bool var")
        self.cond = cond
        self.max_iters = max_iters
        self.main_program = default_main_program()
        parent_idx = self.main_program.current_block().idx
        self.sub_block = self.main_program.create_block(parent_idx)

    class _Ctx:
        def __init__(self, w):
            self.w = w

        def __enter__(self):
            self._guard = self.w.main_program.block_guard(self.w.sub_block)
            self._guard.__enter__()
            return self

        def __exit__(self, *exc):
            self._guard.__exit__(*exc)
            if exc[0] is None:
                self.w._append_op()
            return False

    def block(self):
        return While._Ctx(self)

    def _append_op(self):
        written = _written_outer_vars(self.sub_block)
        carry = list(written)
        if self.cond.name not in carry:
            carry.append(self.cond.name)
        reads = _read_outer_vars(self.sub_block)
        ins = list(dict.fromkeys(carry + reads))
        parent = self.main_program.block(self.sub_block.parent_idx)
        parent.append_op(
            "while", {"X": ins}, {"Out": carry},
            {"sub_block": self.sub_block.idx, "cond": self.cond.name,
             "loop_vars": carry, "max_iters": self.max_iters})


class Switch:
    """First-true-case-wins switch (≙ layers/control_flow.py:1211),
    the piecewise-LR building block:

        with layers.Switch() as sw:
            with sw.case(step < b1):
                layers.assign(v1, lr)
            with sw.default():
                layers.assign(v2, lr)
    """

    def __init__(self, name=None):
        self.main_program = default_main_program()
        self.parent_idx = self.main_program.current_block().idx
        self.case_conds: List[VarDesc] = []
        self.case_blocks = []
        self.default_block = None
        self._inside = False

    def __enter__(self):
        self._inside = True
        return self

    def __exit__(self, *exc):
        self._inside = False
        if exc[0] is None:
            self._append_op()
        return False

    class _CaseCtx:
        def __init__(self, switch, block):
            self.switch, self.block = switch, block

        def __enter__(self):
            self._guard = self.switch.main_program.block_guard(self.block)
            self._guard.__enter__()
            return self

        def __exit__(self, *exc):
            self._guard.__exit__(*exc)
            return False

    def case(self, condition):
        if not self._inside:
            raise RuntimeError("Switch.case must be used inside "
                               "'with Switch()'")
        blk = self.main_program.create_block(self.parent_idx)
        self.case_conds.append(condition)
        self.case_blocks.append(blk)
        return Switch._CaseCtx(self, blk)

    def default(self):
        if not self._inside:
            raise RuntimeError("Switch.default must be used inside "
                               "'with Switch()'")
        blk = self.main_program.create_block(self.parent_idx)
        self.default_block = blk
        return Switch._CaseCtx(self, blk)

    def _append_op(self):
        blocks = list(self.case_blocks)
        if self.default_block is not None:
            blocks.append(self.default_block)
        written: List[str] = []
        for b in blocks:
            for n in _written_outer_vars(b):
                if n not in written:
                    written.append(n)
        if not blocks:
            raise RuntimeError("empty Switch")
        reads: List[str] = []
        for b in blocks:
            for n in _read_outer_vars(b):
                if n not in reads:
                    reads.append(n)
        parent = self.main_program.block(self.parent_idx)
        parent.append_op(
            "switch", {"Conds": [c.name for c in self.case_conds],
                       "X": list(dict.fromkeys(written + reads))},
            {"Out": written},
            {"sub_blocks": [b.idx for b in blocks],
             "has_default": self.default_block is not None,
             "written_vars": written})


class IfElse:
    """Batch-wise branch select (≙ layers/control_flow.py:1070 IfElse).
    cond is [B, 1] bool; each ROW takes its branch's output. The TPU
    lowering computes both branches on the full batch and row-selects
    (no dynamic shapes — ops/flow_ops.py ifelse)."""

    def __init__(self, cond, name=None):
        self.cond = cond
        self.main_program = default_main_program()
        self.parent_idx = self.main_program.current_block().idx
        self.true_sub = self.main_program.create_block(self.parent_idx)
        self.false_sub = self.main_program.create_block(self.parent_idx)
        self._outputs = {True: [], False: []}
        self._current: Optional[bool] = None

    class _BranchCtx:
        def __init__(self, ie, is_true):
            self.ie, self.is_true = ie, is_true

        def __enter__(self):
            self.ie._current = self.is_true
            blk = self.ie.true_sub if self.is_true else self.ie.false_sub
            self._guard = self.ie.main_program.block_guard(blk)
            self._guard.__enter__()
            return self

        def __exit__(self, *exc):
            self._guard.__exit__(*exc)
            self.ie._current = None
            return False

    def true_block(self):
        return IfElse._BranchCtx(self, True)

    def false_block(self):
        return IfElse._BranchCtx(self, False)

    def input(self, x):
        """The reference slices rows for the active branch; the full-batch
        lowering passes the var through unchanged."""
        if self._current is None:
            raise RuntimeError("IfElse.input used outside a branch block")
        return x

    def output(self, *outs):
        if self._current is None:
            raise RuntimeError("IfElse.output used outside a branch block")
        self._outputs[self._current].extend(outs)

    def __call__(self):
        t_outs, f_outs = self._outputs[True], self._outputs[False]
        if len(t_outs) != len(f_outs):
            raise ValueError("IfElse branches declared different numbers "
                             f"of outputs: {len(t_outs)} vs {len(f_outs)}")
        if not t_outs:
            raise ValueError("IfElse has no outputs")
        parent = self.main_program.block(self.parent_idx)
        merged = []
        for tv, fv in zip(t_outs, f_outs):
            out = parent.create_var(unique_name("ifelse_out"),
                                    shape=tv.shape, dtype=tv.dtype)
            merged.append(out)
        reads = list(dict.fromkeys(_read_outer_vars(self.true_sub)
                                   + _read_outer_vars(self.false_sub)))
        parent.append_op(
            "ifelse", {"Cond": self.cond.name, "X": reads},
            {"Out": [m.name for m in merged]},
            {"true_block": self.true_sub.idx,
             "false_block": self.false_sub.idx,
             "output_pairs": [(t.name, f.name)
                              for t, f in zip(t_outs, f_outs)]})
        return merged if len(merged) > 1 else merged[0]


class DynamicRNN:
    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.main_program = default_main_program()
        self.status = DynamicRNN.BEFORE_RNN
        parent_idx = self.main_program._block_stack[-1]
        self.sub_block = self.main_program.create_block(parent_idx)
        self.parent_block = self.main_program.block(parent_idx)
        self.step_outer: List[VarDesc] = []
        self.static_outer: List[VarDesc] = []
        self.step_inner: List[VarDesc] = []
        self.memories: List[VarDesc] = []
        self.mem_init_vars: List[Optional[VarDesc]] = []
        self.mem_init_values: List[float] = []
        self.mem_shapes: List[list] = []
        self.mem_dtypes: List[str] = []
        self.mem_updates = {}
        self.output_inner: List[VarDesc] = []
        self.outputs_outer: List[VarDesc] = []
        self.seq_len_name: Optional[str] = None

    # -- context ------------------------------------------------------------
    class _BlockCtx:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            rnn = self.rnn
            rnn.status = DynamicRNN.IN_RNN
            rnn._guard = rnn.main_program.block_guard(rnn.sub_block)
            rnn._guard.__enter__()
            return rnn

        def __exit__(self, exc_type, *exc):
            rnn = self.rnn
            rnn._guard.__exit__(exc_type, *exc)
            rnn.status = DynamicRNN.AFTER_RNN
            if exc_type is None:
                rnn._append_rnn_op()
            return False

    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise RuntimeError("rnn.block() can only be entered once")
        return DynamicRNN._BlockCtx(self)

    # -- builder API (mirrors control_flow.py DynamicRNN) -------------------
    def step_input(self, x: VarDesc) -> VarDesc:
        self._assert_in_rnn("step_input")
        if not getattr(x, "seq_len_var", None):
            raise ValueError(f"step_input {x.name} must be a sequence var")
        if self.seq_len_name is None:
            self.seq_len_name = x.seq_len_var
        inner = self.sub_block.create_var(
            unique_name("dynamic_rnn_step_in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self.step_outer.append(x)
        self.step_inner.append(inner)
        return inner

    def memory(self, init: Optional[VarDesc] = None, shape=None,
               value: float = 0.0, need_reorder: bool = False,
               dtype: str = "float32") -> VarDesc:
        self._assert_in_rnn("memory")
        if init is not None:
            inner = self.sub_block.create_var(
                unique_name("dynamic_rnn_mem"), shape=init.shape,
                dtype=init.dtype)
            self.mem_init_vars.append(init)
            self.mem_shapes.append(list(init.shape))
            self.mem_init_values.append(0.0)
            self.mem_dtypes.append(str(init.dtype))
        else:
            assert shape is not None
            inner = self.sub_block.create_var(
                unique_name("dynamic_rnn_mem"), shape=(-1,) + tuple(shape),
                dtype=dtype)
            self.mem_init_vars.append(None)
            self.mem_shapes.append(list(shape))
            self.mem_init_values.append(float(value))
            self.mem_dtypes.append(str(dtype))
        self.memories.append(inner)
        return inner

    def static_input(self, x: VarDesc) -> VarDesc:
        """≙ DynamicRNN.static_input (control_flow.py:1313 area). The
        reference copies/reorders a parent-scope LoDTensor into each step
        scope; here sub-block ops read outer vars directly from the
        enclosing trace environment (ops/rnn_ops.py dynamic_rnn `outer_env`),
        so the full [B, T, ...] tensor is visible at every step as-is.
        The var is also DECLARED as a dynamic_rnn input ("Statics") so
        program pruning (io.get_inference_program) keeps its producer —
        an undeclared capture would be dead-code-eliminated."""
        self._assert_in_rnn("static_input")
        if x not in self.static_outer:
            self.static_outer.append(x)
        return x

    def update_memory(self, ex_mem: VarDesc, new_mem: VarDesc):
        self._assert_in_rnn("update_memory")
        self.mem_updates[ex_mem.name] = new_mem.name

    def output(self, *outputs: VarDesc):
        self._assert_in_rnn("output")
        for o in outputs:
            self.output_inner.append(o)

    def __call__(self):
        if self.status != DynamicRNN.AFTER_RNN:
            raise RuntimeError("rnn() must be called after the with-block")
        if len(self.outputs_outer) == 1:
            return self.outputs_outer[0]
        return self.outputs_outer

    # -- finalize -----------------------------------------------------------
    def _append_rnn_op(self):
        block = self.parent_block
        outs = []
        T = self.step_outer[0].shape[1] if self.step_outer else -1
        for inner in self.output_inner:
            out = block.create_var(unique_name("dynamic_rnn_out"),
                                   shape=(inner.shape[0], T) + tuple(inner.shape[1:]),
                                   dtype=inner.dtype)
            _mark_seq(out, self.seq_len_name)
            outs.append(out)
        self.outputs_outer = outs
        final_mems = [block.create_var(unique_name("dynamic_rnn_final_mem"),
                                       shape=m.shape, dtype=m.dtype)
                      for m in self.memories]
        inputs = {"X": [v.name for v in self.step_outer],
                  "SeqLen": [self.seq_len_name],
                  "InitMems": [v.name for v in self.mem_init_vars
                               if v is not None]}
        if self.static_outer:
            inputs["Statics"] = [v.name for v in self.static_outer]
        block.append_op(
            "dynamic_rnn", inputs,
            {"Out": [o.name for o in outs],
             "FinalMems": [m.name for m in final_mems]},
            {"sub_block": self.sub_block.idx,
             "step_input_vars": [v.name for v in self.step_inner],
             "memory_vars": [m.name for m in self.memories],
             "memory_updates": dict(self.mem_updates),
             "memory_init_values": list(self.mem_init_values),
             "memory_shapes": list(self.mem_shapes),
             "memory_dtypes": list(self.mem_dtypes),
             "memory_has_init": [v is not None for v in self.mem_init_vars],
             "output_vars": [o.name for o in self.output_inner]})

    def _assert_in_rnn(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise RuntimeError(f"{method} must be called inside rnn.block()")


class StaticRNN:
    """≙ control_flow.py:383 StaticRNN — fixed-length recurrence over a
    known time dimension; same scan machinery with a full-length mask."""

    def __init__(self, name=None):
        self._drnn = DynamicRNN(name=name)
        self._seq_len_added = False

    def step(self):
        return self._drnn.block()

    def step_input(self, x: VarDesc) -> VarDesc:
        if not getattr(x, "seq_len_var", None):
            # synthesize a full-length companion for dense [B, T, ...] input
            from . import tensor as tensor_layers
            block = self._drnn.parent_block
            name = x.name + "@SEQ_LEN"
            if name not in block.vars:
                with self._drnn.main_program.block_guard(
                        self._drnn.parent_block):
                    ln = tensor_layers.fill_constant_batch_size_like(
                        x, [-1], "int32", float(x.shape[1]))
                    ln.stop_gradient = True
                old_name = ln.name
                block.vars[name] = block.vars.pop(old_name)
                block.vars[name].name = name
                # fix the op output reference
                for op in self._drnn.parent_block.ops:
                    for slot, names in op.outputs.items():
                        op.outputs[slot] = [name if n == old_name else n
                                            for n in names]
            x.seq_len_var = name
            x.lod_level = 1
        return self._drnn.step_input(x)

    def memory(self, init=None, shape=None, init_value=0.0,
               dtype="float32", **kw):
        return self._drnn.memory(init=init, shape=shape, value=init_value,
                                 dtype=dtype)

    def static_input(self, x):
        return self._drnn.static_input(x)

    def update_memory(self, mem, new):
        return self._drnn.update_memory(mem, new)

    def output(self, *outputs):
        return self._drnn.output(*outputs)

    def __call__(self):
        return self._drnn()


class Pipeline:
    """GPipe pipeline parallelism over homogeneous stages (additive
    capability — SURVEY §2.4 notes the reference has none; designed
    TPU-first, parallel/pipeline.py has the schedule).

        pipe = layers.Pipeline(num_stages=4, num_microbatches=8)
        with pipe.stage():
            x = pipe.stage_input(h)                      # [mb, D]
            w = pipe.stage_param([D, D])                 # THIS stage's slice
            b = pipe.stage_param([D], is_bias=True)
            y = layers.tanh(layers.elementwise_add(layers.matmul(x, w), b))
            pipe.output(y)
        h = pipe()                                       # [B, D]

    Parameters are stored STACKED [num_stages, ...] and annotated sharded
    over 'pp', so each stage's slice lives on its own devices; without a
    pp mesh axis the op runs the numerically identical sequential scan.
    """

    def __init__(self, num_stages: int, num_microbatches: int, name=None):
        self.num_stages = num_stages
        self.num_microbatches = num_microbatches
        self.helper = LayerHelper(name or "pipeline")
        self.main_program = default_main_program()
        parent_idx = self.main_program.current_block().idx
        self.sub_block = self.main_program.create_block(parent_idx)
        self._x_outer = None
        self._x_inner = None
        self._out_inner = None
        self._stacked = []      # outer stacked param vars
        self._inner = []        # inner per-stage slice names

    def stage(self):
        return self.main_program.block_guard(self.sub_block)

    def stage_input(self, x: VarDesc) -> VarDesc:
        self._x_outer = x
        inner = self.sub_block.create_var(
            unique_name("pipe_x"), shape=tuple(x.shape), dtype=x.dtype)
        self._x_inner = inner
        return inner

    def stage_param(self, shape, dtype="float32", is_bias=False,
                    param_attr=None) -> VarDesc:
        """Create a stacked [num_stages]+shape parameter sharded over 'pp'
        and return the INNER per-stage slice var the stage code uses."""
        import numpy as np
        from ..initializer import XavierInitializer
        from ..param_attr import ParamAttr
        attr = ParamAttr.to_attr(param_attr)
        # default init must use the PER-STAGE fan, not the stacked 3-D
        # shape (which Xavier would read as a conv kernel)
        default = None
        if not is_bias:
            if len(shape) >= 2:
                fi, fo = int(np.prod(shape[:-1])), int(shape[-1])
            else:
                fi = fo = int(shape[0])
            default = XavierInitializer(fan_in=fi, fan_out=fo)
        from ..parallel.mesh import PP
        stacked = self.helper.create_parameter(
            attr, [self.num_stages] + list(shape), dtype, is_bias=is_bias,
            default_initializer=default)
        stacked.sharding = (PP,) + (None,) * len(shape)
        inner = self.sub_block.create_var(
            unique_name("pipe_p"), shape=tuple(shape), dtype=dtype)
        self._stacked.append(stacked)
        self._inner.append(inner.name)
        return inner

    def output(self, var: VarDesc):
        in_shape = tuple(self._x_inner.shape) if self._x_inner is not None \
            else None
        if in_shape is not None and tuple(var.shape) != in_shape:
            raise ValueError(
                f"Pipeline stages must be homogeneous: stage output shape "
                f"{tuple(var.shape)} != stage input shape {in_shape} (the "
                "same stage function runs on every pp rank)")
        self._out_inner = var.name

    def __call__(self) -> VarDesc:
        if self._x_inner is None or self._out_inner is None:
            raise RuntimeError("Pipeline needs stage_input() and output()")
        parent = self.main_program.block(self.sub_block.parent_idx)
        out = parent.create_var(unique_name("pipeline_out"),
                                shape=tuple(self._x_outer.shape),
                                dtype=self._x_outer.dtype)
        parent.append_op(
            "pipeline",
            {"X": self._x_outer, "Params": self._stacked},
            {"Out": out},
            {"sub_block": self.sub_block.idx,
             "x_var": self._x_inner.name,
             "param_vars": list(self._inner),
             "out_var": self._out_inner,
             "n_microbatches": self.num_microbatches,
             "num_stages": self.num_stages})
        return out


def array_length(array):
    """lod_array_length_op.cc: the array's (static) capacity — dense
    tensor arrays are fixed [max_len, ...] buffers; see ops/flow_ops.py
    array_length for the design note."""
    helper = LayerHelper("array_length")
    out = helper.create_tmp_variable("int32")
    out.stop_gradient = True
    helper.append_op("array_length", {"X": array}, {"Out": out}, {})
    out.shape = ()
    return out


def max_sequence_len(x):
    """max_sequence_len_op.cc re-read for the padded+lengths design: the
    longest sequence length in a ragged batch (reduce_max over the
    @SEQ_LEN companion)."""
    from .sequence import _seq_len_of
    from . import nn
    helper = LayerHelper("max_sequence_len")
    seq_len = helper.main_program.current_block().var(_seq_len_of(x, helper))
    return nn.reduce_max(seq_len)


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """≙ layers.Print (print_op.cc): print the tensor at every execution
    — lowered to jax.debug.print, which fires even under jit. Returns the
    input (the op is an identity in the dataflow)."""
    helper = LayerHelper("print")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("print", {"In": input}, {"Out": out},
                     {"message": message or ""})
    out.shape, out.dtype = input.shape, input.dtype
    return out
