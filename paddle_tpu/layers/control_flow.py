"""Control-flow layers: DynamicRNN, StaticRNN.

≙ reference python/paddle/fluid/layers/control_flow.py (DynamicRNN:1313,
StaticRNN:383). The reference interprets sub-blocks per timestep through
recurrent_op's StepScopes (recurrent_op.cc:53-222); here the sub-block is
*traced* once into a lax.scan body (ops/rnn_ops.py dynamic_rnn) — compiled,
fused, differentiable through scan's native VJP.
"""

from __future__ import annotations

from typing import List, Optional

from ..core.program import VarDesc, default_main_program, unique_name
from ..layer_helper import LayerHelper
from .sequence import _mark_seq

__all__ = ["DynamicRNN", "StaticRNN"]


class DynamicRNN:
    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.main_program = default_main_program()
        self.status = DynamicRNN.BEFORE_RNN
        parent_idx = self.main_program._block_stack[-1]
        self.sub_block = self.main_program.create_block(parent_idx)
        self.parent_block = self.main_program.block(parent_idx)
        self.step_outer: List[VarDesc] = []
        self.step_inner: List[VarDesc] = []
        self.memories: List[VarDesc] = []
        self.mem_init_vars: List[Optional[VarDesc]] = []
        self.mem_init_values: List[float] = []
        self.mem_shapes: List[list] = []
        self.mem_dtypes: List[str] = []
        self.mem_updates = {}
        self.output_inner: List[VarDesc] = []
        self.outputs_outer: List[VarDesc] = []
        self.seq_len_name: Optional[str] = None

    # -- context ------------------------------------------------------------
    class _BlockCtx:
        def __init__(self, rnn):
            self.rnn = rnn

        def __enter__(self):
            rnn = self.rnn
            rnn.status = DynamicRNN.IN_RNN
            rnn._guard = rnn.main_program.block_guard(rnn.sub_block)
            rnn._guard.__enter__()
            return rnn

        def __exit__(self, exc_type, *exc):
            rnn = self.rnn
            rnn._guard.__exit__(exc_type, *exc)
            rnn.status = DynamicRNN.AFTER_RNN
            if exc_type is None:
                rnn._append_rnn_op()
            return False

    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise RuntimeError("rnn.block() can only be entered once")
        return DynamicRNN._BlockCtx(self)

    # -- builder API (mirrors control_flow.py DynamicRNN) -------------------
    def step_input(self, x: VarDesc) -> VarDesc:
        self._assert_in_rnn("step_input")
        if not getattr(x, "seq_len_var", None):
            raise ValueError(f"step_input {x.name} must be a sequence var")
        if self.seq_len_name is None:
            self.seq_len_name = x.seq_len_var
        inner = self.sub_block.create_var(
            unique_name("dynamic_rnn_step_in"),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self.step_outer.append(x)
        self.step_inner.append(inner)
        return inner

    def memory(self, init: Optional[VarDesc] = None, shape=None,
               value: float = 0.0, need_reorder: bool = False,
               dtype: str = "float32") -> VarDesc:
        self._assert_in_rnn("memory")
        if init is not None:
            inner = self.sub_block.create_var(
                unique_name("dynamic_rnn_mem"), shape=init.shape,
                dtype=init.dtype)
            self.mem_init_vars.append(init)
            self.mem_shapes.append(list(init.shape))
            self.mem_init_values.append(0.0)
            self.mem_dtypes.append(str(init.dtype))
        else:
            assert shape is not None
            inner = self.sub_block.create_var(
                unique_name("dynamic_rnn_mem"), shape=(-1,) + tuple(shape),
                dtype=dtype)
            self.mem_init_vars.append(None)
            self.mem_shapes.append(list(shape))
            self.mem_init_values.append(float(value))
            self.mem_dtypes.append(str(dtype))
        self.memories.append(inner)
        return inner

    def static_input(self, x: VarDesc) -> VarDesc:
        """≙ DynamicRNN.static_input (control_flow.py:1313 area). The
        reference copies/reorders a parent-scope LoDTensor into each step
        scope; here sub-block ops read outer vars directly from the
        enclosing trace environment (ops/rnn_ops.py dynamic_rnn `outer_env`),
        so the full [B, T, ...] tensor is visible at every step as-is."""
        self._assert_in_rnn("static_input")
        return x

    def update_memory(self, ex_mem: VarDesc, new_mem: VarDesc):
        self._assert_in_rnn("update_memory")
        self.mem_updates[ex_mem.name] = new_mem.name

    def output(self, *outputs: VarDesc):
        self._assert_in_rnn("output")
        for o in outputs:
            self.output_inner.append(o)

    def __call__(self):
        if self.status != DynamicRNN.AFTER_RNN:
            raise RuntimeError("rnn() must be called after the with-block")
        if len(self.outputs_outer) == 1:
            return self.outputs_outer[0]
        return self.outputs_outer

    # -- finalize -----------------------------------------------------------
    def _append_rnn_op(self):
        block = self.parent_block
        outs = []
        T = self.step_outer[0].shape[1] if self.step_outer else -1
        for inner in self.output_inner:
            out = block.create_var(unique_name("dynamic_rnn_out"),
                                   shape=(inner.shape[0], T) + tuple(inner.shape[1:]),
                                   dtype=inner.dtype)
            _mark_seq(out, self.seq_len_name)
            outs.append(out)
        self.outputs_outer = outs
        final_mems = [block.create_var(unique_name("dynamic_rnn_final_mem"),
                                       shape=m.shape, dtype=m.dtype)
                      for m in self.memories]
        inputs = {"X": [v.name for v in self.step_outer],
                  "SeqLen": [self.seq_len_name],
                  "InitMems": [v.name for v in self.mem_init_vars
                               if v is not None]}
        block.append_op(
            "dynamic_rnn", inputs,
            {"Out": [o.name for o in outs],
             "FinalMems": [m.name for m in final_mems]},
            {"sub_block": self.sub_block.idx,
             "step_input_vars": [v.name for v in self.step_inner],
             "memory_vars": [m.name for m in self.memories],
             "memory_updates": dict(self.mem_updates),
             "memory_init_values": list(self.mem_init_values),
             "memory_shapes": list(self.mem_shapes),
             "memory_dtypes": list(self.mem_dtypes),
             "memory_has_init": [v is not None for v in self.mem_init_vars],
             "output_vars": [o.name for o in self.output_inner]})

    def _assert_in_rnn(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise RuntimeError(f"{method} must be called inside rnn.block()")


class StaticRNN:
    """≙ control_flow.py:383 StaticRNN — fixed-length recurrence over a
    known time dimension; same scan machinery with a full-length mask."""

    def __init__(self, name=None):
        self._drnn = DynamicRNN(name=name)
        self._seq_len_added = False

    def step(self):
        return self._drnn.block()

    def step_input(self, x: VarDesc) -> VarDesc:
        if not getattr(x, "seq_len_var", None):
            # synthesize a full-length companion for dense [B, T, ...] input
            from . import tensor as tensor_layers
            block = self._drnn.parent_block
            name = x.name + "@SEQ_LEN"
            if name not in block.vars:
                with self._drnn.main_program.block_guard(
                        self._drnn.parent_block):
                    ln = tensor_layers.fill_constant_batch_size_like(
                        x, [-1], "int32", float(x.shape[1]))
                    ln.stop_gradient = True
                old_name = ln.name
                block.vars[name] = block.vars.pop(old_name)
                block.vars[name].name = name
                # fix the op output reference
                for op in self._drnn.parent_block.ops:
                    for slot, names in op.outputs.items():
                        op.outputs[slot] = [name if n == old_name else n
                                            for n in names]
            x.seq_len_var = name
            x.lod_level = 1
        return self._drnn.step_input(x)

    def memory(self, init=None, shape=None, init_value=0.0,
               dtype="float32", **kw):
        return self._drnn.memory(init=init, shape=shape, value=init_value,
                                 dtype=dtype)

    def static_input(self, x):
        return self._drnn.static_input(x)

    def update_memory(self, mem, new):
        return self._drnn.update_memory(mem, new)

    def output(self, *outputs):
        return self._drnn.output(*outputs)

    def __call__(self):
        return self._drnn()
