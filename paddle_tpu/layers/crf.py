"""CRF / CTC layers (≙ layers/nn.py linear_chain_crf, crf_decoding,
ctc_greedy_decoder, chunk_eval around nn.py:~900-1100 in the reference)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from .sequence import _seq_len_of, _mark_seq

__all__ = ["linear_chain_crf", "crf_decoding", "ctc_greedy_decoder",
           "chunk_eval"]


def linear_chain_crf(input, label, param_attr=None):
    """≙ nn.py linear_chain_crf: creates the [N+2, N] transition parameter
    (row 0 start, row 1 end, rest N x N) and emits the CRF NLL [B, 1]."""
    helper = LayerHelper("linear_chain_crf", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(helper.param_attr,
                                         [size + 2, size], input.dtype)
    alpha = helper.create_tmp_variable(input.dtype)
    emission_exps = helper.create_tmp_variable(input.dtype)
    transition_exps = helper.create_tmp_variable(input.dtype)
    log_likelihood = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        "linear_chain_crf",
        {"Emission": input, "Transition": transition, "Label": label,
         "SeqLen": _seq_len_of(input, helper)},
        {"LogLikelihood": log_likelihood, "Alpha": alpha,
         "EmissionExps": emission_exps, "TransitionExps": transition_exps})
    log_likelihood.shape = (input.shape[0], 1)
    log_likelihood.dtype = input.dtype
    return log_likelihood


def crf_decoding(input, param_attr=None, label=None):
    """≙ nn.py crf_decoding: Viterbi path (or 0/1 correctness marks when
    `label` is given). Reuses the transition parameter by ParamAttr name."""
    helper = LayerHelper("crf_decoding", param_attr=param_attr)
    size = input.shape[-1]
    transition = helper.create_parameter(helper.param_attr,
                                         [size + 2, size], input.dtype)
    path = helper.create_tmp_variable("int64")
    path.stop_gradient = True
    inputs = {"Emission": input, "Transition": transition,
              "SeqLen": _seq_len_of(input, helper)}
    if label is not None:
        inputs["Label"] = label
    helper.append_op("crf_decoding", inputs, {"ViterbiPath": path})
    path.shape = tuple(input.shape[:2])
    return _mark_seq(path, input.seq_len_var)


def ctc_greedy_decoder(input, blank, padding_value=0, name=None):
    """≙ nn.py ctc_greedy_decoder: argmax over classes then ctc_align
    (merge repeats, drop blanks)."""
    from . import nn as nn_layers
    helper = LayerHelper("ctc_align", name=name)
    _, top_idx = nn_layers.topk(input, 1)
    pred = nn_layers.squeeze(top_idx, [2])
    out = helper.create_tmp_variable(pred.dtype)
    out_len = helper.create_tmp_variable("int32")
    out.stop_gradient = out_len.stop_gradient = True
    helper.append_op("ctc_align",
                     {"Input": pred, "SeqLen": _seq_len_of(input, helper)},
                     {"Output": out, "OutLen": out_len},
                     {"blank": blank, "padding_value": padding_value})
    out.shape = tuple(input.shape[:2])
    out_len.shape = (input.shape[0],)
    out_len.persistable = False
    _mark_seq(out, out_len.name)
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """≙ nn.py chunk_eval: chunk-level P/R/F1 + raw counts."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_tmp_variable("float32")
    recall = helper.create_tmp_variable("float32")
    f1 = helper.create_tmp_variable("float32")
    num_infer = helper.create_tmp_variable("int64")
    num_label = helper.create_tmp_variable("int64")
    num_correct = helper.create_tmp_variable("int64")
    for v in (precision, recall, f1, num_infer, num_label, num_correct):
        v.stop_gradient = True
    helper.append_op(
        "chunk_eval",
        {"Inference": input, "Label": label,
         "SeqLen": _seq_len_of(input, helper)},
        {"Precision": precision, "Recall": recall, "F1-Score": f1,
         "NumInferChunks": num_infer, "NumLabelChunks": num_label,
         "NumCorrectChunks": num_correct},
        {"num_chunk_types": num_chunk_types, "chunk_scheme": chunk_scheme,
         "excluded_chunk_types": excluded_chunk_types or []})
    return precision, recall, f1, num_infer, num_label, num_correct
