"""Detection layers (≙ python/paddle/fluid/layers/detection.py, 911 LoC).

Dense-shape conventions (vs the reference's LoD outputs) are documented on
each op in ops/detection_ops.py; ground-truth tensors are padded [B, G, …]
with all-zero box rows as padding.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..core.program import VarDesc
from ..layer_helper import LayerHelper

__all__ = ["prior_box", "anchor_generator", "box_coder", "box_clip",
           "bipartite_match", "target_assign", "mine_hard_examples",
           "multiclass_nms", "detection_output", "ssd_loss", "roi_pool",
           "roi_align", "iou_similarity", "polygon_box_transform",
           "detection_map", "multi_box_head"]


def iou_similarity(x, y, name=None):
    """layers/detection.py iou_similarity wrapper (op in ops/math_ops)."""
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("iou_similarity", {"X": x, "Y": y}, {"Out": out}, {})
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """layers/detection.py:prior_box. Returns (boxes, variances),
    each [H, W, n_priors, 4]."""
    helper = LayerHelper("prior_box", name=name)
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError(
            f"prior_box: max_sizes ({len(max_sizes)}) must pair 1:1 with "
            f"min_sizes ({len(min_sizes)})")
    boxes = helper.create_tmp_variable("float32")
    var = helper.create_tmp_variable("float32")
    helper.append_op(
        "prior_box", {"Input": input, "Image": image},
        {"Boxes": boxes, "Variances": var},
        {"min_sizes": list(min_sizes), "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios), "variances": list(variance),
         "flip": flip, "clip": clip, "step_w": steps[0], "step_h": steps[1],
         "offset": offset})
    return boxes, var


def anchor_generator(input, anchor_sizes, aspect_ratios, stride,
                     variance=(0.1, 0.1, 0.2, 0.2), offset=0.5, name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_tmp_variable("float32")
    var = helper.create_tmp_variable("float32")
    helper.append_op(
        "anchor_generator", {"Input": input},
        {"Anchors": anchors, "Variances": var},
        {"anchor_sizes": list(anchor_sizes),
         "aspect_ratios": list(aspect_ratios), "stride": list(stride),
         "variances": list(variance), "offset": offset})
    return anchors, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_tmp_variable(target_box.dtype)
    ins = {"PriorBox": prior_box, "TargetBox": target_box}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = prior_box_var
    helper.append_op("box_coder", ins, {"OutputBox": out},
                     {"code_type": code_type,
                      "box_normalized": box_normalized})
    return out


def box_clip(input, im_info, name=None):
    helper = LayerHelper("box_clip", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("box_clip", {"Input": input, "ImInfo": im_info},
                     {"Output": out}, {})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_tmp_variable("int32")
    dist = helper.create_tmp_variable(dist_matrix.dtype)
    helper.append_op("bipartite_match", {"DistMat": dist_matrix},
                     {"ColToRowMatchIndices": idx,
                      "ColToRowMatchDist": dist},
                     {"match_type": match_type,
                      "dist_threshold": dist_threshold})
    return idx, dist


def target_assign(input, matched_indices, mismatch_value=0, name=None):
    helper = LayerHelper("target_assign", name=name)
    out = helper.create_tmp_variable(input.dtype)
    weight = helper.create_tmp_variable("float32")
    helper.append_op("target_assign",
                     {"X": input, "MatchIndices": matched_indices},
                     {"Out": out, "OutWeight": weight},
                     {"mismatch_value": mismatch_value})
    return out, weight


def mine_hard_examples(cls_loss, match_indices, neg_pos_ratio=3.0,
                       name=None):
    helper = LayerHelper("mine_hard_examples", name=name)
    mask = helper.create_tmp_variable("float32")
    upd = helper.create_tmp_variable("int32")
    helper.append_op("mine_hard_examples",
                     {"ClsLoss": cls_loss, "MatchIndices": match_indices},
                     {"NegMask": mask, "UpdatedMatchIndices": upd},
                     {"neg_pos_ratio": neg_pos_ratio})
    return mask, upd


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=64,
                   keep_top_k=16, nms_threshold=0.3, background_label=0,
                   name=None):
    """Out [B, keep_top_k, 6] = (label, score, x0, y0, x1, y1); label -1
    marks padding rows (dense stand-in for the reference's LoD result)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_tmp_variable(bboxes.dtype)
    helper.append_op("multiclass_nms", {"BBoxes": bboxes, "Scores": scores},
                     {"Out": out},
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=64,
                     keep_top_k=16, score_threshold=0.01, name=None):
    """layers/detection.py detection_output: decode + NMS.
    loc [B,M,4] offsets, scores [B,M,C] (post-softmax)."""
    from . import nn as L
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = L.transpose(scores, perm=[0, 2, 1])       # [B,C,M]
    return multiclass_nms(decoded, scores_t,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label, name=name)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, loc_loss_weight=1.0, conf_loss_weight=1.0,
             name=None):
    """layers/detection.py:ssd_loss — one fused op here (the reference
    composes ~10 ops; ops/detection_ops.py ssd_loss documents the math).
    Returns per-image loss [B, 1]."""
    helper = LayerHelper("ssd_loss", name=name)
    loss = helper.create_tmp_variable("float32")
    ins = {"Location": location, "Confidence": confidence,
           "GtBox": gt_box, "GtLabel": gt_label, "PriorBox": prior_box}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = prior_box_var
    helper.append_op("ssd_loss", ins, {"Loss": loss},
                     {"background_label": background_label,
                      "overlap_threshold": overlap_threshold,
                      "neg_pos_ratio": neg_pos_ratio,
                      "loc_loss_weight": loc_loss_weight,
                      "conf_loss_weight": conf_loss_weight})
    return loss


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, name=None):
    """rois: dense [R, 5] = (batch_idx, x0, y0, x1, y1)."""
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("roi_pool", {"X": input, "ROIs": rois}, {"Out": out},
                     {"pooled_height": pooled_height,
                      "pooled_width": pooled_width,
                      "spatial_scale": spatial_scale})
    return out


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, name=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("roi_align", {"X": input, "ROIs": rois}, {"Out": out},
                     {"pooled_height": pooled_height,
                      "pooled_width": pooled_width,
                      "spatial_scale": spatial_scale})
    return out


def polygon_box_transform(input, name=None):
    """≙ layers/detection.py polygon_box_transform: decode EAST geometry
    maps [N, geo_ch, H, W] into absolute vertex coordinates."""
    helper = LayerHelper("polygon_box_transform", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("polygon_box_transform", {"Input": input},
                     {"Output": out}, {})
    return out


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral", name=None):
    """≙ layers/detection.py detection_map (detection_map_op.cc). Dense
    inputs: detect_res [B, D, 6] (label, score, x0, y0, x1, y1; label -1
    pads — multiclass_nms output format), label [B, G, 6] (label,
    is_difficult, x0, y0, x1, y1; label -1 pads) or [B, G, 5] without the
    difficult column. Returns the batch mAP scalar [1]; streaming
    accumulation across batches lives in metrics.DetectionMAP."""
    helper = LayerHelper("detection_map", name=name)
    out = helper.create_tmp_variable("float32")
    helper.append_op("detection_map",
                     {"DetectRes": detect_res, "Label": label},
                     {"MAP": out},
                     {"class_num": class_num,
                      "background_label": background_label,
                      "overlap_threshold": overlap_threshold,
                      "evaluate_difficult": evaluate_difficult,
                      "ap_type": ap_version})
    return out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None):
    """≙ layers/detection.py multi_box_head: the SSD prediction head.
    Per feature map: prior boxes + a loc conv ([N, HWP, 4]) + a conf conv
    ([N, HWP, C]); results concatenate across maps. min/max sizes derive
    from min_ratio/max_ratio when not given (>2 maps, SSD paper §2.2)."""
    import math
    from . import nn

    num_layer = len(inputs)
    if num_layer <= 2:
        assert min_sizes is not None and max_sizes is not None
    elif min_sizes is None and max_sizes is None:
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes
    if steps:
        step_w = step_h = steps

    mbox_locs, mbox_confs, box_results, var_results = [], [], [], []
    for i, inp in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i]
        if not isinstance(min_size, (list, tuple)):
            min_size = [min_size]
        if not isinstance(max_size, (list, tuple)):
            max_size = [max_size]
        ar = aspect_ratios[i] if aspect_ratios is not None else []
        if not isinstance(ar, (list, tuple)):
            ar = [ar]
        box, var = prior_box(
            inp, image, min_size, max_size, ar, list(variance), flip, clip,
            steps=(step_w[i] if step_w else 0.0,
                   step_h[i] if step_h else 0.0), offset=offset)
        box_results.append(box)
        var_results.append(var)
        num_boxes = box.shape[2]

        loc = nn.conv2d(inp, num_filters=num_boxes * 4,
                        filter_size=kernel_size, padding=pad, stride=stride)
        loc = nn.transpose(loc, perm=[0, 2, 3, 1])
        mbox_locs.append(nn.reshape(
            loc, [-1, (loc.shape[1] * loc.shape[2] * loc.shape[3]) // 4, 4]))

        conf = nn.conv2d(inp, num_filters=num_boxes * num_classes,
                         filter_size=kernel_size, padding=pad, stride=stride)
        conf = nn.transpose(conf, perm=[0, 2, 3, 1])
        mbox_confs.append(nn.reshape(
            conf, [-1, (conf.shape[1] * conf.shape[2] * conf.shape[3])
                   // num_classes, num_classes]))

    if num_layer == 1:
        return mbox_locs[0], mbox_confs[0], box_results[0], var_results[0]
    boxes = nn.concat([nn.reshape(b, [-1, 4]) for b in box_results], axis=0)
    vars_ = nn.concat([nn.reshape(v, [-1, 4]) for v in var_results], axis=0)
    locs = nn.concat(mbox_locs, axis=1)
    confs = nn.concat(mbox_confs, axis=1)
    return locs, confs, boxes, vars_
