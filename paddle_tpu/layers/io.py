"""IO layers: the `data` feed declaration (+ reader plumbing lives in
paddle_tpu/data/, host-side by design).

≙ reference python/paddle/fluid/layers/io.py:31 `data`. The reader-op stack
(open_files/double_buffer, layers/io.py:295-574) is host-side Python here
(data/pipeline.py): on a functional runtime the device-side reader variables
serve no purpose — prefetch overlap comes from jax's async dispatch +
double-buffered host staging.
"""

from __future__ import annotations

from ..core.program import default_main_program

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    if lod_level >= 1:
        # padded ragged representation: insert the time dim and declare the
        # @SEQ_LEN companion (SURVEY.md §5 — LoD becomes dense + lengths)
        shape = shape[:1] + [-1] + shape[1:]
    block = default_main_program().global_block
    var = block.create_var(name, shape=shape, dtype=dtype, lod_level=lod_level)
    var.stop_gradient = stop_gradient
    var.is_data = True
    if lod_level >= 1:
        seq_len = block.create_var(name + "@SEQ_LEN", shape=(-1,),
                                   dtype="int32", lod_level=0)
        seq_len.stop_gradient = True
        seq_len.is_data = True
        var.seq_len_var = seq_len.name
    return var
