"""In-graph learning-rate schedules.

≙ reference python/paddle/fluid/layers/learning_rate_scheduler.py:
exponential_decay, natural_exp_decay, inverse_time_decay, polynomial_decay,
piecewise_decay, noam_decay. Each builds ops that compute the LR tensor from
a persistable global step counter — the schedule is part of the program,
compiled into the same XLA executable as the update.
"""

from __future__ import annotations

import math

from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer
from . import tensor, nn, ops

__all__ = ["exponential_decay", "natural_exp_decay", "inverse_time_decay",
           "polynomial_decay", "piecewise_decay", "noam_decay",
           "global_step_counter", "autoincreased_step_counter"]

_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def global_step_counter():
    """Persistable float32 step counter incremented once per program run."""
    helper = LayerHelper("global_step_counter")
    block = helper.main_program.global_block
    if _COUNTER_NAME in block.vars:
        return block.vars[_COUNTER_NAME]
    counter = helper.create_global_variable(
        name=_COUNTER_NAME, dtype="float32", shape=(1,), persistable=True)
    counter.stop_gradient = True
    helper.set_variable_initializer(counter, ConstantInitializer(0.0))
    helper.append_op("increment", {"X": counter}, {"Out": counter}, {"step": 1.0})
    return counter


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = global_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return _scale_pow(learning_rate, decay_rate, div)


def _scale_pow(lr, rate, exponent):
    """lr * rate^exponent via exp(log(rate)*exponent) (rate is a python float)."""
    scaled = exponent * math.log(rate)
    return ops.exp(scaled) * lr


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = global_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return ops.exp(div * (-decay_rate)) * learning_rate


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = global_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return (div * decay_rate + 1.0).__rtruediv__(1.0) * learning_rate


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = global_step_counter()
    if cycle:
        div = ops.ceil(step / float(decay_steps))
        # avoid zero divisor on step 0: max(div, 1)
        one = tensor.fill_constant([1], "float32", 1.0)
        div = nn.elementwise_max(div, one)
        decay_steps_var = div * float(decay_steps)
        frac = step / decay_steps_var
    else:
        cap = tensor.fill_constant([1], "float32", float(decay_steps))
        capped = nn.elementwise_min(step, cap)
        frac = capped * (1.0 / float(decay_steps))
    base = (1.0 - frac) if True else frac
    return base ** float(power) * (learning_rate - end_learning_rate) + end_learning_rate


def piecewise_decay(boundaries, values):
    """Piecewise-constant LR: smooth formulation with comparisons summed —
    in-graph, branch-free (TPU-friendly; the reference builds a switch)."""
    assert len(values) == len(boundaries) + 1
    step = global_step_counter()
    lr = None
    prev = None
    for i, v in enumerate(values):
        if i == 0:
            indicator = _step_less_than(step, boundaries[0])
        elif i < len(values) - 1:
            indicator = _step_in_range(step, boundaries[i - 1], boundaries[i])
        else:
            indicator = _step_ge(step, boundaries[-1])
        term = indicator * float(v)
        lr = term if lr is None else lr + term
    return lr


def _to_float(cond_var):
    return nn.cast(cond_var, "float32")


def _step_less_than(step, b):
    return _to_float(step < float(b))


def _step_ge(step, b):
    return _to_float(step >= float(b))


def _step_in_range(step, lo, hi):
    return _step_ge(step, lo) * _step_less_than(step, hi)


def noam_decay(d_model, warmup_steps):
    """Transformer LR (layers/learning_rate_scheduler.py noam_decay)."""
    step = global_step_counter()
    a = step ** -0.5
    b = step * (warmup_steps ** -1.5)
    lr = nn.elementwise_min(a, b)
    return lr * (d_model ** -0.5)


# ≙ layers.autoincreased_step_counter (the fluid name for the same op)
autoincreased_step_counter = global_step_counter
