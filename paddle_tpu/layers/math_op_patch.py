"""Operator overloading on VarDesc: `a + b`, `a * 2`, `a < b`, ...

≙ reference python/paddle/fluid/layers/math_op_patch.py `monkey_patch_variable`.
Scalars use `scale`; tensors use elementwise ops — same lowering choices.
"""

from __future__ import annotations

from ..core.program import VarDesc, default_main_program
from ..layer_helper import LayerHelper


def _create_op(op_type, x, y, axis=-1, reverse=False):
    helper = LayerHelper(op_type)
    out = helper.create_tmp_variable(x.dtype)
    a, b = (y, x) if reverse else (x, y)
    helper.append_op(op_type, {"X": a, "Y": b}, {"Out": out}, {"axis": axis})
    return out


def _scalar_op(x, scale, bias):
    helper = LayerHelper("scale")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("scale", {"X": x}, {"Out": out},
                     {"scale": float(scale), "bias": float(bias)})
    return out


def _to_var(x, ref):
    """Promote python scalars to a filled tensor when needed (rdiv etc.)."""
    from .tensor import fill_constant
    shape = list(ref.shape) if ref.shape else [1]
    shape = [1 if s == -1 else s for s in shape]
    return fill_constant(shape, ref.dtype, x)


def monkey_patch_variable():
    def binary(op_type):
        def impl(self, other):
            if isinstance(other, (int, float)):
                if op_type == "elementwise_add":
                    return _scalar_op(self, 1.0, other)
                if op_type == "elementwise_sub":
                    return _scalar_op(self, 1.0, -other)
                if op_type == "elementwise_mul":
                    return _scalar_op(self, other, 0.0)
                if op_type == "elementwise_div":
                    return _scalar_op(self, 1.0 / other, 0.0)
                other = _to_var(other, self)
            return _create_op(op_type, self, other)
        return impl

    def rbinary(op_type):
        def impl(self, other):
            if isinstance(other, (int, float)):
                if op_type == "elementwise_add":
                    return _scalar_op(self, 1.0, other)
                if op_type == "elementwise_mul":
                    return _scalar_op(self, other, 0.0)
                other = _to_var(other, self)
            return _create_op(op_type, self, other, reverse=True)
        return impl

    def compare(op_type):
        def impl(self, other):
            if isinstance(other, (int, float)):
                other = _to_var(other, self)
            helper = LayerHelper(op_type)
            out = helper.create_tmp_variable("bool")
            out.stop_gradient = True
            helper.append_op(op_type, {"X": self, "Y": other}, {"Out": out})
            return out
        return impl

    VarDesc.__add__ = binary("elementwise_add")
    VarDesc.__radd__ = rbinary("elementwise_add")
    VarDesc.__sub__ = binary("elementwise_sub")
    VarDesc.__rsub__ = rbinary("elementwise_sub")
    VarDesc.__mul__ = binary("elementwise_mul")
    VarDesc.__rmul__ = rbinary("elementwise_mul")
    VarDesc.__truediv__ = binary("elementwise_div")
    VarDesc.__rtruediv__ = rbinary("elementwise_div")
    VarDesc.__pow__ = binary("elementwise_pow")
    VarDesc.__neg__ = lambda self: _scalar_op(self, -1.0, 0.0)
    VarDesc.__lt__ = compare("less_than")
    VarDesc.__le__ = compare("less_equal")
    VarDesc.__gt__ = compare("greater_than")
    VarDesc.__ge__ = compare("greater_equal")
    # NOTE: __eq__/__ne__ are NOT patched — VarDesc identity/hash must keep
    # working for dict keys (the reference makes the same choice).
