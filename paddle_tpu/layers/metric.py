"""In-graph metric layers (≙ python/paddle/fluid/layers/metric.py:
accuracy, auc)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from . import nn

__all__ = ["accuracy", "auc"]


def accuracy(input, label, k=1, correct=None, total=None):
    """accuracy_op: fraction of samples whose top-k predictions hit label."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_tmp_variable("float32")
    correct = correct or helper.create_tmp_variable("int32")
    total = total or helper.create_tmp_variable("int32")
    for v in (acc_out, correct, total):
        v.stop_gradient = True
    helper.append_op("accuracy",
                     {"Out": topk_out, "Indices": topk_indices, "Label": label},
                     {"Accuracy": acc_out, "Correct": correct, "Total": total})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1):
    """auc_op: streaming AUC approximated over a threshold grid. Stateless
    per-batch version (the reference accumulates in op state; here the
    Python metrics.Auc accumulator owns the streaming part)."""
    helper = LayerHelper("auc")
    out = helper.create_tmp_variable("float32")
    out.stop_gradient = True
    helper.append_op("auc", {"Predict": input, "Label": label}, {"AUC": out},
                     {"curve": curve, "num_thresholds": num_thresholds})
    return out
