"""Neural-network layer functions building program ops.

≙ reference python/paddle/fluid/layers/nn.py (4.3k LoC, 60+ layers: fc:45,
embedding:153, conv2d:1172, batch_norm:1551, layer_norm:1668, ...). Each
function appends ops to the default main program via LayerHelper and returns
the output VarDesc.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np

from ..core.program import VarDesc, default_main_program
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer, NormalInitializer

__all__ = [
    "fc", "embedding", "dropout", "cross_entropy", "square_error_cost",
    "conv2d", "conv2d_transpose", "pool2d", "batch_norm", "layer_norm",
    "fused_bottleneck",
    "softmax", "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
    "matmul", "topk", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "mean", "mul", "dot_product", "l2_normalize", "one_hot",
    "transpose", "reshape", "concat", "split", "stack", "unstack", "expand",
    "squeeze", "unsqueeze", "flatten", "pad", "im2sequence", "lrn", "prelu",
    "relu", "log", "crop", "elementwise_add", "elementwise_sub",
    "elementwise_mul", "elementwise_div", "elementwise_max", "elementwise_min",
    "elementwise_pow", "clip", "clip_by_norm", "scale", "cast", "gather",
    "scatter", "slice", "shape", "maxout", "smooth_l1", "warpctc",
    "label_smooth", "bilinear_interp", "resize_bilinear", "random_crop",
    "nce", "row_conv", "mean_iou", "bpr_loss", "spp", "moe_ffn",
    "conv3d", "pool3d", "cos_sim", "multiplex", "dice_loss", "image_resize",
    "image_resize_short", "gru_unit", "lstm_unit", "uniform_random",
    "uniform_random_batch_size_like", "gaussian_random",
    "gaussian_random_batch_size_like",
]


def _current_block():
    return default_main_program().current_block()


# ---------------------------------------------------------------------------
# Core layers
# ---------------------------------------------------------------------------

def fc(input, size: int, num_flatten_dims: int = 1, param_attr=None,
       bias_attr=None, act=None, is_test=False, name=None) -> VarDesc:
    """Fully connected (layers/nn.py:45): per-input mul + sum + bias + act."""
    helper = LayerHelper("fc", input=input, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = helper.input_dtype()
    mul_results = []
    seq_src = None
    flatten_used = num_flatten_dims
    inputs_list = helper.multiple_input()
    for in_idx, input_var in enumerate(inputs_list):
        input_shape = input_var.shape
        flatten = num_flatten_dims
        # per-timestep fc on padded sequences (the reference's [T_total, D]
        # row-major sequence fc becomes [B, T, D] with x_num_col_dims=2)
        if getattr(input_var, "seq_len_var", None) and len(input_shape) > 2 \
                and num_flatten_dims == 1:
            flatten = len(input_shape) - 1
            seq_src = input_var
        flatten_used = max(flatten_used, flatten)
        param_shape = [int(np.prod(input_shape[flatten:]))] + [size]
        pa = ParamAttr_to(param_attr)
        if pa.name is not None and len(inputs_list) > 1:
            pa.name = f"{pa.name}_{in_idx}"  # one weight per fc input
        w = helper.create_parameter(pa, param_shape, dtype)
        tmp = helper.create_tmp_variable(dtype)
        helper.append_op("mul", {"X": input_var, "Y": w}, {"Out": tmp},
                         {"x_num_col_dims": flatten, "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_tmp_variable(dtype)
        helper.append_op("sum", {"X": mul_results}, {"Out": pre_bias})
    if bias_attr is not False:
        # bias spans the feature (last) axis: alignment follows the flatten
        # point, not the (possibly unknown at build time) tmp-var shape
        pre_act = helper.append_bias_op(pre_bias, dim_start=flatten_used,
                                        size=[size])
    else:
        pre_act = pre_bias
    out = helper.append_activation(pre_act)
    if seq_src is not None:
        from .sequence import propagate_seq
        propagate_seq(seq_src, out)
    return out


def ParamAttr_to(attr):
    from ..param_attr import ParamAttr
    a = ParamAttr.to_attr(attr)
    # each fc input needs a fresh weight: clone to avoid name reuse
    from ..param_attr import ParamAttr as PA
    return PA(name=a.name, initializer=a.initializer,
              learning_rate=a.learning_rate, regularizer=a.regularizer,
              trainable=a.trainable, gradient_clip=a.gradient_clip)


def embedding(input, size: Sequence[int], is_sparse: bool = False,
              is_distributed: bool = False, padding_idx: Optional[int] = None,
              param_attr=None, dtype: str = "float32") -> VarDesc:
    """layers/nn.py:153.

    is_sparse=True → RowSparseGrad gradients (≙ SelectedRows,
    lookup_table_op.cc sparse path; see core/selected_rows.py).
    is_distributed=True → the table is annotated vocab-sharded over the
    ('tp','dp') mesh axes; under a sharded executor GSPMD partitions the
    gather across devices and each device stores only its vocab slice
    (≙ the distributed lookup table, distribute_transpiler.py:120-180,
    re-read as a sharding annotation instead of pserver prefetch RPCs —
    see docs/distributed_embedding.md for the sync-only decision)."""
    helper = LayerHelper("embedding", param_attr=param_attr)
    w = helper.create_parameter(helper.param_attr, size, dtype)
    if is_distributed:
        # vocab (dim 0) sharded over tp and/or dp — whichever axes the
        # runtime mesh actually has (spec_for drops absent axes)
        from ..parallel.mesh import DP, TP
        w.sharding = ((TP, DP), None)
    tmp = helper.create_tmp_variable(dtype)
    padding_idx = -1 if padding_idx is None else (
        padding_idx if padding_idx >= 0 else size[0] + padding_idx)
    helper.append_op("lookup_table", {"Ids": input, "W": w}, {"Out": tmp},
                     {"is_sparse": is_sparse, "is_distributed": is_distributed,
                      "padding_idx": padding_idx})
    if getattr(input, "seq_len_var", None):
        from .sequence import propagate_seq
        propagate_seq(input, tmp)
        tmp.shape = tuple(input.shape[:2]) + (size[1],)
        tmp.dtype = dtype
    return tmp


def dropout(x, dropout_prob: float, is_test: bool = False, seed=None,
            name=None) -> VarDesc:
    helper = LayerHelper("dropout", name=name)
    out = helper.create_tmp_variable(x.dtype)
    mask = helper.create_tmp_variable(x.dtype)
    mask.stop_gradient = True
    helper.append_op("dropout", {"X": x}, {"Out": out, "Mask": mask},
                     {"dropout_prob": dropout_prob, "is_test": is_test,
                      "seed": seed if seed is not None else 0})
    return out


def cross_entropy(input, label, soft_label: bool = False) -> VarDesc:
    helper = LayerHelper("cross_entropy")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("cross_entropy", {"X": input, "Label": label}, {"Y": out},
                     {"soft_label": soft_label})
    return out


def square_error_cost(input, label) -> VarDesc:
    helper = LayerHelper("square_error_cost")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("square_error_cost", {"X": input, "Y": label}, {"Out": out})
    return out


def conv2d(input, num_filters: int, filter_size, stride=1, padding=0,
           dilation=1, groups=None, param_attr=None, bias_attr=None,
           use_cudnn: bool = True, use_mkldnn: bool = False, act=None,
           name=None) -> VarDesc:
    """layers/nn.py:1172 (NCHW). use_cudnn/use_mkldnn accepted+ignored: XLA
    owns kernel selection on TPU."""
    helper = LayerHelper("conv2d", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    dtype = input.dtype
    num_channels = input.shape[1]
    groups = groups or 1
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)

    def _std(shape):
        fan_in = (num_channels // groups) * filter_size[0] * filter_size[1]
        return (2.0 / fan_in) ** 0.5

    w = helper.create_parameter(helper.param_attr, filter_shape, dtype,
                                default_initializer=NormalInitializer(0.0, _std(filter_shape)))
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op("conv2d", {"Input": input, "Filter": w}, {"Output": pre_bias},
                     {"strides": _pair(stride), "paddings": _pair(padding),
                      "dilations": _pair(dilation), "groups": groups,
                      "use_cudnn": use_cudnn})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2) \
        if bias_attr is not False else pre_bias
    return helper.append_activation(pre_act)


def conv2d_transpose(input, num_filters: int, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                     name=None) -> VarDesc:
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    in_channels = input.shape[1]
    groups = groups or 1
    if filter_size is None:
        raise ValueError("filter_size must be set (output_size inference TBD)")
    if isinstance(filter_size, int):
        filter_size = [filter_size, filter_size]
    filter_shape = [in_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    pre_bias = helper.create_tmp_variable(dtype)
    helper.append_op("conv2d_transpose", {"Input": input, "Filter": w},
                     {"Output": pre_bias},
                     {"strides": _pair(stride), "paddings": _pair(padding),
                      "dilations": _pair(dilation), "groups": groups})
    pre_act = helper.append_bias_op(pre_bias, 1, 2) if bias_attr is not False else pre_bias
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type: str = "max", pool_stride=1,
           pool_padding=0, global_pooling: bool = False, use_cudnn=True,
           ceil_mode: bool = False, name=None, exclusive=True) -> VarDesc:
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("pool2d", {"X": input}, {"Out": out},
                     {"pooling_type": pool_type, "ksize": _pair(pool_size),
                      "strides": _pair(pool_stride), "paddings": _pair(pool_padding),
                      "global_pooling": global_pooling, "ceil_mode": ceil_mode,
                      "exclusive": exclusive})
    return out


def _bn_state_vars(helper, pshape, dtype, param_attr, bias_attr,
                   moving_mean_name=None, moving_variance_name=None):
    """The ONE definition of batch-norm state creation (scale/bias params,
    persistable f32 running mean/var, saved-stat tmp vars) — shared by
    batch_norm and fused_bottleneck so their BN state policies can never
    diverge."""
    scale = helper.create_parameter(
        param_attr, pshape, dtype,
        default_initializer=ConstantInitializer(1.0))
    bias = helper.create_parameter(bias_attr, pshape, dtype, is_bias=True)
    mean = helper.create_global_variable(
        name=moving_mean_name, dtype="float32", shape=pshape,
        persistable=True)
    mean.stop_gradient = True
    helper.set_variable_initializer(mean, ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        name=moving_variance_name, dtype="float32", shape=pshape,
        persistable=True)
    variance.stop_gradient = True
    helper.set_variable_initializer(variance, ConstantInitializer(1.0))
    saved_mean = helper.create_tmp_variable("float32", stop_gradient=True)
    saved_var = helper.create_tmp_variable("float32", stop_gradient=True)
    return scale, bias, mean, variance, saved_mean, saved_var


def batch_norm(input, act=None, is_test: bool = False, momentum: float = 0.9,
               epsilon: float = 1e-5, param_attr=None, bias_attr=None,
               data_layout: str = "NCHW", in_place: bool = False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=False) -> VarDesc:
    """layers/nn.py:1551. Running mean/var are persistable state vars updated
    functionally each step (MeanOut/VarianceOut rebind the same names)."""
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    channels = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    pshape = [channels]
    scale, bias, mean, variance, saved_mean, saved_var = _bn_state_vars(
        helper, pshape, dtype, helper.param_attr, helper.bias_attr,
        moving_mean_name, moving_variance_name)
    out = helper.create_tmp_variable(dtype)
    # a relu activation folds into the op itself (≙ the reference op's
    # fuse_with_relu attr): the op's custom VJP then recomputes the mask in
    # backward instead of keeping a separate relu residual chain
    fuse_relu = act == "relu"
    helper.append_op("batch_norm",
                     {"X": input, "Scale": scale, "Bias": bias,
                      "Mean": mean, "Variance": variance},
                     {"Y": out, "MeanOut": mean, "VarianceOut": variance,
                      "SavedMean": saved_mean, "SavedVariance": saved_var},
                     {"momentum": momentum, "epsilon": epsilon,
                      "is_test": is_test, "data_layout": data_layout,
                      "fuse_with_relu": fuse_relu})
    return out if fuse_relu else helper.append_activation(out)


def fused_bottleneck(input, ch_out, momentum: float = 0.9,
                     epsilon: float = 1e-5, is_test: bool = False,
                     name=None) -> VarDesc:
    """Fused stride-1 ResNet bottleneck (conv1x1-BN-relu, conv3x3-BN-relu,
    conv1x1-BN, +input, relu) as ONE op — the tuned-kernel tier above the
    generic conv path (≙ the role of conv_cudnn_op.cu.cc in the reference;
    ops/fused_ops.py, kernels/fused_block.py).  Emitted in BOTH train and
    inference graphs (the is_test attr switches the math and internalizes
    the conv→BN weight fold InferenceTranspiler would have applied), so
    the two graphs share parameter names and checkpoints interchange
    BETWEEN THEM.  Parameter layouts match what conv2d/batch_norm create,
    but the NAMES differ from the op-by-op graph's — a checkpoint saved
    from an unfused graph (PT_FUSED_BLOCK=never) does not load into a
    fused one; pick one graph form per model lifetime."""
    helper = LayerHelper("fused_bottleneck", name=name)
    dtype = input.dtype
    cin = input.shape[1]
    assert ch_out * 4 == cin, "rest-block: input channels == 4*ch_out"

    from ..param_attr import ParamAttr

    def conv_w(cout, cink, k):
        fan_in = cink * k * k
        return helper.create_parameter(
            ParamAttr(), [cout, cink, k, k], dtype,
            default_initializer=NormalInitializer(0.0, (2.0 / fan_in) ** 0.5))

    def bn_vars(c):
        return _bn_state_vars(helper, [c], dtype, ParamAttr(), ParamAttr())

    w1 = conv_w(ch_out, cin, 1)
    w2 = conv_w(ch_out, ch_out, 3)
    w3 = conv_w(cin, ch_out, 1)
    bn1 = bn_vars(ch_out)
    bn2 = bn_vars(ch_out)
    bn3 = bn_vars(cin)
    out = helper.create_tmp_variable(dtype)
    inputs = {"X": input, "W1": w1, "W2": w2, "W3": w3}
    outputs = {"Out": out}
    for k, bn in (("1", bn1), ("2", bn2), ("3", bn3)):
        scale, bias, mean, var, saved_m, saved_v = bn
        inputs["Scale" + k] = scale
        inputs["Bias" + k] = bias
        inputs["Mean" + k] = mean
        inputs["Variance" + k] = var
        outputs["MeanOut" + k] = mean
        outputs["VarOut" + k] = var
        outputs["SavedMean" + k] = saved_m
        outputs["SavedVar" + k] = saved_v
    helper.append_op("fused_bottleneck", inputs, outputs,
                     {"momentum": momentum, "epsilon": epsilon,
                      "is_test": is_test})
    return out


def layer_norm(input, scale: bool = True, shift: bool = True,
               begin_norm_axis: int = 1, epsilon: float = 1e-5,
               param_attr=None, bias_attr=None, act=None, name=None) -> VarDesc:
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    dtype = input.dtype
    param_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": input}
    if scale:
        inputs["Scale"] = helper.create_parameter(
            helper.param_attr, param_shape, dtype,
            default_initializer=ConstantInitializer(1.0))
    if shift:
        inputs["Bias"] = helper.create_parameter(
            helper.bias_attr, param_shape, dtype, is_bias=True)
    mean_out = helper.create_tmp_variable("float32", stop_gradient=True)
    var_out = helper.create_tmp_variable("float32", stop_gradient=True)
    out = helper.create_tmp_variable(dtype)
    helper.append_op("layer_norm", inputs,
                     {"Y": out, "Mean": mean_out, "Variance": var_out},
                     {"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(out)


# ---------------------------------------------------------------------------
# Simple wrappers
# ---------------------------------------------------------------------------

def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def _unary(op_type, x, attrs=None, out_dtype=None, extra_outputs=None):
    helper = LayerHelper(op_type)
    out = helper.create_tmp_variable(out_dtype or x.dtype)
    outputs = {"Out": out}
    for slot in (extra_outputs or []):
        ev = helper.create_tmp_variable(x.dtype)
        ev.stop_gradient = True
        outputs[slot] = ev
    helper.append_op(op_type, {"X": x}, outputs, attrs or {})
    return out


def _binary(op_type, x, y, attrs=None):
    helper = LayerHelper(op_type)
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op(op_type, {"X": x, "Y": y}, {"Out": out}, attrs or {})
    return out


def softmax(input, use_cudnn=True, name=None):
    return _unary("softmax", input)


def relu(x, name=None):
    return _unary("relu", x)


def log(x, name=None):
    return _unary("log", x)


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_tmp_variable(logits.dtype)
    loss = helper.create_tmp_variable(logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     {"Logits": logits, "Label": label},
                     {"Loss": loss, "Softmax": softmax_out},
                     {"soft_label": soft_label})
    return loss


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     {"X": x, "Label": label}, {"Out": out})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("matmul", {"X": x, "Y": y}, {"Out": out},
                     {"transpose_X": transpose_x, "transpose_Y": transpose_y,
                      "alpha": alpha})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1):
    helper = LayerHelper("mul")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("mul", {"X": x, "Y": y}, {"Out": out},
                     {"x_num_col_dims": x_num_col_dims,
                      "y_num_col_dims": y_num_col_dims})
    return out


def dot_product(x, y):
    return reduce_sum(elementwise_mul(x, y), dim=-1, keep_dim=True)


def topk(input, k):
    helper = LayerHelper("top_k")
    values = helper.create_tmp_variable(input.dtype)
    indices = helper.create_tmp_variable("int64")
    indices.stop_gradient = True
    helper.append_op("top_k", {"X": input}, {"Out": values, "Indices": indices},
                     {"k": k})
    return values, indices


def _reduce(op_type, input, dim, keep_dim, name=None):
    helper = LayerHelper(op_type)
    out = helper.create_tmp_variable(input.dtype)
    if dim is None:
        attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
    else:
        attrs = {"dim": dim if isinstance(dim, list) else [dim],
                 "keep_dim": keep_dim, "reduce_all": False}
    helper.append_op(op_type, {"X": input}, {"Out": out}, attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim)


def mean(x, name=None):
    return _unary("mean", x)


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize")
    out = helper.create_tmp_variable(x.dtype)
    norm = helper.create_tmp_variable(x.dtype)
    norm.stop_gradient = True
    helper.append_op("l2_normalize", {"X": x}, {"Out": out, "Norm": norm},
                     {"axis": axis, "epsilon": epsilon})
    return out


def one_hot(input, depth):
    return _unary("one_hot", input, {"depth": depth}, out_dtype="float32")


def transpose(x, perm, name=None):
    return _unary("transpose", x, {"axis": list(perm)})


def reshape(x, shape, actual_shape=None, act=None, inplace=True, name=None):
    out = _unary("reshape", x, {"shape": list(shape)})
    if act:
        return _unary(act, out)
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat")
    out = helper.create_tmp_variable(helper.input_dtype() if False else input[0].dtype)
    helper.append_op("concat", {"X": list(input)}, {"Out": out}, {"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split")
    dim = dim if dim >= 0 else dim + len(input.shape)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        attrs = {"num": num, "sections": [], "axis": dim}
    else:
        num = len(num_or_sections)
        attrs = {"num": 0, "sections": list(num_or_sections), "axis": dim}
    outs = [helper.create_tmp_variable(input.dtype) for _ in range(num)]
    helper.append_op("split", {"X": input}, {"Out": outs}, attrs)
    return outs


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_tmp_variable(x[0].dtype)
    helper.append_op("stack", {"X": list(x)}, {"Y": out}, {"axis": axis})
    return out


def unstack(x, axis=0, num=None):
    helper = LayerHelper("unstack")
    num = num if num is not None else x.shape[axis]
    outs = [helper.create_tmp_variable(x.dtype) for _ in range(num)]
    helper.append_op("unstack", {"X": x}, {"Y": outs}, {"axis": axis, "num": num})
    return outs


def expand(x, expand_times, name=None):
    return _unary("expand", x, {"expand_times": list(expand_times)})


def squeeze(input, axes, name=None):
    return _unary("squeeze", input, {"axes": list(axes)})


def unsqueeze(input, axes, name=None):
    return _unary("unsqueeze", input, {"axes": list(axes)})


def flatten(x, axis=1, name=None):
    return _unary("flatten", x, {"axis": axis})


def pad(x, paddings, pad_value=0.0, name=None):
    return _unary("pad", x, {"paddings": list(paddings), "pad_value": pad_value})


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("crop", {"X": x}, {"Out": out},
                     {"shape": list(shape), "offsets": list(offsets or [0] * len(shape))})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("im2sequence", {"X": input}, {"Out": out},
                     {"kernels": _pair(filter_size), "strides": _pair(stride),
                      "paddings": _pair(padding) + _pair(padding)})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn")
    out = helper.create_tmp_variable(input.dtype)
    mid = helper.create_tmp_variable(input.dtype)
    mid.stop_gradient = True
    helper.append_op("lrn", {"X": input}, {"Out": out, "MidOut": mid},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(helper.param_attr, alpha_shape, x.dtype,
                                    default_initializer=ConstantInitializer(0.25))
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("prelu", {"X": x, "Alpha": alpha}, {"Out": out}, {"mode": mode})
    return out


def maxout(x, groups, name=None):
    return _unary("maxout", x, {"groups": groups})


def elementwise_add(x, y, axis=-1, act=None, name=None):
    out = _binary("elementwise_add", x, y, {"axis": axis})
    return _unary(act, out) if act else out


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    out = _binary("elementwise_sub", x, y, {"axis": axis})
    return _unary(act, out) if act else out


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    out = _binary("elementwise_mul", x, y, {"axis": axis})
    return _unary(act, out) if act else out


def elementwise_div(x, y, axis=-1, act=None, name=None):
    out = _binary("elementwise_div", x, y, {"axis": axis})
    return _unary(act, out) if act else out


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_max", x, y, {"axis": axis})


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_min", x, y, {"axis": axis})


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_pow", x, y, {"axis": axis})


def clip(x, min, max, name=None):
    return _unary("clip", x, {"min": float(min), "max": float(max)})


def clip_by_norm(x, max_norm, name=None):
    return _unary("clip_by_norm", x, {"max_norm": float(max_norm)})


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = _unary("scale", x, {"scale": float(scale), "bias": float(bias),
                              "bias_after_scale": bias_after_scale})
    return _unary(act, out) if act else out


def cast(x, dtype):
    return _unary("cast", x, {"in_dtype": x.dtype, "out_dtype": dtype},
                  out_dtype=dtype)


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("gather", {"X": input, "Index": index}, {"Out": out})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("scatter", {"X": input, "Ids": index, "Updates": updates},
                     {"Out": out}, {"overwrite": overwrite})
    return out


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("slice", {"Input": input}, {"Out": out},
                     {"axes": list(axes), "starts": list(starts), "ends": list(ends)})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_tmp_variable("int32")
    out.stop_gradient = True
    helper.append_op("shape", {"Input": input}, {"Out": out})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    diff = helper.create_tmp_variable(x.dtype)
    loss = helper.create_tmp_variable(x.dtype)
    inputs = {"X": x, "Y": y}
    if inside_weight is not None:
        inputs["InsideWeight"] = inside_weight
    if outside_weight is not None:
        inputs["OutsideWeight"] = outside_weight
    helper.append_op("smooth_l1_loss", inputs, {"Diff": diff, "Out": loss},
                     {"sigma": sigma if sigma is not None else 1.0})
    return loss


def warpctc(input, label, blank=0, norm_by_times=False):
    """CTC loss (≙ nn.py warpctc): input [B,T,C] raw logits (sequence var),
    label [B,L] int sequence var; returns Loss [B,1]."""
    from .sequence import _seq_len_of
    helper = LayerHelper("warpctc")
    loss = helper.create_tmp_variable(input.dtype)
    grad = helper.create_tmp_variable(input.dtype)
    grad.stop_gradient = True
    helper.append_op("warpctc",
                     {"Logits": input, "Label": label,
                      "LogitsLen": _seq_len_of(input, helper),
                      "LabelLen": _seq_len_of(label, helper)},
                     {"Loss": loss, "WarpCTCGrad": grad},
                     {"blank": blank, "norm_by_times": norm_by_times})
    loss.shape = (input.shape[0], 1)
    loss.dtype = input.dtype
    return loss


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth")
    out = helper.create_tmp_variable(dtype)
    helper.append_op("label_smooth", {"X": label}, {"Out": out},
                     {"epsilon": float(epsilon)})
    return out


def bilinear_interp(input, out_h, out_w, name=None):
    helper = LayerHelper("bilinear_interp")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("bilinear_interp", {"X": input}, {"Out": out},
                     {"out_h": out_h, "out_w": out_w})
    return out


resize_bilinear = bilinear_interp


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("random_crop", {"X": x}, {"Out": out}, {"shape": list(shape)})
    return out


def nce(input, label, num_total_classes, num_neg_samples=10, param_attr=None,
        bias_attr=None, name=None):
    """layers/nn.py nce (noise-contrastive estimation head). Returns the
    per-row NCE cost [B, 1]; weights [V, D] + bias [V] are parameters."""
    helper = LayerHelper("nce", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                [num_total_classes, dim], "float32")
    b = helper.create_parameter(helper.bias_attr, [num_total_classes],
                                "float32", is_bias=True)
    cost = helper.create_tmp_variable("float32")
    sample_logits = helper.create_tmp_variable("float32")
    sample_labels = helper.create_tmp_variable("int32")
    sample_logits.stop_gradient = True
    sample_labels.stop_gradient = True
    helper.append_op("nce",
                     {"Input": input, "Label": label, "Weight": w,
                      "Bias": b},
                     {"Cost": cost, "SampleLogits": sample_logits,
                      "SampleLabels": sample_labels},
                     {"num_total_classes": num_total_classes,
                      "num_neg_samples": num_neg_samples})
    return cost


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """layers/nn.py row_conv (lookahead convolution, DeepSpeech2)."""
    helper = LayerHelper("row_conv", param_attr=param_attr, act=act,
                         name=name)
    dim = input.shape[-1]
    f = helper.create_parameter(helper.param_attr,
                                [future_context_size + 1, dim], "float32")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("row_conv", {"X": input, "Filter": f}, {"Out": out}, {})
    return helper.append_activation(out)


def mean_iou(input, label, num_classes, name=None):
    """layers/nn.py:mean_iou — returns (mean_iou, out_wrong, out_correct)."""
    helper = LayerHelper("mean_iou", name=name)
    miou = helper.create_tmp_variable("float32")
    wrong = helper.create_tmp_variable("int32")
    correct = helper.create_tmp_variable("int32")
    helper.append_op("mean_iou", {"Predictions": input, "Labels": label},
                     {"OutMeanIou": miou, "OutWrong": wrong,
                      "OutCorrect": correct},
                     {"num_classes": num_classes})
    return miou, wrong, correct


def bpr_loss(input, label, name=None):
    """layers/nn.py bpr_loss (Bayesian Personalized Ranking)."""
    helper = LayerHelper("bpr_loss", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("bpr_loss", {"X": input, "Label": label}, {"Y": out}, {})
    return out


def spp(input, pyramid_height, pool_type="max", name=None):
    """Spatial pyramid pooling layer (spp_op.cc)."""
    helper = LayerHelper("spp", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("spp", {"X": input}, {"Out": out},
                     {"pyramid_height": pyramid_height,
                      "pooling_type": pool_type})
    return out


def moe_ffn(input, num_experts, hidden_size, top_k=1, capacity_factor=1.25,
            act="relu", param_attr=None, name=None):
    """Mixture-of-Experts FFN with expert parallelism (additive — SURVEY
    §2.4 notes the reference has none). Expert weights are stacked
    [E, ...] and annotated sharded over the 'ep' mesh axis, so each
    expert's parameters live on its own devices and GSPMD inserts the
    dispatch all-to-all. Returns (out, aux_loss); add aux_loss (scaled
    ~1e-2) to the training loss for load balancing."""
    import copy
    helper = LayerHelper(name or "moe", param_attr=param_attr)
    d = int(input.shape[-1])
    from ..param_attr import ParamAttr as _PA
    from ..initializer import XavierInitializer as _Xavier

    def _attr(tag):
        # fresh copy per parameter: create_parameter fills attr.name in
        # place, and a user-supplied explicit name must not alias the five
        # distinct parameters
        a = copy.copy(_PA.to_attr(param_attr))
        if a.name is not None:
            a.name = f"{a.name}.{tag}"
        return a

    def expert_param(shape, fan_in, fan_out, tag, is_bias=False):
        p = helper.create_parameter(
            _attr(tag), [num_experts] + list(shape), "float32",
            is_bias=is_bias,
            default_initializer=None if is_bias
            else _Xavier(fan_in=fan_in, fan_out=fan_out))
        from ..parallel.mesh import EP
        p.sharding = (EP,) + (None,) * len(shape)
        return p

    gate_w = helper.create_parameter(_attr("gate"), [d, num_experts],
                                     "float32")
    w1 = expert_param([d, hidden_size], d, hidden_size, "w1")
    b1 = expert_param([hidden_size], 0, 0, "b1", is_bias=True)
    w2 = expert_param([hidden_size, d], hidden_size, d, "w2")
    b2 = expert_param([d], 0, 0, "b2", is_bias=True)
    out = helper.create_tmp_variable(input.dtype)
    aux = helper.create_tmp_variable("float32")
    helper.append_op("moe_ffn",
                     {"X": input, "GateW": gate_w, "W1": w1, "B1": b1,
                      "W2": w2, "B2": b2},
                     {"Out": out, "AuxLoss": aux},
                     {"top_k": top_k, "capacity_factor": capacity_factor,
                      "act": act})
    return out, aux


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None,
           name=None):
    """NCDHW 3-D convolution (conv_op.cc 3-D path)."""
    helper = LayerHelper("conv3d", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c_in = input.shape[1]
    k = (filter_size,) * 3 if isinstance(filter_size, int) \
        else tuple(filter_size)
    g = groups or 1
    w = helper.create_parameter(
        helper.param_attr, [num_filters, c_in // g] + list(k), "float32")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("conv3d", {"Input": input, "Filter": w},
                     {"Output": out},
                     {"strides": [stride] * 3 if isinstance(stride, int)
                      else list(stride),
                      "paddings": [padding] * 3 if isinstance(padding, int)
                      else list(padding),
                      "dilations": [dilation] * 3
                      if isinstance(dilation, int) else list(dilation),
                      "groups": g})
    if bias_attr is not False:
        out = helper.append_bias_op(out, dim_start=1, dim_end=2,
                                    size=[num_filters])
    return helper.append_activation(out)


def pool3d(input, pool_size, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, exclusive=True, name=None):
    """NCDHW 3-D pooling (pool_op.cc 3-D path)."""
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_tmp_variable(input.dtype)
    tri = lambda v: [v] * 3 if isinstance(v, int) else list(v)
    helper.append_op("pool3d", {"X": input}, {"Out": out},
                     {"ksize": tri(pool_size), "strides": tri(pool_stride),
                      "paddings": tri(pool_padding),
                      "pooling_type": pool_type,
                      "global_pooling": global_pooling,
                      "exclusive": exclusive})
    return out


def cos_sim(X, Y, name=None):
    """cos_sim_op.cc: row-wise cosine similarity (Y may broadcast [1, D])."""
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_tmp_variable(X.dtype)
    xn = helper.create_tmp_variable(X.dtype)
    yn = helper.create_tmp_variable(X.dtype)
    helper.append_op("cos_sim", {"X": X, "Y": Y},
                     {"Out": out, "XNorm": xn, "YNorm": yn}, {})
    out.shape = tuple(X.shape[:-1]) + (1,)
    out.dtype = X.dtype
    return out


def multiplex(inputs, index, name=None):
    """multiplex_op.cc: per-row select among candidate tensors by index."""
    helper = LayerHelper("multiplex", name=name)
    out = helper.create_tmp_variable(inputs[0].dtype)
    helper.append_op("multiplex", {"X": list(inputs), "Ids": index},
                     {"Out": out}, {})
    out.shape, out.dtype = inputs[0].shape, inputs[0].dtype
    return out


def dice_loss(input, label, epsilon=1e-5):
    """≙ layers/nn.py dice_loss: 1 - 2|X∩Y| / (|X|+|Y|), composed from
    elementwise ops exactly like the reference (no dedicated kernel)."""
    label = one_hot(label, depth=input.shape[-1])
    reduce_dims = list(range(1, len(input.shape)))
    inse = reduce_sum(elementwise_mul(input, label), dim=reduce_dims)
    dice_denominator = elementwise_add(
        reduce_sum(input, dim=reduce_dims),
        reduce_sum(label, dim=reduce_dims))
    dice_score = scale(elementwise_div(
        scale(inse, scale=2.0),
        scale(dice_denominator, bias=epsilon)), scale=-1.0, bias=1.0)
    return reduce_mean(dice_score)


def image_resize(input, out_shape=None, scale=None, resample="BILINEAR",
                 name=None):
    """≙ layers/nn.py image_resize → bilinear_interp op (NCHW)."""
    if resample not in ("BILINEAR", "NEAREST"):
        raise ValueError(f"image_resize: unsupported resample {resample!r}")
    if out_shape is None:
        if scale is None:
            raise ValueError("image_resize: give out_shape or scale")
        out_shape = [int(input.shape[2] * scale), int(input.shape[3] * scale)]
    helper = LayerHelper("image_resize", name=name)
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("bilinear_interp", {"X": input}, {"Out": out},
                     {"out_h": int(out_shape[0]), "out_w": int(out_shape[1]),
                      "method": "nearest" if resample == "NEAREST"
                      else "bilinear"})
    out.shape = tuple(input.shape[:2]) + (int(out_shape[0]), int(out_shape[1]))
    out.dtype = input.dtype
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """≙ layers/nn.py image_resize_short: resize keeping aspect ratio so
    the SHORT side hits out_short_len."""
    h, w = input.shape[2], input.shape[3]
    short = min(h, w)
    return image_resize(input, [h * out_short_len // short,
                                w * out_short_len // short], resample=resample)


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    """≙ layers/nn.py gru_unit (gru_unit_op.cc): one GRU step. `size` =
    3×hidden per the reference convention. Returns (hidden [B, D],
    reset_hidden_prev, gate)."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr)
    d = size // 3
    weight = helper.create_parameter(helper.param_attr, [d, 3 * d],
                                     input.dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * d], input.dtype,
                                   is_bias=True)
    h = helper.create_tmp_variable(input.dtype)
    gate = helper.create_tmp_variable(input.dtype)
    reset_h = helper.create_tmp_variable(input.dtype)
    helper.append_op(
        "gru_unit",
        {"Input": input, "HiddenPrev": hidden, "Weight": weight,
         "Bias": bias},
        {"Hidden": h, "Gate": gate, "ResetHiddenPrev": reset_h},
        {"activation": activation, "gate_activation": gate_activation})
    # (the op reads both attrs; see ops/volumetric_ops.py gru_unit)
    h.shape = reset_h.shape = tuple(hidden.shape)
    gate.shape = tuple(hidden.shape[:-1]) + (3 * d,)
    h.dtype = gate.dtype = reset_h.dtype = input.dtype
    return h, reset_h, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """≙ layers/nn.py lstm_unit (lstm_unit_op): one LSTM step. Projects
    [x_t, h_prev] by an fc to 4D gate pre-activations (i|f|o|g layout,
    lstm_unit_op.h:63-66), then applies the cell. Returns (h, c)."""
    d = cell_t_prev.shape[-1]
    gates = fc(input=[x_t, hidden_t_prev], size=4 * d,
               param_attr=param_attr, bias_attr=bias_attr)
    helper = LayerHelper("lstm_unit", name=name)
    h = helper.create_tmp_variable(x_t.dtype)
    c = helper.create_tmp_variable(x_t.dtype)
    helper.append_op("lstm_unit", {"X": gates, "C_prev": cell_t_prev},
                     {"H": h, "C": c}, {"forget_bias": float(forget_bias)})
    h.shape = c.shape = tuple(cell_t_prev.shape)
    h.dtype = c.dtype = x_t.dtype
    return h, c


def uniform_random_batch_size_like(input, shape, input_dim_idx=0,
                                   output_dim_idx=0, min=-1.0, max=1.0,
                                   dtype="float32", seed=0):
    """uniform_random_batch_size_like_op.cc: uniform noise whose dim
    `output_dim_idx` copies `input`'s dim `input_dim_idx`."""
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_tmp_variable(dtype)
    helper.append_op("uniform_random_batch_size_like", {"Input": input},
                     {"Out": out},
                     {"shape": list(shape), "min": min, "max": max,
                      "dtype": dtype, "seed": seed,
                      "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def gaussian_random(shape, mean=0.0, std=1.0, dtype="float32", seed=0):
    """gaussian_random_op.cc."""
    helper = LayerHelper("gaussian_random")
    out = helper.create_tmp_variable(dtype)
    helper.append_op("gaussian_random", {}, {"Out": out},
                     {"shape": list(shape), "mean": mean, "std": std,
                      "dtype": dtype, "seed": seed})
    out.shape, out.dtype = tuple(shape), dtype
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    dtype="float32", seed=0):
    """gaussian_random_batch_size_like_op.cc."""
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_tmp_variable(dtype)
    helper.append_op("gaussian_random_batch_size_like", {"Input": input},
                     {"Out": out},
                     {"shape": list(shape), "mean": mean, "std": std,
                      "dtype": dtype, "seed": seed,
                      "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def uniform_random(shape, min=-1.0, max=1.0, dtype="float32", seed=0):
    """uniform_random_op.cc."""
    helper = LayerHelper("uniform_random")
    out = helper.create_tmp_variable(dtype)
    helper.append_op("uniform_random", {}, {"Out": out},
                     {"shape": list(shape), "min": min, "max": max,
                      "dtype": dtype, "seed": seed})
    out.shape, out.dtype = tuple(shape), dtype
    return out
