"""Auto-generated thin layer wrappers for registered elementwise ops.

≙ reference python/paddle/fluid/layers/ops.py +
layer_function_generator.py — the reference generates ~40 layer functions
from OpProto self-descriptions; here we generate them from the op registry.
"""

from __future__ import annotations

from ..layer_helper import LayerHelper

_UNARY_OPS = [
    "sigmoid", "logsigmoid", "exp", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round",
    "reciprocal", "square", "softplus", "softsign", "brelu", "leaky_relu",
    "soft_relu", "elu", "relu6", "pow", "stanh", "hard_sigmoid", "swish",
    "gelu", "thresholded_relu", "hard_shrink", "cumsum", "log_softmax",
]

__all__ = list(_UNARY_OPS)


def _make_layer(op_type):
    def layer(x, **kwargs):
        helper = LayerHelper(op_type)
        out = helper.create_tmp_variable(x.dtype)
        attrs = {k: v for k, v in kwargs.items() if k != "name" and v is not None}
        helper.append_op(op_type, {"X": x}, {"Out": out}, attrs)
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"Auto-generated wrapper for the `{op_type}` op."
    return layer


for _op in _UNARY_OPS:
    globals()[_op] = _make_layer(_op)
