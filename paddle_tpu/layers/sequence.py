"""Sequence layer functions over padded+lengths ragged batches.

≙ reference python/paddle/fluid/layers/nn.py sequence_* layers +
dynamic_lstm:216 / dynamic_gru. Every sequence variable carries a
`@SEQ_LEN` companion (VarDesc.seq_len_var) wired automatically.
"""

from __future__ import annotations

import numpy as np

from ..core.program import VarDesc
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer

__all__ = [
    "sequence_pool", "sequence_softmax", "sequence_first_step",
    "sequence_last_step", "sequence_expand", "sequence_conv",
    "sequence_reshape", "sequence_concat", "sequence_erase",
    "sequence_enumerate", "dynamic_lstm", "dynamic_lstmp", "dynamic_gru",
    "edit_distance",
]


def _seq_len_of(x: VarDesc, helper: LayerHelper) -> str:
    name = getattr(x, "seq_len_var", None)
    if not name:
        raise ValueError(
            f"{x.name} is not a sequence variable (no @SEQ_LEN companion); "
            "declare it with layers.data(..., lod_level=1)")
    return name


def _mark_seq(out: VarDesc, seq_len_name: str):
    out.seq_len_var = seq_len_name
    out.lod_level = 1
    return out


def propagate_seq(src: VarDesc, dst: VarDesc):
    """Carry the sequence companion through a timestep-preserving layer."""
    if getattr(src, "seq_len_var", None):
        dst.seq_len_var = src.seq_len_var
        dst.lod_level = src.lod_level
    return dst


def sequence_pool(input, pool_type: str):
    helper = LayerHelper("sequence_pool")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_pool",
                     {"X": input, "SeqLen": _seq_len_of(input, helper)},
                     {"Out": out}, {"pooltype": pool_type})
    if input.shape:
        out.shape = tuple(input.shape[:1]) + tuple(input.shape[2:])
        out.dtype = input.dtype
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_softmax",
                     {"X": input, "SeqLen": _seq_len_of(input, helper)},
                     {"Out": out})
    out.shape, out.dtype = input.shape, input.dtype
    return _mark_seq(out, input.seq_len_var)


def sequence_expand(x, y, name=None):
    helper = LayerHelper("sequence_expand")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("sequence_expand", {"X": x, "Y": y}, {"Out": out})
    if x.shape and y.shape:
        out.shape = (x.shape[0], y.shape[1]) + tuple(x.shape[1:])
    return _mark_seq(out, _seq_len_of(y, helper))


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act)
    dtype = input.dtype
    d = input.shape[-1]
    filter_shape = [filter_size * d, num_filters]
    w = helper.create_parameter(helper.param_attr, filter_shape, dtype)
    out = helper.create_tmp_variable(dtype)
    helper.append_op("sequence_conv",
                     {"X": input, "Filter": w,
                      "SeqLen": _seq_len_of(input, helper)},
                     {"Out": out},
                     {"contextStride": filter_stride,
                      "contextStart": -int(filter_size // 2),
                      "contextLength": filter_size})
    out.shape = tuple(input.shape[:2]) + (num_filters,)
    out.dtype = dtype
    _mark_seq(out, input.seq_len_var)
    pre_act = helper.append_bias_op(out, dim_start=2)
    res = helper.append_activation(pre_act)
    if res is not out:
        _mark_seq(res, input.seq_len_var)
        res.shape = out.shape
    return res


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_reshape", {"X": input}, {"Out": out},
                     {"new_dim": new_dim})
    return _mark_seq(out, _seq_len_of(input, helper))


def sequence_concat(input, axis=-1, name=None):
    helper = LayerHelper("sequence_concat")
    out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op("sequence_concat", {"X": list(input)}, {"Out": out})
    return _mark_seq(out, _seq_len_of(input[0], helper))


def sequence_erase(input, tokens):
    helper = LayerHelper("sequence_erase")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_erase", {"X": input}, {"Out": out},
                     {"tokens": list(tokens)})
    return _mark_seq(out, _seq_len_of(input, helper))


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    helper = LayerHelper("sequence_enumerate")
    out = helper.create_tmp_variable(input.dtype)
    helper.append_op("sequence_enumerate", {"X": input}, {"Out": out},
                     {"win_size": win_size, "pad_value": pad_value})
    return _mark_seq(out, _seq_len_of(input, helper))


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    helper = LayerHelper("edit_distance")
    out = helper.create_tmp_variable("float32")
    seq_num = helper.create_tmp_variable("int64")
    for v in (out, seq_num):
        v.stop_gradient = True
    inputs = {"Hyps": input, "Refs": label}
    if getattr(input, "seq_len_var", None):
        inputs["HypsLen"] = input.seq_len_var
    if getattr(label, "seq_len_var", None):
        inputs["RefsLen"] = label.seq_len_var
    helper.append_op("edit_distance", inputs,
                     {"Out": out, "SequenceNum": seq_num},
                     {"normalized": normalized})
    return out, seq_num


# ---------------------------------------------------------------------------
# Fused recurrent layers
# ---------------------------------------------------------------------------

def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """layers/nn.py:216. `size` = 4×hidden (reference convention); input is
    the pre-projected [B, T, 4H]. Returns (hidden, cell) each [B, T, H]."""
    helper = LayerHelper("lstm", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    hidden_size = size // 4
    weight = helper.create_parameter(helper.param_attr,
                                     [hidden_size, 4 * hidden_size], dtype)
    bias_size = 4 * hidden_size + (3 * hidden_size if use_peepholes else 0)
    bias = helper.create_parameter(helper.bias_attr, [1, bias_size], dtype,
                                   is_bias=True)
    hidden = helper.create_tmp_variable(dtype)
    cell = helper.create_tmp_variable(dtype)
    inputs = {"Input": input, "Weight": weight, "Bias": bias,
              "SeqLen": _seq_len_of(input, helper)}
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op("dynamic_lstm", inputs,
                     {"Hidden": hidden, "Cell": cell},
                     {"use_peepholes": use_peepholes, "is_reverse": is_reverse,
                      "gate_activation": gate_activation,
                      "cell_activation": cell_activation,
                      "candidate_activation": candidate_activation})
    shape = tuple(input.shape[:2]) + (hidden_size,)
    hidden.shape = cell.shape = shape
    hidden.dtype = cell.dtype = dtype
    _mark_seq(hidden, input.seq_len_var)
    _mark_seq(cell, input.seq_len_var)
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=True,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", dtype="float32", name=None):
    """≙ layers/nn.py dynamic_lstmp (lstmp_op.cc): LSTM with recurrent
    projection. `size` = 4×hidden; returns (projection [B,T,P], cell
    [B,T,H])."""
    import copy
    helper = LayerHelper("lstmp", param_attr=param_attr, bias_attr=bias_attr,
                         name=name)
    hidden_size = size // 4
    # separate ParamAttr copies: create_parameter binds attr.name in place,
    # so sharing one attr would collide the two weights
    weight = helper.create_parameter(copy.copy(helper.param_attr),
                                     [proj_size, 4 * hidden_size], dtype)
    proj_attr = copy.copy(helper.param_attr)
    if getattr(proj_attr, "name", None):
        # an explicit ParamAttr(name=...) would otherwise bind both weights
        # to the same parameter; give the projection weight its own name
        proj_attr.name = proj_attr.name + "_proj"
    proj_weight = helper.create_parameter(proj_attr,
                                          [hidden_size, proj_size], dtype)
    bias_size = 4 * hidden_size + (3 * hidden_size if use_peepholes else 0)
    bias = helper.create_parameter(helper.bias_attr, [1, bias_size], dtype,
                                   is_bias=True)
    proj = helper.create_tmp_variable(dtype)
    cell = helper.create_tmp_variable(dtype)
    inputs = {"Input": input, "Weight": weight, "ProjWeight": proj_weight,
              "Bias": bias, "SeqLen": _seq_len_of(input, helper)}
    if h_0 is not None:
        inputs["H0"] = h_0
    if c_0 is not None:
        inputs["C0"] = c_0
    helper.append_op("lstmp", inputs,
                     {"Projection": proj, "Cell": cell},
                     {"use_peepholes": use_peepholes, "is_reverse": is_reverse,
                      "gate_activation": gate_activation,
                      "cell_activation": cell_activation,
                      "candidate_activation": candidate_activation,
                      "proj_activation": proj_activation})
    proj.shape = tuple(input.shape[:2]) + (proj_size,)
    cell.shape = tuple(input.shape[:2]) + (hidden_size,)
    proj.dtype = cell.dtype = dtype
    _mark_seq(proj, input.seq_len_var)
    _mark_seq(cell, input.seq_len_var)
    return proj, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, dtype="float32"):
    """layers/nn.py dynamic_gru: `size` = hidden; input [B, T, 3H]."""
    helper = LayerHelper("gru", param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(helper.param_attr, [size, 3 * size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * size], dtype,
                                   is_bias=True)
    hidden = helper.create_tmp_variable(dtype)
    inputs = {"Input": input, "Weight": weight, "Bias": bias,
              "SeqLen": _seq_len_of(input, helper)}
    if h_0 is not None:
        inputs["H0"] = h_0
    helper.append_op("dynamic_gru", inputs, {"Hidden": hidden},
                     {"is_reverse": is_reverse,
                      "gate_activation": gate_activation,
                      "activation": candidate_activation})
    hidden.shape = tuple(input.shape[:2]) + (size,)
    hidden.dtype = dtype
    return _mark_seq(hidden, input.seq_len_var)
