"""Tensor-creation layer functions.

≙ reference python/paddle/fluid/layers/tensor.py (create_tensor,
create_parameter, create_global_var, fill_constant, ones, zeros, sums,
assign, argmin/argmax, ...).
"""

from __future__ import annotations

from ..core.program import VarDesc, default_main_program, default_startup_program
from ..layer_helper import LayerHelper
from ..initializer import ConstantInitializer
from ..param_attr import ParamAttr

__all__ = [
    "create_tensor", "create_parameter", "create_global_var", "fill_constant",
    "fill_constant_batch_size_like", "ones", "zeros", "sums", "assign",
    "argmin", "argmax", "reverse", "cast", "concat", "sum", "is_empty",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    helper = LayerHelper("create_parameter", name=name)
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(name=name, dtype=dtype, shape=shape,
                                        persistable=persistable)
    helper.set_variable_initializer(var, ConstantInitializer(value))
    return var


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant")
    if out is None:
        out = helper.create_tmp_variable(dtype)
    out.stop_gradient = True
    helper.append_op("fill_constant", {}, {"Out": out},
                     {"shape": list(shape), "dtype": dtype, "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_tmp_variable(dtype)
    out.stop_gradient = True
    helper.append_op("fill_constant_batch_size_like", {"Input": input},
                     {"Out": out},
                     {"shape": list(shape), "dtype": dtype, "value": float(value),
                      "input_dim_idx": input_dim_idx,
                      "output_dim_idx": output_dim_idx})
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape, dtype, 0.0)


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_tmp_variable(input[0].dtype)
    helper.append_op("sum", {"X": list(input)}, {"Out": out})
    # summing per-timestep features keeps raggedness (reference: sum_op
    # shares the inputs' LoD) — propagate the @SEQ_LEN companion
    from .sequence import propagate_seq
    for x in input:
        if getattr(x, "seq_len_var", None):
            propagate_seq(x, out)
            break
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if output is None:
        output = helper.create_tmp_variable(
            input.dtype if isinstance(input, VarDesc) else "float32")
    if isinstance(input, VarDesc):
        helper.append_op("assign", {"X": input}, {"Out": output})
    else:
        import numpy as np
        arr = np.asarray(input)
        helper.append_op("assign_value", {}, {"Out": output},
                         {"shape": list(arr.shape), "dtype": str(arr.dtype),
                          "values": arr.ravel().tolist()})
    return output


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    out = helper.create_tmp_variable("int64")
    out.stop_gradient = True
    helper.append_op("arg_min", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    out = helper.create_tmp_variable("int64")
    out.stop_gradient = True
    helper.append_op("arg_max", {"X": x}, {"Out": out}, {"axis": axis})
    return out


def reverse(x, axis):
    helper = LayerHelper("reverse")
    out = helper.create_tmp_variable(x.dtype)
    helper.append_op("reverse", {"X": x}, {"Out": out},
                     {"axis": [axis] if isinstance(axis, int) else list(axis)})
    return out


# re-export from nn to mirror fluid.layers flat namespace
from .nn import cast, concat  # noqa: E402,F401


def is_empty(x, cond=None):
    """is_empty_op.cc: scalar bool, true when x has zero elements."""
    helper = LayerHelper("is_empty")
    if cond is None:
        cond = helper.create_tmp_variable("bool")
    helper.append_op("is_empty", {"X": x}, {"Out": cond}, {})
    cond.shape, cond.dtype = (), "bool"
    return cond


# public alias for fluid.layers.sum (sum_op.cc). NOTE: this shadows the
# builtin `sum` for ALL code in this module (globals resolve at call time) —
# any future helper here must use builtins.sum explicitly.
sum = sums
