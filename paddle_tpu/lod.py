"""Host-side ragged-sequence containers and padding.

≙ reference LoDTensor (paddle/fluid/framework/lod_tensor.h:110, python
python/paddle/fluid/lod_tensor.py). On device a sequence batch is padded
dense + lengths (ops/sequence_ops.py); this module is the host-side bridge:
build from a list of variable-length sequences, pad to a bucketed max length
(bounding XLA recompiles while keeping pad waste low — the static-shape
answer to LoD's zero-padding batching).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def _round_up(n: int, multiple: int) -> int:
    if multiple <= 1:
        return n
    return ((n + multiple - 1) // multiple) * multiple


class LoDTensor:
    """A batch of variable-length sequences (level-1 LoD parity)."""

    def __init__(self, sequences: Optional[Sequence[np.ndarray]] = None):
        self.sequences: List[np.ndarray] = [np.asarray(s) for s in (sequences or [])]

    # reference-compatible construction: flat data + offsets
    @staticmethod
    def from_flat(data: np.ndarray, lod: Sequence[Sequence[int]]) -> "LoDTensor":
        data = np.asarray(data)
        offsets = list(lod[0])
        seqs = [data[offsets[i]:offsets[i + 1]] for i in range(len(offsets) - 1)]
        return LoDTensor(seqs)

    def set(self, data, place=None):
        self._flat = np.asarray(data)
        return self

    def set_lod(self, lod):
        t = LoDTensor.from_flat(self._flat, lod)
        self.sequences = t.sequences
        return self

    def lod(self):
        offs = [0]
        for s in self.sequences:
            offs.append(offs[-1] + len(s))
        return [offs]

    def __len__(self):
        return len(self.sequences)

    def to_padded(self, pad_multiple: int = 8, pad_value=0,
                  max_len: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
        """-> (padded [B, T, ...], lengths [B] int32)."""
        lens = np.asarray([len(s) for s in self.sequences], np.int32)
        T = int(max_len if max_len is not None else
                _round_up(int(lens.max() if len(lens) else 1), pad_multiple))
        B = len(self.sequences)
        tail = self.sequences[0].shape[1:] if B else ()
        out = np.full((B, T) + tuple(tail), pad_value,
                      self.sequences[0].dtype if B else np.float32)
        for i, s in enumerate(self.sequences):
            out[i, :len(s)] = s
        return out, lens


def pad_sequences(seqs: Sequence, dtype=None, pad_multiple: int = 8,
                  pad_value=0) -> Tuple[np.ndarray, np.ndarray]:
    """list of per-sequence arrays/lists -> (padded, lengths)."""
    arrs = [np.asarray(s, dtype=dtype) for s in seqs]
    return LoDTensor(arrs).to_padded(pad_multiple, pad_value)


def create_lod_tensor(data, recursive_seq_lens=None, place=None) -> LoDTensor:
    """≙ fluid.create_lod_tensor (lod_tensor.py): data may be a list of
    sequences or flat ndarray + lengths."""
    if recursive_seq_lens is None:
        return LoDTensor(data)
    lens = recursive_seq_lens[0]
    offsets = np.concatenate([[0], np.cumsum(lens)])
    return LoDTensor.from_flat(np.asarray(data), [offsets.tolist()])
