"""Host-side ragged-sequence containers and padding.

≙ reference LoDTensor (paddle/fluid/framework/lod_tensor.h:110, python
python/paddle/fluid/lod_tensor.py). On device a sequence batch is padded
dense + lengths (ops/sequence_ops.py); this module is the host-side bridge:
build from a list of variable-length sequences, pad to a bucketed max length
(bounding XLA recompiles while keeping pad waste low — the static-shape
answer to LoD's zero-padding batching).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def _round_up(n: int, multiple: int) -> int:
    if multiple <= 1:
        return n
    return ((n + multiple - 1) // multiple) * multiple


class LoDTensor:
    """A batch of variable-length sequences.

    Level-1: `sequences` is a list of arrays. Multi-level (nested) LoD
    (lod_tensor.h:44-58, e.g. paragraph→sentence→word): `sequences` is a
    list of LISTS (recursively) with arrays at the leaves; `lod()` returns
    one offset table per level and `to_padded` pads every nesting level
    ([B, S, W, ...] for level 2) with per-level length arrays.
    """

    def __init__(self, sequences: Optional[Sequence] = None):
        self.sequences: List = [self._ingest(s) for s in (sequences or [])]

    @staticmethod
    def _ingest(s):
        """One element of `sequences`. ndarray = a leaf sequence; a list
        whose children are ndarrays (or deeper lists) = a NESTED element —
        including rectangular ones, which must not collapse to a leaf.
        Python list-of-scalars / list-of-rows stay leaf [T] / [T, D]."""
        if isinstance(s, np.ndarray):
            return s
        if isinstance(s, (list, tuple)):
            if any(isinstance(c, (np.ndarray, list, tuple))
                   and LoDTensor._is_sequencey(c) for c in s):
                return [LoDTensor._ingest(c) for c in s]
        return np.asarray(s)

    @staticmethod
    def _is_sequencey(c) -> bool:
        """True when c is itself a sequence-of-sequences or an ndarray —
        i.e. its parent is a nesting level, not a leaf row matrix."""
        if isinstance(c, np.ndarray):
            return True
        return bool(c) and isinstance(c, (list, tuple)) and isinstance(
            c[0], (list, tuple, np.ndarray))

    @property
    def lod_level(self) -> int:
        def depth(x):
            return 1 if isinstance(x, np.ndarray) else 1 + max(
                (depth(c) for c in x), default=1)
        return max((depth(s) for s in self.sequences), default=1)

    # reference-compatible construction: flat data + offsets (any depth)
    @staticmethod
    def from_flat(data: np.ndarray, lod: Sequence[Sequence[int]]) -> "LoDTensor":
        data = np.asarray(data)
        # innermost level slices the data rows
        offsets = list(lod[-1])
        pieces: List = [data[offsets[i]:offsets[i + 1]]
                        for i in range(len(offsets) - 1)]
        # outer levels group the previous level's pieces
        for level in reversed(lod[:-1]):
            offs = list(level)
            pieces = [pieces[offs[i]:offs[i + 1]]
                      for i in range(len(offs) - 1)]
        t = LoDTensor()
        t.sequences = pieces  # structure is explicit: bypass _ingest
        return t

    def set(self, data, place=None):
        self._flat = np.asarray(data)
        return self

    def set_lod(self, lod):
        t = LoDTensor.from_flat(self._flat, lod)
        self.sequences = t.sequences
        return self

    def lod(self):
        """Offset tables, outermost first (≙ LoD, lod_tensor.h:58)."""
        levels: List[List[int]] = []
        layer = self.sequences
        while True:
            offs = [0]
            leaf = all(isinstance(s, np.ndarray) for s in layer)
            for s in layer:
                offs.append(offs[-1] + len(s))
            levels.append(offs)
            if leaf:
                return levels
            layer = [c for s in layer for c in s]

    def __len__(self):
        return len(self.sequences)

    def to_padded(self, pad_multiple: int = 8, pad_value=0,
                  max_len: Optional[int] = None):
        """Level-1 -> (padded [B, T, ...], lengths [B] int32).
        Level-2 -> (padded [B, S, W, ...], (outer_lens [B],
        inner_lens [B, S])) — nested sequences padded at every level."""
        if self.lod_level <= 1:
            return self._pad_level1(self.sequences, pad_multiple, pad_value,
                                    max_len)
        assert self.lod_level == 2, "deeper nesting: pad recursively"
        B = len(self.sequences)
        outer_lens = np.asarray([len(s) for s in self.sequences], np.int32)
        S = _round_up(int(outer_lens.max() if B else 1), 1)
        leaves = [leaf for s in self.sequences for leaf in s]
        W = int(max_len if max_len is not None else
                _round_up(max((len(x) for x in leaves), default=1),
                          pad_multiple))
        tail = leaves[0].shape[1:] if leaves else ()
        dtype = leaves[0].dtype if leaves else np.float32
        out = np.full((B, S, W) + tuple(tail), pad_value, dtype)
        inner_lens = np.zeros((B, S), np.int32)
        for i, s in enumerate(self.sequences):
            for j, leaf in enumerate(s):
                out[i, j, :len(leaf)] = leaf
                inner_lens[i, j] = len(leaf)
        return out, (outer_lens, inner_lens)

    @staticmethod
    def _pad_level1(sequences, pad_multiple, pad_value, max_len):
        lens = np.asarray([len(s) for s in sequences], np.int32)
        if max_len is not None and len(lens) and int(lens.max()) > max_len:
            raise ValueError(
                f"pad_sequences: a sequence of length {int(lens.max())} "
                f"exceeds max_len={max_len} (bucketed on a different "
                "slot? pin pad_to only to slots that fit)")
        T = int(max_len if max_len is not None else
                _round_up(int(lens.max() if len(lens) else 1), pad_multiple))
        B = len(sequences)
        tail = sequences[0].shape[1:] if B else ()
        dtype = sequences[0].dtype if B else np.float32
        native = _pack_rows_native(sequences, lens, T, tail, dtype, pad_value)
        if native is not None:
            return native
        out = np.full((B, T) + tuple(tail), pad_value, dtype)
        for i, s in enumerate(sequences):
            out[i, :len(s)] = s
        return out, lens


def _pack_rows_native(sequences, lens, T, tail, dtype, pad_value):
    """One-call native pack (native/batcher.cpp pack_rows, ≙ the
    reference's native sequence2batch host layer). Returns (out, lens) or
    None to fall back to the Python loop (no toolchain, or rows that are
    not plain contiguous same-dtype arrays)."""
    import ctypes
    if not sequences:
        return None
    from .native import batcher_lib
    lib = batcher_lib()
    if lib is None:
        return None
    dtype = np.dtype(dtype)
    pad_elem = np.asarray(pad_value, dtype)
    if dtype == object or pad_elem.ndim != 0:
        return None  # non-scalar pad patterns: np.full broadcast semantics
    tail = tuple(tail)
    for s in sequences:
        # the C side memcpys len*step_bytes straight from each row buffer:
        # every guarantee (dtype, tail shape, contiguity) must hold here,
        # anything else takes the Python loop
        if (not isinstance(s, np.ndarray) or s.dtype != dtype
                or s.shape[1:] != tail
                or not s.flags["C_CONTIGUOUS"]):
            return None
    step_bytes = int(np.prod(tail, dtype=np.int64)) * dtype.itemsize
    if step_bytes <= 0:
        return None
    B = len(sequences)
    out = np.empty((B, T) + tail, dtype)
    out_lens = np.empty((B,), np.int32)
    row_ptrs = (ctypes.c_void_p * B)(
        *[s.ctypes.data_as(ctypes.c_void_p).value for s in sequences])
    lens64 = np.asarray(lens, np.int64)
    rc = lib.pack_rows(
        row_ptrs, lens64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        B, T, step_bytes, pad_elem.ctypes.data_as(ctypes.c_void_p),
        dtype.itemsize, out.ctypes.data_as(ctypes.c_void_p),
        out_lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        return None
    return out, out_lens


def pad_sequences(seqs: Sequence, dtype=None, pad_multiple: int = 8,
                  pad_value=0,
                  max_len: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """list of per-sequence arrays/lists -> (padded, lengths); max_len
    pins the padded length (the bucketing decorator uses this to bound
    the number of distinct shapes XLA sees)."""
    arrs = [np.asarray(s, dtype=dtype) for s in seqs]
    return LoDTensor(arrs).to_padded(pad_multiple, pad_value,
                                     max_len=max_len)


def create_lod_tensor(data, recursive_seq_lens=None, place=None) -> LoDTensor:
    """≙ fluid.create_lod_tensor (lod_tensor.py): data may be a list of
    sequences or flat ndarray + per-level lengths (every level is
    cumsum'd to offsets and forwarded — multi-level supported)."""
    if recursive_seq_lens is None:
        return LoDTensor(data)
    lod = [np.concatenate([[0], np.cumsum(lens)]).tolist()
           for lens in recursive_seq_lens]
    return LoDTensor.from_flat(np.asarray(data), lod)
