"""Python-side streaming metric accumulators.

≙ reference python/paddle/fluid/metrics.py: MetricBase, CompositeMetric,
Accuracy, ChunkEvaluator, EditDistance, DetectionMAP, Auc.
"""

from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Accuracy", "ChunkEvaluator",
           "EditDistance", "Auc"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.ravel(value)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """metrics.py ChunkEvaluator: micro-F1 over chunk counts."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.ravel(num_infer_chunks)[0])
        self.num_label_chunks += int(np.ravel(num_label_chunks)[0])
        self.num_correct_chunks += int(np.ravel(num_correct_chunks)[0])

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).ravel()
        self.total_distance += float(distances.sum())
        self.seq_num += int(np.ravel(seq_num)[0])
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data accumulated")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """metrics.py Auc: streaming ROC AUC over a threshold histogram."""

    def __init__(self, name=None, curve="ROC", num_thresholds=200):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).ravel()
        pos_score = preds[:, 1] if preds.ndim == 2 and preds.shape[1] >= 2 \
            else preds.ravel()
        kepsilon = 1e-7
        thresholds = [(i + 1) * 1.0 / (self._num_thresholds - 1)
                      for i in range(self._num_thresholds - 2)]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        for i, t in enumerate(thresholds):
            above = pos_score >= t
            self.tp_list[i] += int((above & (labels > 0)).sum())
            self.fp_list[i] += int((above & (labels <= 0)).sum())
            self.fn_list[i] += int((~above & (labels > 0)).sum())
            self.tn_list[i] += int((~above & (labels <= 0)).sum())

    def eval(self):
        epsilon = 1e-6
        tpr = self.tp_list / (self.tp_list + self.fn_list + epsilon)
        fpr = self.fp_list / (self.fp_list + self.tn_list + epsilon)
        return float(np.abs(np.trapezoid(tpr, fpr)))
