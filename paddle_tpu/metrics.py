"""Python-side streaming metric accumulators.

≙ reference python/paddle/fluid/metrics.py: MetricBase, CompositeMetric,
Accuracy, ChunkEvaluator, EditDistance, DetectionMAP, Auc.
"""

from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "CompositeMetric", "Accuracy", "ChunkEvaluator",
           "EditDistance", "Auc",
           "Precision", "Recall", "DetectionMAP"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.ravel(value)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no batches accumulated")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    """metrics.py ChunkEvaluator: micro-F1 over chunk counts."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.ravel(num_infer_chunks)[0])
        self.num_label_chunks += int(np.ravel(num_label_chunks)[0])
        self.num_correct_chunks += int(np.ravel(num_correct_chunks)[0])

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).ravel()
        self.total_distance += float(distances.sum())
        self.seq_num += int(np.ravel(seq_num)[0])
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data accumulated")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)


class Auc(MetricBase):
    """metrics.py Auc: streaming ROC AUC over a threshold histogram."""

    def __init__(self, name=None, curve="ROC", num_thresholds=200):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).ravel()
        pos_score = preds[:, 1] if preds.ndim == 2 and preds.shape[1] >= 2 \
            else preds.ravel()
        kepsilon = 1e-7
        thresholds = [(i + 1) * 1.0 / (self._num_thresholds - 1)
                      for i in range(self._num_thresholds - 2)]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        for i, t in enumerate(thresholds):
            above = pos_score >= t
            self.tp_list[i] += int((above & (labels > 0)).sum())
            self.fp_list[i] += int((above & (labels <= 0)).sum())
            self.fn_list[i] += int((~above & (labels > 0)).sum())
            self.tn_list[i] += int((~above & (labels <= 0)).sum())

    def eval(self):
        epsilon = 1e-6
        tpr = self.tp_list / (self.tp_list + self.fn_list + epsilon)
        fpr = self.fp_list / (self.fp_list + self.tn_list + epsilon)
        return float(np.abs(np.trapezoid(tpr, fpr)))


class Precision(MetricBase):
    """Binary precision accumulator (≙ fluid.metrics.Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).ravel()
        labels = np.asarray(labels).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels != 1)).sum())

    def eval(self):
        return self.tp / (self.tp + self.fp) if self.tp + self.fp else 0.0


class Recall(MetricBase):
    """Binary recall accumulator (≙ fluid.metrics.Recall)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).ravel()
        labels = np.asarray(labels).ravel()
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds != 1) & (labels == 1)).sum())

    def eval(self):
        return self.tp / (self.tp + self.fn) if self.tp + self.fn else 0.0


class DetectionMAP(MetricBase):
    """Mean average precision over detection results
    (≙ fluid.metrics.DetectionMAP / detection_map_op.cc, 11-point
    interpolated by default).

    update(detections, gts): detections = [N, 6] rows
    (label, score, x0, y0, x1, y1) with label -1 = padding (the dense
    multiclass_nms output for ONE image); gts = [G, 5] rows
    (label, x0, y0, x1, y1), all-zero rows = padding.
    """

    def __init__(self, name=None, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="11point"):
        super().__init__(name)
        self.overlap_threshold = overlap_threshold
        self.evaluate_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.reset()

    def reset(self):
        self._dets = []   # (label, score, matched) per detection
        self._n_gt = {}   # label -> count

    @staticmethod
    def _iou(a, b):
        ix0 = max(a[0], b[0]); iy0 = max(a[1], b[1])
        ix1 = min(a[2], b[2]); iy1 = min(a[3], b[3])
        inter = max(ix1 - ix0, 0.0) * max(iy1 - iy0, 0.0)
        ua = max(a[2] - a[0], 0) * max(a[3] - a[1], 0)
        ub = max(b[2] - b[0], 0) * max(b[3] - b[1], 0)
        return inter / (ua + ub - inter) if ua + ub - inter > 0 else 0.0

    def update(self, detections, gts):
        """gts rows: (label, x0, y0, x1, y1[, difficult]). When
        evaluate_difficult=False (the VOC convention), difficult ground
        truths are excluded from the GT count and detections matching
        them count as neither TP nor FP."""
        detections = np.asarray(detections, np.float64)
        gts = np.asarray(gts, np.float64)
        gts = [g for g in gts if np.abs(g[1:5]).sum() > 0]
        difficult = [len(g) > 5 and g[5] > 0 for g in gts]
        for g, dif in zip(gts, difficult):
            if self.evaluate_difficult or not dif:
                self._n_gt[int(g[0])] = self._n_gt.get(int(g[0]), 0) + 1
        used = [False] * len(gts)
        dets = [d for d in detections if d[0] >= 0]
        dets.sort(key=lambda d: -d[1])
        for d in dets:
            lbl = int(d[0])
            best, best_i = 0.0, -1
            for i, g in enumerate(gts):
                if int(g[0]) != lbl or used[i]:
                    continue
                iou = self._iou(d[2:6], g[1:5])
                if iou > best:
                    best, best_i = iou, i
            matched = best >= self.overlap_threshold and best_i >= 0
            if matched:
                used[best_i] = True
                if not self.evaluate_difficult and difficult[best_i]:
                    continue  # ignored: neither TP nor FP
            self._dets.append((lbl, float(d[1]), matched))

    def eval(self):
        aps = []
        for lbl, n_gt in self._n_gt.items():
            rows = sorted((d for d in self._dets if d[0] == lbl),
                          key=lambda d: -d[1])
            tp = np.cumsum([1.0 if m else 0.0 for _, _, m in rows])
            fp = np.cumsum([0.0 if m else 1.0 for _, _, m in rows])
            if len(rows) == 0:
                aps.append(0.0)
                continue
            recall = tp / max(n_gt, 1)
            precision = tp / np.maximum(tp + fp, 1e-12)
            if self.ap_version == "11point":
                ap = np.mean([precision[recall >= t].max()
                              if (recall >= t).any() else 0.0
                              for t in np.linspace(0, 1, 11)])
            else:  # "integral"
                ap = 0.0
                prev_r = 0.0
                for p, r in zip(precision, recall):
                    ap += p * (r - prev_r)
                    prev_r = r
            aps.append(float(ap))
        return float(np.mean(aps)) if aps else 0.0
