"""Benchmark + book model zoo.

≙ reference benchmark/fluid/models/{mnist,resnet,vgg,stacked_dynamic_lstm,
machine_translation}.py — the five north-star configs (BASELINE.md) — plus
book models with no benchmark config (label_semantic_roles) and the
transformer LM showpiece.
"""

from . import mnist, resnet, vgg

__all__ = ["mnist", "resnet", "vgg", "get_model"]


def get_model(name: str):
    import importlib
    mod = importlib.import_module("paddle_tpu.models." + name)
    return mod.get_model
