"""Semantic role labeling: 8-feature deep bidirectional LSTM + CRF.

≙ reference tests/book/test_label_semantic_roles.py (db_lstm, :51-115):
six context-window word slots + predicate + mark are embedded (the six
word slots SHARE one embedding table, param 'emb'), mixed into a hidden
layer by per-slot tanh fc's summed together, then an 8-deep stack of
alternating forward/backward LSTMs with direct edges (each level sums a
projection of the previous mix and the previous LSTM), ending in a
linear-chain CRF over the label vocabulary (conll05 data).

All sequence slots are ragged (lod_level=1); every LSTM runs as one
lax.scan over the padded [B, T, ...] batch with length masking.
"""

from __future__ import annotations

from .. import layers
from ..param_attr import ParamAttr

WORD_SLOTS = ["word_data", "ctx_n2_data", "ctx_n1_data", "ctx_0_data",
              "ctx_p1_data", "ctx_p2_data"]
# feeder slot order ≙ the reference's feed_list
# (test_label_semantic_roles.py:170-173)
FEED_ORDER = WORD_SLOTS + ["verb_data", "mark_data", "target"]


def db_lstm(word_dict_len, label_dict_len, pred_dict_len, word_dim=32,
            mark_dim=5, hidden_dim=512, depth=8, mark_dict_len=2,
            embedding_trainable=False):
    """Build the feature network; returns the CRF-input emission scores
    [B, T, label_dict_len] (≙ db_lstm, test_label_semantic_roles.py:51)."""
    word_slots = [layers.data(n, [1], dtype="int64", lod_level=1)
                  for n in WORD_SLOTS]
    predicate = layers.data("verb_data", [1], dtype="int64", lod_level=1)
    mark = layers.data("mark_data", [1], dtype="int64", lod_level=1)

    predicate_emb = layers.embedding(predicate, [pred_dict_len, word_dim],
                                     param_attr=ParamAttr(name="vemb"))
    mark_emb = layers.embedding(mark, [mark_dict_len, mark_dim])
    # the six word-feature slots share one table (param 'emb'), frozen by
    # default as in the reference (it is loaded from pretrained wordvecs)
    emb_layers = [layers.embedding(
        w, [word_dict_len, word_dim],
        param_attr=ParamAttr(name="emb", trainable=embedding_trainable))
        for w in word_slots]
    emb_layers += [predicate_emb, mark_emb]

    hidden_0 = layers.sums([layers.fc(emb, size=hidden_dim, act="tanh")
                            for emb in emb_layers])
    # size = 4*units (fluid convention): the reference passes
    # size=hidden_dim, so each LSTM has hidden_dim/4 units
    lstm_0, _ = layers.dynamic_lstm(hidden_0, size=hidden_dim,
                                    candidate_activation="relu",
                                    gate_activation="sigmoid",
                                    cell_activation="sigmoid",
                                    use_peepholes=True)

    # stacked L/R LSTMs with direct edges
    input_tmp = [hidden_0, lstm_0]
    for i in range(1, depth):
        mix_hidden = layers.sums([
            layers.fc(input_tmp[0], size=hidden_dim, act="tanh"),
            layers.fc(input_tmp[1], size=hidden_dim, act="tanh")])
        lstm, _ = layers.dynamic_lstm(mix_hidden, size=hidden_dim,
                                      candidate_activation="relu",
                                      gate_activation="sigmoid",
                                      cell_activation="sigmoid",
                                      use_peepholes=True,
                                      is_reverse=(i % 2 == 1))
        input_tmp = [mix_hidden, lstm]

    feature_out = layers.sums([
        layers.fc(input_tmp[0], size=label_dict_len, act="tanh"),
        layers.fc(input_tmp[1], size=label_dict_len, act="tanh")])
    return feature_out


def train_net(word_dict_len, label_dict_len, pred_dict_len, word_dim=32,
              mark_dim=5, hidden_dim=512, depth=8, mix_hidden_lr=1e-3,
              embedding_trainable=False):
    """≙ train() topology (test_label_semantic_roles.py:119-146): db_lstm
    emissions + linear_chain_crf cost, sharing the 'crfw' transition with
    crf_decoding. Returns (avg_cost, crf_decode path)."""
    feature_out = db_lstm(word_dict_len, label_dict_len, pred_dict_len,
                          word_dim=word_dim, mark_dim=mark_dim,
                          hidden_dim=hidden_dim, depth=depth,
                          embedding_trainable=embedding_trainable)
    target = layers.data("target", [1], dtype="int64", lod_level=1)
    crf_cost = layers.linear_chain_crf(
        feature_out, target,
        param_attr=ParamAttr(name="crfw", learning_rate=mix_hidden_lr))
    avg_cost = layers.mean(crf_cost)
    crf_decode = layers.crf_decoding(feature_out,
                                     param_attr=ParamAttr(name="crfw"))
    return avg_cost, crf_decode
