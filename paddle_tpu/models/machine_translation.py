"""Seq2seq with attention (≙ benchmark/fluid/models/machine_translation.py
seq_to_seq_net, and the book's machine-translation chapter).

Training: bi-LSTM encoder -> Bahdanau-style additive attention decoder built
inside a DynamicRNN (one lax.scan after lowering). Generation: beam search
on a StaticRNN over `max_length` steps with dense [B, W] beam lanes — the
reference's While + LoD-candidate machinery (beam_search_op.cc) becomes a
fixed-shape scan + top_k, the TPU-idiomatic formulation. Attention uses
broadcast adds over the padded time axis, so no op ever needs the runtime
sequence length as a static attribute.

All parameters carry explicit names so the generation program (a separate
Program) shares weights with the training program through the scope.
"""

from __future__ import annotations

from .. import layers, optimizer
from ..param_attr import ParamAttr


def _pa(name):
    return ParamAttr(name=name)


def bi_lstm_encoder(input_seq, gate_size, prefix="enc"):
    """Forward + reverse fused LSTM over pre-projected inputs
    (≙ machine_translation.py bi_lstm_encoder)."""
    fwd_proj = layers.fc(input=input_seq, size=gate_size * 4, act=None,
                         bias_attr=False, param_attr=_pa(prefix + "_fw_proj"))
    forward, _ = layers.dynamic_lstm(fwd_proj, size=gate_size * 4,
                                     use_peepholes=False,
                                     param_attr=_pa(prefix + "_fw_w"),
                                     bias_attr=_pa(prefix + "_fw_b"))
    rev_proj = layers.fc(input=input_seq, size=gate_size * 4, act=None,
                         bias_attr=False, param_attr=_pa(prefix + "_rv_proj"))
    reversed_, _ = layers.dynamic_lstm(rev_proj, size=gate_size * 4,
                                       is_reverse=True, use_peepholes=False,
                                       param_attr=_pa(prefix + "_rv_w"),
                                       bias_attr=_pa(prefix + "_rv_b"))
    return forward, reversed_


def lstm_step(x_t, hidden_prev, cell_prev, size, nfd=1, prefix="dec_cell"):
    """Composed LSTM cell from fc primitives (≙ reference lstm_step).
    `nfd` = num_flatten_dims for the inner fcs (2 when beams ride a lane
    axis: [B, W, D] inputs)."""
    def linear(inputs, tag):
        return layers.fc(input=inputs, size=size, num_flatten_dims=nfd,
                         bias_attr=_pa(f"{prefix}_{tag}_b"),
                         param_attr=_pa(f"{prefix}_{tag}_w"))

    forget_g = layers.sigmoid(linear([hidden_prev, x_t], "f"))
    input_g = layers.sigmoid(linear([hidden_prev, x_t], "i"))
    output_g = layers.sigmoid(linear([hidden_prev, x_t], "o"))
    cell_tilde = layers.tanh(linear([hidden_prev, x_t], "c"))
    cell_t = layers.sums(input=[
        layers.elementwise_mul(x=forget_g, y=cell_prev),
        layers.elementwise_mul(x=input_g, y=cell_tilde)])
    hidden_t = layers.elementwise_mul(x=output_g, y=layers.tanh(x=cell_t))
    return hidden_t, cell_t


def simple_attention(encoder_vec, encoder_proj, decoder_state, decoder_size,
                     prefix="att"):
    """Additive attention e_t = v·tanh(enc_proj_t + W_s s) over the padded
    time axis (≙ reference simple_attention: its concat+fc-of-size-1 is the
    same family with the weight split into enc_proj's fc and W_s).

    decoder_state [B, D] -> context [B, C]."""
    state_proj = layers.fc(input=decoder_state, size=decoder_size,
                           bias_attr=False, param_attr=_pa(prefix + "_sp"))
    summed = layers.elementwise_add(encoder_proj,
                                    layers.unsqueeze(state_proj, [1]))
    e = layers.fc(input=layers.tanh(summed), size=1, num_flatten_dims=2,
                  bias_attr=False, param_attr=_pa(prefix + "_e"))  # [B,T,1]
    weights = layers.sequence_softmax(layers.lod_reset(
        layers.squeeze(e, [2]), y=encoder_proj))                   # [B,T]
    context = layers.reduce_sum(
        layers.elementwise_mul(encoder_vec, layers.unsqueeze(weights, [2])),
        dim=1)                                                     # [B,C]
    return context


def beam_attention(encoder_vec, encoder_proj, decoder_state, decoder_size,
                   src_mask, prefix="att"):
    """Same attention with a beam lane: decoder_state [B, W, D], encoder
    vars [B, T, .], src_mask [B, T] -> context [B, W, C]. Pure broadcast —
    the encoder is never tiled per beam."""
    state_proj = layers.fc(input=decoder_state, size=decoder_size,
                           num_flatten_dims=2, bias_attr=False,
                           param_attr=_pa(prefix + "_sp"))          # [B,W,D]
    summed = layers.elementwise_add(
        layers.unsqueeze(encoder_proj, [1]),       # [B,1,T,D]
        layers.unsqueeze(state_proj, [2]))         # [B,W,1,D] -> [B,W,T,D]
    e = layers.fc(input=layers.tanh(summed), size=1, num_flatten_dims=3,
                  bias_attr=False, param_attr=_pa(prefix + "_e"))  # [B,W,T,1]
    e = layers.squeeze(e, [3])                                     # [B,W,T]
    neg = layers.scale(src_mask, scale=1e9, bias=-1e9)  # 0 valid, -1e9 pad
    e = layers.elementwise_add(e, layers.unsqueeze(neg, [1]))
    weights = layers.softmax(e)                                    # [B,W,T]
    context = layers.reduce_sum(
        layers.elementwise_mul(layers.unsqueeze(encoder_vec, [1]),
                               layers.unsqueeze(weights, [3])), dim=2)
    return context                                                 # [B,W,C]


def encoder_net(src_word_idx, source_dict_dim, embedding_dim, encoder_size,
                decoder_size):
    src_embedding = layers.embedding(
        input=src_word_idx, size=[source_dict_dim, embedding_dim],
        dtype="float32", param_attr=_pa("src_emb"))
    src_forward, src_reversed = bi_lstm_encoder(src_embedding, encoder_size)
    encoded_vector = layers.lod_reset(
        layers.concat([src_forward, src_reversed], axis=2), y=src_forward)
    encoded_proj = layers.fc(input=encoded_vector, size=decoder_size,
                             bias_attr=False, param_attr=_pa("enc_proj"))
    backward_first = layers.sequence_pool(src_reversed, "first")
    decoder_boot = layers.fc(input=backward_first, size=decoder_size,
                             bias_attr=False, act="tanh",
                             param_attr=_pa("dec_boot"))
    return encoded_vector, encoded_proj, decoder_boot


def train_net(source_dict_dim=30000, target_dict_dim=30000, embedding_dim=512,
              encoder_size=512, decoder_size=512, learning_rate=2e-4,
              with_optimizer=True):
    """Build the training loss. Feeds: source_sequence, target_sequence,
    label_sequence (next-word targets), all [B, T] int64 sequences."""
    src = layers.data(name="source_sequence", shape=[1], dtype="int64",
                      lod_level=1)
    encoder_vec, encoder_proj, decoder_boot = encoder_net(
        src, source_dict_dim, embedding_dim, encoder_size, decoder_size)

    trg = layers.data(name="target_sequence", shape=[1], dtype="int64",
                      lod_level=1)
    trg_embedding = layers.embedding(
        input=trg, size=[target_dict_dim, embedding_dim], dtype="float32",
        param_attr=_pa("trg_emb"))

    rnn = layers.DynamicRNN()
    with rnn.block():
        x = rnn.step_input(trg_embedding)
        encoder_vec_s = rnn.static_input(encoder_vec)
        encoder_proj_s = rnn.static_input(encoder_proj)
        hidden_mem = rnn.memory(init=decoder_boot, need_reorder=True)
        cell_mem = rnn.memory(value=0.0, shape=[decoder_size])
        context = simple_attention(encoder_vec_s, encoder_proj_s, hidden_mem,
                                   decoder_size)
        decoder_inputs = layers.concat([context, x], axis=1)
        h, c = lstm_step(decoder_inputs, hidden_mem, cell_mem, decoder_size)
        rnn.update_memory(hidden_mem, h)
        rnn.update_memory(cell_mem, c)
        out = layers.fc(input=h, size=target_dict_dim, act="softmax",
                        param_attr=_pa("dec_out_w"),
                        bias_attr=_pa("dec_out_b"))
        rnn.output(out)

    prediction = rnn()                          # [B, T, V] seq-marked
    label = layers.data(name="label_sequence", shape=[1], dtype="int64",
                        lod_level=1)
    # masked sequence cross-entropy: per-step CE zeroed beyond each length,
    # normalized by total token count (≙ the reference's LoD-packed mean)
    from ..core.program import default_main_program
    seq_len = default_main_program().global_block.var(trg.seq_len_var)
    ce = layers.cross_entropy(input=prediction, label=label)      # [B, T, 1]
    mask = layers.sequence_mask(seq_len, maxlen_ref=prediction)   # [B, T]
    ce = layers.elementwise_mul(layers.squeeze(ce, [2]), mask)
    avg_cost = layers.elementwise_div(
        layers.reduce_sum(ce), layers.reduce_sum(mask))
    if with_optimizer:
        opt = optimizer.AdamOptimizer(learning_rate=learning_rate)
        opt.minimize(avg_cost)
    return avg_cost, prediction, ["source_sequence", "target_sequence",
                                  "label_sequence"]


def decode_net(source_dict_dim=30000, target_dict_dim=30000, embedding_dim=512,
               encoder_size=512, decoder_size=512, beam_size=4, max_length=32,
               start_id=0, end_id=1):
    """Beam-search generation program (≙ seq_to_seq_net is_generating=True).

    Returns (sentence_ids [B, W, max_length], sentence_scores [B, W],
    feed names). Runs max_length fixed steps; finished beams are frozen by
    the beam_search op rather than exiting early (static shapes for XLA)."""
    src = layers.data(name="source_sequence", shape=[1], dtype="int64",
                      lod_level=1)
    encoder_vec, encoder_proj, decoder_boot = encoder_net(
        src, source_dict_dim, embedding_dim, encoder_size, decoder_size)
    W = beam_size

    from ..core.program import default_main_program
    src_len = default_main_program().global_block.var(src.seq_len_var)
    src_mask = layers.sequence_mask(src_len, maxlen_ref=encoder_vec)

    boot = layers.expand(layers.unsqueeze(decoder_boot, [1]),
                         [1, W, 1])                          # [B, W, D]
    cell_init = layers.fill_constant_batch_size_like(
        boot, [-1, W, decoder_size], "float32", 0.0)
    # scores init: beam 0 live at 0.0, others -1e9 so step 1 diversifies
    zeros_idx = layers.fill_constant_batch_size_like(
        decoder_boot, [-1, 1], "int64", 0.0)
    ones_row = layers.fill_constant_batch_size_like(
        decoder_boot, [-1, W], "float32", 1.0)
    scores_init = layers.scale(
        layers.elementwise_sub(layers.one_hot(zeros_idx, W), ones_row),
        scale=1e9)                                           # [B, W]
    dummy_steps = layers.fill_constant_batch_size_like(
        decoder_boot, [-1, max_length, 1], "float32", 0.0)

    rnn = layers.StaticRNN()
    with rnn.step():
        rnn.step_input(dummy_steps)
        pre_ids = rnn.memory(shape=[W], init_value=float(start_id),
                             dtype="int64")                  # [B, W]
        pre_scores = rnn.memory(init=scores_init)            # [B, W]
        hidden_mem = rnn.memory(init=boot)                   # [B, W, D]
        cell_mem = rnn.memory(init=cell_init)

        # ids carry fluid's trailing-1 convention so lookup_table's squeeze
        # yields [B, W, E] for any W (including beam_size=1)
        prev_emb = layers.embedding(
            input=layers.unsqueeze(pre_ids, [2]),
            size=[target_dict_dim, embedding_dim],
            dtype="float32", param_attr=_pa("trg_emb"))      # [B, W, E]
        context = beam_attention(rnn.static_input(encoder_vec),
                                 rnn.static_input(encoder_proj),
                                 hidden_mem, decoder_size,
                                 rnn.static_input(src_mask))
        decoder_inputs = layers.concat([context, prev_emb], axis=2)
        h, c = lstm_step(decoder_inputs, hidden_mem, cell_mem, decoder_size,
                         nfd=2)
        probs = layers.fc(input=h, size=target_dict_dim, num_flatten_dims=2,
                          act="softmax", param_attr=_pa("dec_out_w"),
                          bias_attr=_pa("dec_out_b"))        # [B, W, V]
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_scores, probs, beam_size=W, end_id=end_id)
        h_sel = layers.batch_gather(h, parent)
        c_sel = layers.batch_gather(c, parent)
        rnn.update_memory(pre_ids, sel_ids)
        rnn.update_memory(pre_scores, sel_scores)
        rnn.update_memory(hidden_mem, h_sel)
        rnn.update_memory(cell_mem, c_sel)
        rnn.output(sel_ids, parent, sel_scores)

    ids_steps, parent_steps, scores_steps = rnn()   # each [B, T, W]
    sentence_ids, sentence_scores = layers.beam_search_decode(
        ids_steps, parent_steps, scores_steps, beam_size=W, end_id=end_id)
    return sentence_ids, sentence_scores, ["source_sequence"]


def get_model(source_dict_dim=30000, target_dict_dim=30000, embedding_dim=512,
              encoder_size=512, decoder_size=512, learning_rate=2e-4):
    """BASELINE config 5 entry (≙ machine_translation.get_model)."""
    avg_cost, prediction, feeds = train_net(
        source_dict_dim, target_dict_dim, embedding_dim, encoder_size,
        decoder_size, learning_rate)
    return avg_cost, prediction, feeds
