"""LeNet-5-class MNIST CNN (≙ benchmark/fluid/models/mnist.py cnn_model):
conv5x5x20-pool2 → conv5x5x50-pool2 → fc10 softmax."""

from __future__ import annotations

from .. import layers, nets, optimizer


def cnn_model(data):
    conv_pool_1 = nets.simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    predict = layers.fc(input=conv_pool_2, size=10, act="softmax")
    return predict


def get_model(batch_size: int = 128, use_adam: bool = True):
    """Build train program; returns (loss, acc, predict, feed names)."""
    images = layers.data("pixel", [1, 28, 28])
    label = layers.data("label", [1], dtype="int64")
    predict = cnn_model(images)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    batch_acc = layers.accuracy(input=predict, label=label)
    opt = optimizer.AdamOptimizer(learning_rate=0.001) if use_adam else \
        optimizer.MomentumOptimizer(learning_rate=0.01, momentum=0.9)
    opt.minimize(avg_cost)
    return avg_cost, batch_acc, predict, ["pixel", "label"]
