"""ResNet for ImageNet/cifar10 (≙ benchmark/fluid/models/resnet.py):
conv-bn blocks, basic (18/34) and bottleneck (50/101/152) residuals.
This is the north-star model (BASELINE.md: ResNet-50 ≥45% MFU)."""

from __future__ import annotations

import os

from .. import layers, optimizer


def _use_fused_block() -> bool:
    """Emit the one-op fused bottleneck (layers.fused_bottleneck) for
    stride-1 rest blocks — in BOTH train and inference graphs, so the two
    share parameter names (checkpoints interchange; the op's is_test attr
    selects running-stat math).  The op lowers to the Pallas chain on a
    single TPU device when PT_FUSED_BLOCK=always and to the identical
    op-by-op composition otherwise (ops/fused_ops.py), so this changes
    kernels, not semantics.  PT_FUSED_BLOCK=never reverts to the op-by-op
    graph."""
    return os.environ.get("PT_FUSED_BLOCK", "auto") not in ("0", "never")


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False):
    conv = layers.conv2d(input=input, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None, is_test)
    return input


def basicblock(input, ch_out, stride, is_test=False):
    short = shortcut(input, ch_out, stride, is_test)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test)
    return layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_out, stride, is_test=False):
    if stride == 1 and input.shape[1] == ch_out * 4 and _use_fused_block():
        return layers.fused_bottleneck(input, ch_out, is_test=is_test)
    short = shortcut(input, ch_out * 4, stride, is_test)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None, is_test=is_test)
    return layers.elementwise_add(x=short, y=conv3, act="relu")


def layer_warp(block_func, input, ch_out, count, stride, is_test=False):
    res_out = block_func(input, ch_out, stride, is_test)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_test)
    return res_out


_CFG = {
    18: ([2, 2, 2, 1], basicblock),
    34: ([3, 4, 6, 3], basicblock),
    50: ([3, 4, 6, 3], bottleneck),
    101: ([3, 4, 23, 3], bottleneck),
    152: ([3, 8, 36, 3], bottleneck),
}


def resnet_imagenet(input, class_dim, depth=50, is_test=False, head_act="softmax"):
    stages, block_func = _CFG[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_test=is_test)
    pool1 = layers.pool2d(input=conv1, pool_type="max", pool_size=3,
                          pool_stride=2, pool_padding=1)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_test)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_test)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_test)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_test)
    pool2 = layers.pool2d(input=res4, pool_size=7, pool_type="avg",
                          global_pooling=True)
    out = layers.fc(input=pool2, size=class_dim, act=head_act)
    return out


def resnet_cifar10(input, class_dim, depth=32, is_test=False, head_act="softmax"):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_test=is_test)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_test)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_test)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_test)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                         global_pooling=True)
    out = layers.fc(input=pool, size=class_dim, act=head_act)
    return out


def get_model(data_set: str = "flowers", depth: int = 50,
              learning_rate: float = 0.01, is_test: bool = False,
              dtype: str = "float32", fused_xent: bool = False):
    """fused_xent: emit logits + softmax_with_cross_entropy (numerically
    stable in bf16; the fused path of softmax_with_cross_entropy_op.cu)."""
    if data_set == "cifar10":
        class_dim, shape = 10, [3, 32, 32]
        model = resnet_cifar10
        depth = 32 if depth == 50 else depth
    else:
        class_dim = 102 if data_set == "flowers" else 1000
        shape = [3, 224, 224]
        model = resnet_imagenet

    input = layers.data("data", shape, dtype=dtype)
    label = layers.data("label", [1], dtype="int64")
    if fused_xent:
        logits = model(input, class_dim, depth=depth, is_test=is_test,
                       head_act=None)
        predict = layers.softmax(logits)
        cost = layers.softmax_with_cross_entropy(logits, label)
    else:
        predict = model(input, class_dim, depth=depth, is_test=is_test)
        cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    batch_acc = layers.accuracy(input=predict, label=label)
    opt = optimizer.MomentumOptimizer(learning_rate=learning_rate, momentum=0.9)
    opt.minimize(avg_cost)
    return avg_cost, batch_acc, predict, ["data", "label"]
