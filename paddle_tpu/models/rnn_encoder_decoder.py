"""Seq2seq WITHOUT attention: bi-LSTM encoder, plain LSTM decoder.

≙ reference tests/book/test_rnn_encoder_decoder.py (bi_lstm_encoder :40,
lstm_step :62, lstm_decoder_without_attention :85, seq_to_seq_net :115):
the encoder's last-forward/first-backward states concatenate into one
fixed context vector fed to every decoder step (no attention — the
attention variant is models/machine_translation.py). The decoder is a
hand-built LSTM cell inside DynamicRNN (per-step fc gates), exercising
the sub-block-to-lax.scan lowering rather than the fused kernel.
"""

from __future__ import annotations

from .. import layers

USE_PEEPHOLES = False


def bi_lstm_encoder(input_seq, hidden_size):
    """:40 — returns (forward last step, backward first step)."""
    fwd_proj = layers.fc(input=input_seq, size=hidden_size * 4,
                         bias_attr=True)
    forward, _ = layers.dynamic_lstm(fwd_proj, size=hidden_size * 4,
                                     use_peepholes=USE_PEEPHOLES)
    bwd_proj = layers.fc(input=input_seq, size=hidden_size * 4,
                         bias_attr=True)
    backward, _ = layers.dynamic_lstm(bwd_proj, size=hidden_size * 4,
                                      is_reverse=True,
                                      use_peepholes=USE_PEEPHOLES)
    return (layers.sequence_last_step(forward),
            layers.sequence_first_step(backward))


def lstm_step(x_t, hidden_t_prev, cell_t_prev, size):
    """:62 — an LSTM cell composed from fc gates (the reference notes it
    predates lstm_unit_op; kept composed for book parity)."""
    def linear(inputs):
        return layers.fc(input=inputs, size=size, bias_attr=True)

    forget_gate = layers.sigmoid(linear([hidden_t_prev, x_t]))
    input_gate = layers.sigmoid(linear([hidden_t_prev, x_t]))
    output_gate = layers.sigmoid(linear([hidden_t_prev, x_t]))
    cell_tilde = layers.tanh(linear([hidden_t_prev, x_t]))
    cell_t = layers.sums([layers.elementwise_mul(forget_gate, cell_t_prev),
                          layers.elementwise_mul(input_gate, cell_tilde)])
    hidden_t = layers.elementwise_mul(output_gate, layers.tanh(cell_t))
    return hidden_t, cell_t


def lstm_decoder_without_attention(target_embedding, decoder_boot, context,
                                   decoder_size, target_dict_dim):
    """:85 — every step sees the SAME encoder context (static input)."""
    rnn = layers.DynamicRNN()
    cell_init = layers.fill_constant_batch_size_like(
        input=decoder_boot, value=0.0, shape=[-1, decoder_size],
        dtype="float32")
    cell_init.stop_gradient = False
    with rnn.block():
        current_word = rnn.step_input(target_embedding)
        ctx = rnn.static_input(context)
        hidden_mem = rnn.memory(init=decoder_boot)
        cell_mem = rnn.memory(init=cell_init)
        decoder_inputs = layers.concat([ctx, current_word], axis=1)
        h, c = lstm_step(decoder_inputs, hidden_mem, cell_mem, decoder_size)
        rnn.update_memory(hidden_mem, h)
        rnn.update_memory(cell_mem, c)
        out = layers.fc(input=h, size=target_dict_dim, bias_attr=True,
                        act="softmax")
        rnn.output(out)
    return rnn()


def seq_to_seq_net(source_dict_dim=30000, target_dict_dim=30000,
                   embedding_dim=512, encoder_size=512, decoder_size=512):
    """:115 — returns (avg_cost, prediction); feeds: source_sequence,
    target_sequence, label_sequence (all ragged int64)."""
    src = layers.data("source_sequence", [1], dtype="int64", lod_level=1)
    src_emb = layers.embedding(src, [source_dict_dim, embedding_dim])
    fwd_last, bwd_first = bi_lstm_encoder(src_emb, encoder_size)
    encoded = layers.concat([fwd_last, bwd_first], axis=1)
    decoder_boot = layers.fc(input=bwd_first, size=decoder_size,
                             bias_attr=False, act="tanh")
    trg = layers.data("target_sequence", [1], dtype="int64", lod_level=1)
    trg_emb = layers.embedding(trg, [target_dict_dim, embedding_dim])
    prediction = lstm_decoder_without_attention(
        trg_emb, decoder_boot, encoded, decoder_size, target_dict_dim)
    label = layers.data("label_sequence", [1], dtype="int64", lod_level=1)
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    return avg_cost, prediction
