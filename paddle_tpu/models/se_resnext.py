"""SE-ResNeXt: grouped-convolution ResNeXt bottlenecks with
squeeze-excitation channel gating.

≙ reference test_parallel_executor_seresnext.py (SE_ResNeXt50Small,
squeeze_excitation :21, bottleneck_block :66) — the second model named in
the BASELINE north-star metric ("images/sec/chip + MFU on
ResNet-50/SE-ResNeXt"). Grouped 3x3 convs lower to XLA's
feature_group_count path (one MXU-batched conv, no per-group loop); the
SE gate is two tiny fc's on globally-pooled channels.
"""

from __future__ import annotations

from .. import layers


def squeeze_excitation(input, num_channels, reduction_ratio):
    """test_parallel_executor_seresnext.py:21: global-avg-pool the spatial
    dims, bottleneck fc (relu) then expand fc (sigmoid), scale channels."""
    shape = input.shape
    reshaped = layers.reshape(input, [-1, shape[1], shape[2] * shape[3]])
    pool = layers.reduce_mean(reshaped, dim=2)          # [B, C]
    squeeze = layers.fc(pool, size=max(num_channels // reduction_ratio, 1),
                        act="relu")
    excitation = layers.fc(squeeze, size=num_channels, act="sigmoid")
    return layers.elementwise_mul(input, excitation, axis=0)


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None):
    conv = layers.conv2d(input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         bias_attr=False)
    return layers.batch_norm(conv, act=act, momentum=0.1)


def shortcut(input, ch_out, stride):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        filter_size = 1 if stride == 1 else 3
        return conv_bn_layer(input, ch_out, filter_size, stride)
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio):
    """1x1 reduce -> grouped 3x3 -> 1x1 -> SE gate, residual add.
    The reference halves the first 1x1's width to cut compute
    (test_parallel_executor_seresnext.py:66)."""
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu")
    conv1 = conv_bn_layer(conv0, num_filters * 2, 3, stride=stride,
                          groups=cardinality, act="relu")
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = shortcut(input, num_filters * 2, stride)
    return layers.elementwise_add(short, scale, act="relu")


def se_resnext_net(img, class_dim=1000, cardinality=32, reduction_ratio=16,
                   depth=(3, 4, 6, 3), num_filters=(128, 256, 512, 1024),
                   stem_filters=16, dropout_prob=0.2):
    """The SE_ResNeXt-50 trunk (small stem variant, per the reference
    test model). Returns softmax predictions [B, class_dim]."""
    conv = conv_bn_layer(img, stem_filters, 3, stride=2, act="relu")
    conv = conv_bn_layer(conv, stem_filters, 3, stride=1, act="relu")
    conv = conv_bn_layer(conv, stem_filters, 3, stride=1, act="relu")
    conv = layers.pool2d(conv, pool_size=3, pool_stride=2, pool_padding=1,
                         pool_type="max")
    for block, d in enumerate(depth):
        for i in range(d):
            conv = bottleneck_block(
                conv, num_filters[block],
                stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality, reduction_ratio=reduction_ratio)
    shape = conv.shape
    reshaped = layers.reshape(conv, [-1, shape[1], shape[2] * shape[3]])
    pool = layers.reduce_mean(reshaped, dim=2)
    dropped = layers.dropout(pool, dropout_prob=dropout_prob)
    return layers.fc(dropped, size=class_dim, act="softmax")


def get_model(batch_size=None, class_dim=1000, image_size=224,
              cardinality=32, reduction_ratio=16, depth=(3, 4, 6, 3),
              num_filters=(128, 256, 512, 1024), dropout_prob=0.2,
              dtype="float32"):
    """Feedable training net (the reference test hardwires fill_constant
    inputs; real feeds are strictly more capable). Returns
    (avg_cost, accuracy, predictions, feed names)."""
    img = layers.data("data", [3, image_size, image_size], dtype=dtype)
    label = layers.data("label", [1], dtype="int64")
    predict = se_resnext_net(img, class_dim=class_dim,
                             cardinality=cardinality,
                             reduction_ratio=reduction_ratio, depth=depth,
                             num_filters=num_filters,
                             dropout_prob=dropout_prob)
    cost = layers.cross_entropy(predict, label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(predict, label)
    return avg_cost, acc, predict, ["data", "label"]
