"""Stacked dynamic LSTM text classifier
(≙ benchmark/fluid/models/stacked_dynamic_lstm.py — BASELINE config 4).

Mirrors the reference exactly: embedding → tanh fc → hand-built LSTM cell
inside DynamicRNN (per-step fc gates, sums, sigmoid/tanh) → last-step pool →
fc softmax. The DynamicRNN sub-block lowers to one lax.scan (ops/rnn_ops.py).
A fused alternative (`use_fused=True`) uses the dynamic_lstm op instead —
the production path on TPU.
"""

from __future__ import annotations

from .. import layers, optimizer


def lstm_net(data, dict_size: int, lstm_size: int = 512, emb_dim: int = 512,
             use_fused: bool = False):
    sentence = layers.embedding(input=data, size=[dict_size, emb_dim])
    sentence = layers.fc(input=sentence, size=lstm_size, act="tanh")

    if use_fused:
        proj = layers.fc(input=sentence, size=lstm_size * 4)
        hidden, _ = layers.dynamic_lstm(proj, size=lstm_size * 4,
                                        use_peepholes=False)
        return layers.sequence_pool(hidden, "last")

    rnn = layers.DynamicRNN()
    with rnn.block():
        word = rnn.step_input(sentence)
        prev_hidden = rnn.memory(value=0.0, shape=[lstm_size])
        prev_cell = rnn.memory(value=0.0, shape=[lstm_size])

        def gate_common(ipt, hidden, size):
            gate0 = layers.fc(input=ipt, size=size, bias_attr=True)
            gate1 = layers.fc(input=hidden, size=size, bias_attr=False)
            return layers.sums(input=[gate0, gate1])

        forget_gate = layers.sigmoid(gate_common(word, prev_hidden, lstm_size))
        input_gate = layers.sigmoid(gate_common(word, prev_hidden, lstm_size))
        output_gate = layers.sigmoid(gate_common(word, prev_hidden, lstm_size))
        cell_gate = layers.tanh(gate_common(word, prev_hidden, lstm_size))

        cell = layers.sums(input=[
            layers.elementwise_mul(x=forget_gate, y=prev_cell),
            layers.elementwise_mul(x=input_gate, y=cell_gate),
        ])
        hidden = layers.elementwise_mul(x=output_gate, y=layers.tanh(x=cell))

        rnn.update_memory(prev_cell, cell)
        rnn.update_memory(prev_hidden, hidden)
        rnn.output(hidden)

    return layers.sequence_pool(rnn(), "last")


def get_model(dict_size: int = 30000, lstm_size: int = 512,
              emb_dim: int = 512, use_fused: bool = False):
    data = layers.data(name="words", shape=[1], lod_level=1, dtype="int64")
    label = layers.data(name="label", shape=[1], dtype="int64")
    last = lstm_net(data, dict_size, lstm_size, emb_dim, use_fused)
    logit = layers.fc(input=last, size=2, act="softmax")
    loss = layers.mean(layers.cross_entropy(input=logit, label=label))
    batch_acc = layers.accuracy(input=logit, label=label)
    adam = optimizer.AdamOptimizer(learning_rate=0.001)
    adam.minimize(loss)
    return loss, batch_acc, logit, ["words", "label"]
