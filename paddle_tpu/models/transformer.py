"""Decoder-only transformer LM — the long-context flagship.

No 2018 reference equivalent (the reference's sequence models are LoD
LSTMs/seq2seq, SURVEY.md §5 "long context"); this model exists to exercise
the TPU-native extensions: fused/flash attention, ring & Ulysses sequence
parallelism over the `sp` mesh axis, and Megatron-style tensor parallelism
over `tp` — the capabilities the north star demands beyond reference parity.

Pre-LN blocks: x + MHA(LN(x)), x + FFN(LN(x)); learned positional
embeddings; weight-tied-free output head (fc to vocab).
"""

from __future__ import annotations

import contextlib

from .. import layers
from ..core.program import remat_scope
from ..initializer import NormalInitializer
from ..param_attr import ParamAttr


def _ffn(x, d_model, d_ff, idx, tp_shard):
    from ..layer_helper import capture_new_params
    h, up_params = capture_new_params(lambda: layers.fc(
        x, size=d_ff, num_flatten_dims=2, act="gelu",
        param_attr=ParamAttr(name=f"ffn{idx}_in_w"),
        bias_attr=ParamAttr(name=f"ffn{idx}_in_b"),
        name=f"ffn{idx}_in"))
    out, down_params = capture_new_params(lambda: layers.fc(
        h, size=d_model, num_flatten_dims=2,
        param_attr=ParamAttr(name=f"ffn{idx}_out_w"),
        bias_attr=ParamAttr(name=f"ffn{idx}_out_b"),
        name=f"ffn{idx}_out"))
    if tp_shard:
        from ..parallel.mesh import TP
        for v in up_params:
            if len(v.shape) == 2:
                v.sharding = (None, TP)      # column-parallel up-proj
        for v in down_params:
            if len(v.shape) == 2:
                v.sharding = (TP, None)      # row-parallel down-proj
    return out


def transformer_lm(src_ids, vocab_size, n_layers=2, d_model=128, n_heads=4,
                   d_ff=512, max_len=2048, dropout_rate=0.0,
                   causal=True, sp_mode="none", tp_shard=False,
                   remat=False, pos_table_len=None, collect_kv=None):
    """src_ids: [B, S] int64 var. Returns logits [B, S, vocab_size].

    pos_table_len: size the `pos_emb` parameter to this many rows and
    slice the first S at use (default None keeps the historical
    shape-[S, d] parameter). A prefill program built per length bucket
    passes the trained sequence length here so every bucket shares the
    one trained table.

    collect_kv: optional list — each layer appends its per-head (k, v)
    vars ([B, S, H, d_key]); the decode export fetches them to seed the
    paged KV cache (serving/decode).
    """
    seq_len = int(src_ids.shape[1])
    if seq_len > max_len:
        raise ValueError(f"sequence length {seq_len} exceeds max_len "
                         f"{max_len}; raise max_len")
    pos_rows = seq_len if pos_table_len is None else int(pos_table_len)
    if seq_len > pos_rows:
        raise ValueError(f"sequence length {seq_len} exceeds the "
                         f"pos_table_len {pos_rows} rows of pos_emb")
    emb = layers.embedding(src_ids, [vocab_size, d_model],
                           param_attr=ParamAttr(
                               name="tok_emb",
                               initializer=NormalInitializer(scale=0.02)))
    pos = layers.create_parameter([pos_rows, d_model],
                                  dtype="float32", name="pos_emb",
                                  default_initializer=NormalInitializer(
                                      scale=0.02))
    if pos_rows != seq_len:
        pos = layers.slice(pos, axes=[0], starts=[0], ends=[seq_len])
    x = layers.elementwise_add(emb, pos)
    if dropout_rate:
        x = layers.dropout(x, dropout_prob=dropout_rate)

    for i in range(n_layers):
        # remat: each transformer layer becomes one jax.checkpoint segment
        # (activation memory ~O(n_layers) -> O(1) per layer boundary).
        # remat may be a policy string ("save_attn" | "dots") — see
        # core.program.remat_scope: save_attn keeps the flash-attention
        # outputs so the backward skips the attention recompute.
        policy = remat if isinstance(remat, str) else None
        scope = remat_scope(f"tfm_layer_{i}", policy=policy) if remat \
            else contextlib.nullcontext()
        with scope:
            ln1 = layers.layer_norm(x, begin_norm_axis=2, name=f"ln1_{i}",
                                    param_attr=ParamAttr(name=f"ln1_{i}_scale"),
                                    bias_attr=ParamAttr(name=f"ln1_{i}_bias"))
            att = layers.multi_head_attention(
                ln1, num_heads=n_heads, causal=causal, sp_mode=sp_mode,
                dropout_rate=dropout_rate, tp_shard=tp_shard,
                kv_out=collect_kv, name=f"attn{i}")
            x = layers.elementwise_add(x, att)
            ln2 = layers.layer_norm(x, begin_norm_axis=2, name=f"ln2_{i}",
                                    param_attr=ParamAttr(name=f"ln2_{i}_scale"),
                                    bias_attr=ParamAttr(name=f"ln2_{i}_bias"))
            ff = _ffn(ln2, d_model, d_ff, i, tp_shard)
            x = layers.elementwise_add(x, ff)

    x = layers.layer_norm(x, begin_norm_axis=2, name="ln_f",
                          param_attr=ParamAttr(name="ln_f_scale"),
                          bias_attr=ParamAttr(name="ln_f_bias"))
    logits = layers.fc(x, size=vocab_size, num_flatten_dims=2,
                       param_attr=ParamAttr(name="lm_head_w"),
                       bias_attr=ParamAttr(name="lm_head_b"),
                       name="lm_head")
    return logits


def transformer_lm_loss(vocab_size=1000, seq_len=128, **kw):
    """Build data vars + LM loss. Returns (avg_cost, logits)."""
    src = layers.data("src_ids", [seq_len], dtype="int64")
    tgt = layers.data("tgt_ids", [seq_len, 1], dtype="int64")
    logits = transformer_lm(src, vocab_size, **kw)
    loss = layers.softmax_with_cross_entropy(logits, tgt)
    avg = layers.mean(loss)
    return avg, logits


# ---------------------------------------------------------------------------
# Autoregressive decode-step program (serving/decode)
# ---------------------------------------------------------------------------

def _decode_attention(x, idx, num_heads, d_key, d_model, k_pool, v_pool,
                      block_tables, context_lens):
    """One layer's decode attention: project the single new token per
    slot, write its K/V row into the paged pool, attend through the block
    table. Parameter names match multi_head_attention(name=f"attn{idx}")
    so the decode program shares the trained weights by name."""
    name = f"attn{idx}"

    def proj(inp, width, tag):
        return layers.fc(inp, size=width, num_flatten_dims=2,
                         param_attr=ParamAttr(name=f"{name}_{tag}_w"),
                         bias_attr=ParamAttr(name=f"{name}_{tag}_b"),
                         name=f"{name}_{tag}")

    q = proj(x, num_heads * d_key, "q")
    k = proj(x, num_heads * d_key, "k")
    v = proj(x, num_heads * d_key, "v")
    qr = layers.reshape(q, [0, 0, num_heads, d_key])
    kr = layers.reshape(k, [0, 0, num_heads, d_key])
    vr = layers.reshape(v, [0, 0, num_heads, d_key])
    k_out, v_out = layers.paged_kv_write(k_pool, v_pool, kr, vr,
                                         block_tables, context_lens)
    ctx = layers.paged_attention(qr, k_out, v_out, block_tables,
                                 context_lens)
    merged = layers.reshape(ctx, [0, 0, num_heads * d_key])
    return proj(merged, d_model, "out"), k_out, v_out


def transformer_decode_step(vocab_size, *, n_layers, d_model, n_heads,
                            d_ff, max_context, slots, block_size,
                            pool_blocks, max_blocks_per_seq):
    """Build the fixed-shape continuous-batching decode step: ONE new
    token per active slot against the paged KV pool.

    Feeds (all static shape; no batch coalescing — the slot axis IS the
    batch): token_ids [slots] int64, context_lens [slots] int32 (span
    INCLUDING the new token; 0 = inactive slot), block_tables
    [slots, max_blocks_per_seq] int32 (entries into the pool; 0 is the
    reserved null block), and per layer k_cache_{i}/v_cache_{i}
    [pool_blocks, block_size, H, d_key].

    Returns (logits [slots, vocab], [(k_out, v_out) per layer],
    feed_names) — the pool fetches are the next step's pool feeds.
    """
    d_key = d_model // n_heads
    token_ids = layers.data("token_ids", [slots], dtype="int64",
                            append_batch_size=False)
    context_lens = layers.data("context_lens", [slots], dtype="int32",
                               append_batch_size=False)
    block_tables = layers.data("block_tables", [slots, max_blocks_per_seq],
                               dtype="int32", append_batch_size=False)
    feed_names = ["token_ids", "context_lens", "block_tables"]
    pools = []
    for i in range(n_layers):
        shape = [pool_blocks, block_size, n_heads, d_key]
        kp = layers.data(f"k_cache_{i}", shape, dtype="float32",
                         append_batch_size=False)
        vp = layers.data(f"v_cache_{i}", shape, dtype="float32",
                         append_batch_size=False)
        pools.append((kp, vp))
        feed_names += [f"k_cache_{i}", f"v_cache_{i}"]

    # [slots] ids -> [slots, d] rows -> [slots, 1, d]: the decode "batch"
    # is the slot axis, the sequence axis is the single new token
    emb = layers.unsqueeze(
        layers.embedding(token_ids, [vocab_size, d_model],
                         param_attr=ParamAttr(
                             name="tok_emb",
                             initializer=NormalInitializer(scale=0.02))),
        [1])
    pos_tab = layers.create_parameter([max_context, d_model],
                                      dtype="float32", name="pos_emb",
                                      default_initializer=NormalInitializer(
                                          scale=0.02))
    one = layers.fill_constant([slots], "int32", 1.0)
    zero = layers.fill_constant([slots], "int32", 0.0)
    # the new token sits at position context_len-1; inactive slots (len
    # 0) clamp to row 0 — their rows only ever land in the null block
    pos_ids = layers.elementwise_max(
        layers.elementwise_sub(context_lens, one), zero)
    pos_vec = layers.unsqueeze(layers.gather(pos_tab, pos_ids), [1])
    x = layers.elementwise_add(emb, pos_vec)

    pool_outs = []
    for i in range(n_layers):
        ln1 = layers.layer_norm(x, begin_norm_axis=2, name=f"ln1_{i}",
                                param_attr=ParamAttr(name=f"ln1_{i}_scale"),
                                bias_attr=ParamAttr(name=f"ln1_{i}_bias"))
        att, k_out, v_out = _decode_attention(
            ln1, i, n_heads, d_key, d_model, pools[i][0], pools[i][1],
            block_tables, context_lens)
        pool_outs.append((k_out, v_out))
        x = layers.elementwise_add(x, att)
        ln2 = layers.layer_norm(x, begin_norm_axis=2, name=f"ln2_{i}",
                                param_attr=ParamAttr(name=f"ln2_{i}_scale"),
                                bias_attr=ParamAttr(name=f"ln2_{i}_bias"))
        ff = _ffn(ln2, d_model, d_ff, i, tp_shard=False)
        x = layers.elementwise_add(x, ff)

    x = layers.layer_norm(x, begin_norm_axis=2, name="ln_f",
                          param_attr=ParamAttr(name="ln_f_scale"),
                          bias_attr=ParamAttr(name="ln_f_bias"))
    logits = layers.fc(x, size=vocab_size, num_flatten_dims=2,
                       param_attr=ParamAttr(name="lm_head_w"),
                       bias_attr=ParamAttr(name="lm_head_b"),
                       name="lm_head")
    logits = layers.reshape(logits, [slots, vocab_size])
    return logits, pool_outs, feed_names
