"""Decoder-only transformer LM — the long-context flagship.

No 2018 reference equivalent (the reference's sequence models are LoD
LSTMs/seq2seq, SURVEY.md §5 "long context"); this model exists to exercise
the TPU-native extensions: fused/flash attention, ring & Ulysses sequence
parallelism over the `sp` mesh axis, and Megatron-style tensor parallelism
over `tp` — the capabilities the north star demands beyond reference parity.

Pre-LN blocks: x + MHA(LN(x)), x + FFN(LN(x)); learned positional
embeddings; weight-tied-free output head (fc to vocab).
"""

from __future__ import annotations

import contextlib

from .. import layers
from ..core.program import remat_scope
from ..initializer import NormalInitializer
from ..param_attr import ParamAttr


def _ffn(x, d_model, d_ff, idx, tp_shard):
    from ..layer_helper import capture_new_params
    h, up_params = capture_new_params(lambda: layers.fc(
        x, size=d_ff, num_flatten_dims=2, act="gelu",
        param_attr=ParamAttr(name=f"ffn{idx}_in_w"),
        bias_attr=ParamAttr(name=f"ffn{idx}_in_b"),
        name=f"ffn{idx}_in"))
    out, down_params = capture_new_params(lambda: layers.fc(
        h, size=d_model, num_flatten_dims=2,
        param_attr=ParamAttr(name=f"ffn{idx}_out_w"),
        bias_attr=ParamAttr(name=f"ffn{idx}_out_b"),
        name=f"ffn{idx}_out"))
    if tp_shard:
        for v in up_params:
            if len(v.shape) == 2:
                v.sharding = (None, "tp")     # column-parallel up-proj
        for v in down_params:
            if len(v.shape) == 2:
                v.sharding = ("tp", None)     # row-parallel down-proj
    return out


def transformer_lm(src_ids, vocab_size, n_layers=2, d_model=128, n_heads=4,
                   d_ff=512, max_len=2048, dropout_rate=0.0,
                   causal=True, sp_mode="none", tp_shard=False,
                   remat=False):
    """src_ids: [B, S] int64 var. Returns logits [B, S, vocab_size]."""
    seq_len = int(src_ids.shape[1])
    if seq_len > max_len:
        raise ValueError(f"sequence length {seq_len} exceeds max_len "
                         f"{max_len}; raise max_len")
    emb = layers.embedding(src_ids, [vocab_size, d_model],
                           param_attr=ParamAttr(
                               name="tok_emb",
                               initializer=NormalInitializer(scale=0.02)))
    pos = layers.create_parameter([seq_len, d_model],
                                  dtype="float32", name="pos_emb",
                                  default_initializer=NormalInitializer(
                                      scale=0.02))
    x = layers.elementwise_add(emb, pos)
    if dropout_rate:
        x = layers.dropout(x, dropout_prob=dropout_rate)

    for i in range(n_layers):
        # remat: each transformer layer becomes one jax.checkpoint segment
        # (activation memory ~O(n_layers) -> O(1) per layer boundary).
        # remat may be a policy string ("save_attn" | "dots") — see
        # core.program.remat_scope: save_attn keeps the flash-attention
        # outputs so the backward skips the attention recompute.
        policy = remat if isinstance(remat, str) else None
        scope = remat_scope(f"tfm_layer_{i}", policy=policy) if remat \
            else contextlib.nullcontext()
        with scope:
            ln1 = layers.layer_norm(x, begin_norm_axis=2, name=f"ln1_{i}",
                                    param_attr=ParamAttr(name=f"ln1_{i}_scale"),
                                    bias_attr=ParamAttr(name=f"ln1_{i}_bias"))
            att = layers.multi_head_attention(
                ln1, num_heads=n_heads, causal=causal, sp_mode=sp_mode,
                dropout_rate=dropout_rate, tp_shard=tp_shard, name=f"attn{i}")
            x = layers.elementwise_add(x, att)
            ln2 = layers.layer_norm(x, begin_norm_axis=2, name=f"ln2_{i}",
                                    param_attr=ParamAttr(name=f"ln2_{i}_scale"),
                                    bias_attr=ParamAttr(name=f"ln2_{i}_bias"))
            ff = _ffn(ln2, d_model, d_ff, i, tp_shard)
            x = layers.elementwise_add(x, ff)

    x = layers.layer_norm(x, begin_norm_axis=2, name="ln_f",
                          param_attr=ParamAttr(name="ln_f_scale"),
                          bias_attr=ParamAttr(name="ln_f_bias"))
    logits = layers.fc(x, size=vocab_size, num_flatten_dims=2,
                       param_attr=ParamAttr(name="lm_head_w"),
                       bias_attr=ParamAttr(name="lm_head_b"),
                       name="lm_head")
    return logits


def transformer_lm_loss(vocab_size=1000, seq_len=128, **kw):
    """Build data vars + LM loss. Returns (avg_cost, logits)."""
    src = layers.data("src_ids", [seq_len], dtype="int64")
    tgt = layers.data("tgt_ids", [seq_len, 1], dtype="int64")
    logits = transformer_lm(src, vocab_size, **kw)
    loss = layers.softmax_with_cross_entropy(logits, tgt)
    avg = layers.mean(loss)
    return avg, logits
