"""VGG-16 with batch norm + dropout (≙ benchmark/fluid/models/vgg.py
vgg16_bn_drop)."""

from __future__ import annotations

from .. import layers, nets, optimizer


def vgg16_bn_drop(input, is_test=False):
    def conv_block(ipt, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=ipt, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type="max")

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(x=conv5, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu", is_test=is_test)
    drop2 = layers.dropout(x=bn, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    return fc2


def get_model(data_set: str = "cifar10", learning_rate: float = 1e-3,
              is_test: bool = False):
    if data_set == "cifar10":
        classdim, data_shape = 10, [3, 32, 32]
    else:
        classdim, data_shape = 102, [3, 224, 224]
    images = layers.data("data", data_shape)
    label = layers.data("label", [1], dtype="int64")
    net = vgg16_bn_drop(images, is_test=is_test)
    predict = layers.fc(input=net, size=classdim, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    batch_acc = layers.accuracy(input=predict, label=label)
    opt = optimizer.AdamOptimizer(learning_rate=learning_rate)
    opt.minimize(avg_cost)
    return avg_cost, batch_acc, predict, ["data", "label"]
