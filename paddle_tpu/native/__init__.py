"""Native (C++) runtime pieces, compiled on demand.

≙ the reference's C++ data plane (paddle/fluid/recordio/, operators/
reader/). The build is a single g++ invocation cached by source hash —
the framework stays importable (with Python fallbacks) when no toolchain
is present.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()
_LIBS = {}


def _source_hash(path: str) -> str:
    with open(path, "rb") as f:
        return hashlib.sha256(f.read()).hexdigest()[:12]


def load_library(name: str, extra_flags=()):
    """Compile {name}.cpp (cached) and dlopen it. Returns None when the
    toolchain or a dependency is missing — callers use Python fallbacks."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        src = os.path.join(_DIR, f"{name}.cpp")
        so = os.path.join(_BUILD, f"{name}-{_source_hash(src)}.so")
        if not os.path.exists(so):
            os.makedirs(_BUILD, exist_ok=True)
            cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src,
                   "-o", so + ".tmp"] + list(extra_flags)
            try:
                subprocess.run(cmd, check=True, capture_output=True)
                os.replace(so + ".tmp", so)
            except (subprocess.CalledProcessError, FileNotFoundError):
                _LIBS[name] = None
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            lib = None
        _LIBS[name] = lib
        return lib


def recordio_lib():
    lib = load_library("recordio", extra_flags=["-lz"])
    if lib is not None and not getattr(lib, "_rio_configured", False):
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.c_long]
        lib.rio_writer_write.restype = ctypes.c_int
        lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_long]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_scanner_next.restype = ctypes.c_void_p
        lib.rio_scanner_next.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_long)]
        lib.rio_scanner_error.restype = ctypes.c_char_p
        lib.rio_scanner_error.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib._rio_configured = True
    return lib

def batcher_lib():
    lib = load_library("batcher", extra_flags=["-O3"])
    if lib is not None and not getattr(lib, "_batcher_configured", False):
        lib.pack_rows.restype = ctypes.c_int
        lib.pack_rows.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),        # rows
            ctypes.POINTER(ctypes.c_int64),         # lens
            ctypes.c_int64, ctypes.c_int64,         # n, t_max
            ctypes.c_int64,                         # step_bytes
            ctypes.c_void_p, ctypes.c_int64,        # pad, pad_bytes
            ctypes.c_void_p,                        # out
            ctypes.POINTER(ctypes.c_int32),         # out_lens
        ]
        _configure_dequantize(lib)
        lib._batcher_configured = True
    return lib


def _configure_dequantize(lib):
    lib.dequantize_u8.restype = None
    lib.dequantize_u8.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_int64, ctypes.c_float,
                                  ctypes.c_float]
    lib.dequantize_u8_bf16.restype = None
    lib.dequantize_u8_bf16.argtypes = lib.dequantize_u8.argtypes
    lib.decode_rows_u8_bf16.restype = None
    lib.decode_rows_u8_bf16.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_float, ctypes.c_float]
