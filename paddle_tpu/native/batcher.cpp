// Ragged-sequence batcher: pack N variable-length rows into one padded
// [B, T, ...] buffer + a lengths vector, in a single native call.
//
// ≙ the reference's native sequence packing layer
// (operators/math/sequence2batch.h CopyMatrixRowsFunctor,
// lod_tensor.cc SplitLoDTensor/MergeLoDTensor): the host-side step that
// turns LoD-ragged user data into device-shaped batches. The TPU data
// plane keeps batch-major padded layout (scan kernels mask by length,
// ops/rnn_ops.py) so there is no time-major reorder here — just the pack,
// which on the feed hot path (executor._prep_feed -> lod.to_padded) is
// one C call instead of a Python loop of numpy slice assignments.
//
// Flat C API via ctypes (see native/__init__.py batcher_lib).

#include <cstdint>
#include <cstring>

extern "C" {

// rows:      n pointers, row i holds lens[i] contiguous timesteps
// lens:      timestep counts per row
// step_bytes: bytes per timestep (product of trailing dims * itemsize)
// t_max:     padded timestep capacity (caller rounds up / buckets)
// pad:       pad pattern of pad_bytes (repeated to fill the tail);
//            pad_bytes must divide step_bytes; NULL -> zero fill
// out:       n * t_max * step_bytes destination
// out_lens:  n int32 lengths (written)
// returns 0 on success, -1 if any lens[i] > t_max or pad_bytes invalid
int pack_rows(const void** rows, const int64_t* lens, int64_t n,
              int64_t t_max, int64_t step_bytes, const void* pad,
              int64_t pad_bytes, void* out, int32_t* out_lens) {
  if (pad != nullptr && (pad_bytes <= 0 || step_bytes % pad_bytes != 0))
    return -1;
  char* dst = static_cast<char*>(out);
  const int64_t row_cap = t_max * step_bytes;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t len = lens[i];
    if (len < 0 || len > t_max) return -1;
    const int64_t used = len * step_bytes;
    std::memcpy(dst, rows[i], used);
    char* tail = dst + used;
    const int64_t tail_bytes = row_cap - used;
    if (pad == nullptr) {
      std::memset(tail, 0, tail_bytes);
    } else {
      for (int64_t off = 0; off < tail_bytes; off += pad_bytes)
        std::memcpy(tail + off, pad, pad_bytes);
    }
    out_lens[i] = static_cast<int32_t>(len);
    dst += row_cap;
  }
  return 0;
}

}  // extern "C"

extern "C" {

// u8 -> f32 dequantize: out[i] = in[i] * scale + shift. The feed-decode
// hot loop (image bytes -> normalized floats); numpy needs three passes
// and holds the GIL, this is one pass and runs GIL-released under
// ctypes, so reader worker threads scale across cores.
void dequantize_u8(const uint8_t* in, float* out, int64_t n, float scale,
                   float shift) {
  for (int64_t i = 0; i < n; ++i) out[i] = in[i] * scale + shift;
}

// Same, emitting bfloat16 (round-to-nearest-even truncation). Decoding
// straight to the dtype the TPU model consumes halves the write traffic
// (the decode loop is host-memory-bandwidth bound) AND halves the
// host->device transfer bytes. A u8 input has only 256 possible values,
// so the affine+round collapses to a 256-entry L1-resident lookup table
// built per call — the hot loop is then a pure gather/store.
static void build_bf16_lut(uint16_t lut[256], float scale, float shift) {
  for (int v = 0; v < 256; ++v) {
    float f = v * scale + shift;
    uint32_t bits;
    std::memcpy(&bits, &f, 4);
    bits += 0x7FFFu + ((bits >> 16) & 1u);  // round to nearest even
    lut[v] = static_cast<uint16_t>(bits >> 16);
  }
}

void dequantize_u8_bf16(const uint8_t* in, uint16_t* out, int64_t n,
                        float scale, float shift) {
  uint16_t lut[256];
  build_bf16_lut(lut, scale, shift);
  for (int64_t i = 0; i < n; ++i) out[i] = lut[in[i]];
}

// Batched image-record decode: row r is `elems` u8 pixels followed by an
// 8-byte little-endian int64 label (the bench/recordio image layout). One
// call decodes the whole batch straight into the bf16 feed buffer +
// label column — the per-record Python dispatch (ctypes call + frombuffer
// + np.stack) otherwise costs several ms per 128-image batch on the
// single shared host core.
void decode_rows_u8_bf16(const void** rows, int64_t n_rows, int64_t elems,
                         uint16_t* out, int64_t* labels, float scale,
                         float shift) {
  uint16_t lut[256];
  build_bf16_lut(lut, scale, shift);
  for (int64_t r = 0; r < n_rows; ++r) {
    const uint8_t* in = static_cast<const uint8_t*>(rows[r]);
    uint16_t* dst = out + r * elems;
    for (int64_t i = 0; i < elems; ++i) dst[i] = lut[in[i]];
    std::memcpy(labels + r, in + elems, 8);
  }
}

}  // extern "C"
