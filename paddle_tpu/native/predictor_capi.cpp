// C-callable serving API over the AOT StableHLO artifact.
//
// ≙ the reference's C/C++ inference surface: PaddlePredictor::Run
// (paddle/contrib/inference/paddle_inference_api.h:46) and the capi
// shims (paddle/capi/). The TPU-native artifact is a jax.export
// StableHLO program (io.py export_serving_model); this library embeds
// CPython to deserialize and invoke it, marshalling only flat buffers
// across the C boundary — the compute itself is the compiled XLA
// program, the interpreter only shuttles bytes.
//
// Threading: single-threaded by design (one embedded interpreter, GIL
// held by the caller's thread). Outputs are owned by the predictor and
// valid until the next pt_predictor_run / pt_predictor_destroy.
//
// Build: paddle_tpu.native.load_library("predictor_capi", python_flags)
// or any `g++ -shared -fPIC $(python3-config --includes --embed --ldflags)`.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

std::string g_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  g_error = "python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

struct Output {
  std::vector<char> raw;       // fetch bytes in the fetch's OWN dtype
  std::string dtype;           // numpy dtype name ("float32", "int32", ...)
  std::vector<int64_t> shape;
  std::vector<float> fcache;   // lazy float32 view for the legacy accessor
};

struct Predictor {
  long handle = 0;
  PyObject* module = nullptr;  // borrowed ref to paddle_tpu.serving_embed
  std::vector<Output> outputs;
};

template <typename T>
void widen_to_float(const char* raw, size_t n, std::vector<float>* dst) {
  const T* src = reinterpret_cast<const T*>(raw);
  dst->resize(n);
  for (size_t k = 0; k < n; ++k) (*dst)[k] = static_cast<float>(src[k]);
}

void write_shape(const Output& out, int64_t* shape_out, int* ndim) {
  *ndim = static_cast<int>(out.shape.size());
  for (size_t d = 0; d < out.shape.size() && d < 8; ++d) {
    shape_out[d] = out.shape[d];
  }
}

PyObject* serving_module() {
  if (!Py_IsInitialized()) {
    // Py_Initialize honors PYTHONPATH, which must make paddle_tpu (and,
    // on the axon rig, the TPU plugin) importable
    Py_InitializeEx(0);
  }
  PyObject* mod = PyImport_ImportModule("paddle_tpu.serving_embed");
  if (mod == nullptr) set_error_from_python();
  return mod;
}

}  // namespace

extern "C" {

const char* pt_last_error() { return g_error.c_str(); }

void* pt_predictor_create(const char* model_dir) {
  g_error.clear();
  PyObject* mod = serving_module();
  if (mod == nullptr) return nullptr;
  PyObject* h = PyObject_CallMethod(mod, "create", "s", model_dir);
  if (h == nullptr) {
    set_error_from_python();
    Py_DECREF(mod);
    return nullptr;
  }
  Predictor* p = new Predictor();
  p->handle = PyLong_AsLong(h);
  p->module = mod;
  Py_DECREF(h);
  return p;
}

// feeds: n_feeds flat buffers; dtype 0 = float32, 1 = int64.
// Returns 0 on success; pt_last_error() explains failures.
int pt_predictor_run(void* pred, const void* const* feed_data,
                     const int64_t* const* feed_shapes, const int* feed_ndims,
                     const int* feed_dtypes, int n_feeds) {
  g_error.clear();
  Predictor* p = static_cast<Predictor*>(pred);
  PyObject* feeds = PyList_New(n_feeds);
  for (int i = 0; i < n_feeds; ++i) {
    int64_t elems = 1;
    PyObject* shape = PyTuple_New(feed_ndims[i]);
    for (int d = 0; d < feed_ndims[i]; ++d) {
      elems *= feed_shapes[i][d];
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(feed_shapes[i][d]));
    }
    const int64_t nbytes = elems * (feed_dtypes[i] == 0 ? 4 : 8);
    PyObject* raw = PyBytes_FromStringAndSize(
        static_cast<const char*>(feed_data[i]), nbytes);
    PyObject* dtype =
        PyUnicode_FromString(feed_dtypes[i] == 0 ? "float32" : "int64");
    PyObject* entry = PyTuple_Pack(3, raw, shape, dtype);
    Py_DECREF(raw);
    Py_DECREF(shape);
    Py_DECREF(dtype);
    PyList_SET_ITEM(feeds, i, entry);  // steals entry
  }
  PyObject* result =
      PyObject_CallMethod(p->module, "run", "lO", p->handle, feeds);
  Py_DECREF(feeds);
  if (result == nullptr) {
    set_error_from_python();
    return 1;
  }
  p->outputs.clear();
  const Py_ssize_t n_out = PyList_Size(result);
  for (Py_ssize_t i = 0; i < n_out; ++i) {
    // (bytes, shape, dtype_name); pre-dtype-protocol builds sent 2-tuples
    // of float32 bytes — tolerate both
    PyObject* entry = PyList_GetItem(result, i);
    PyObject* raw = PyTuple_GetItem(entry, 0);
    PyObject* shape = PyTuple_GetItem(entry, 1);
    Output out;
    out.dtype = "float32";
    if (PyTuple_Size(entry) >= 3) {
      const char* dt = PyUnicode_AsUTF8(PyTuple_GetItem(entry, 2));
      if (dt != nullptr) {
        out.dtype = dt;
      } else {
        PyErr_Clear();  // non-str dtype slot: keep the float32 fallback
      }
    }
    const Py_ssize_t ndim = PyTuple_Size(shape);
    for (Py_ssize_t d = 0; d < ndim; ++d) {
      out.shape.push_back(PyLong_AsLongLong(PyTuple_GetItem(shape, d)));
    }
    const char* buf = PyBytes_AsString(raw);
    const Py_ssize_t nbytes = PyBytes_Size(raw);
    out.raw.resize(nbytes);
    std::memcpy(out.raw.data(), buf, nbytes);
    p->outputs.push_back(std::move(out));
  }
  Py_DECREF(result);
  return 0;
}

int pt_predictor_num_outputs(void* pred) {
  return static_cast<int>(static_cast<Predictor*>(pred)->outputs.size());
}

// Dtype-preserving accessor: the i-th output's RAW bytes in its own
// dtype; writes rank to *ndim, up to 8 dims to shape_out, and the numpy
// dtype name to *dtype_out (owned by the predictor). Valid until the
// next run/destroy.
const void* pt_predictor_output_ex(void* pred, int i, int64_t* shape_out,
                                   int* ndim, const char** dtype_out) {
  Predictor* p = static_cast<Predictor*>(pred);
  if (i < 0 || i >= static_cast<int>(p->outputs.size())) return nullptr;
  const Output& out = p->outputs[i];
  write_shape(out, shape_out, ndim);
  if (dtype_out != nullptr) *dtype_out = out.dtype.c_str();
  return out.raw.data();
}

// Legacy float32 accessor: returns the i-th output as float32, converting
// integer/double fetches on demand (pre-dtype-protocol clients assumed
// float everywhere — keep them working). Unconvertible dtypes return
// nullptr; use pt_predictor_output_ex for the raw bytes. Valid until the
// next run/destroy.
const float* pt_predictor_output(void* pred, int i, int64_t* shape_out,
                                 int* ndim) {
  Predictor* p = static_cast<Predictor*>(pred);
  if (i < 0 || i >= static_cast<int>(p->outputs.size())) return nullptr;
  Output& out = p->outputs[i];
  write_shape(out, shape_out, ndim);
  if (out.dtype == "float32") {
    return reinterpret_cast<const float*>(out.raw.data());
  }
  if (out.fcache.empty()) {
    if (out.dtype == "int32") {
      widen_to_float<int32_t>(out.raw.data(), out.raw.size() / 4,
                              &out.fcache);
    } else if (out.dtype == "int64") {
      widen_to_float<int64_t>(out.raw.data(), out.raw.size() / 8,
                              &out.fcache);
    } else if (out.dtype == "float64") {
      widen_to_float<double>(out.raw.data(), out.raw.size() / 8,
                             &out.fcache);
    } else if (out.dtype == "uint8") {
      widen_to_float<uint8_t>(out.raw.data(), out.raw.size(), &out.fcache);
    } else if (out.dtype == "bool") {
      widen_to_float<int8_t>(out.raw.data(), out.raw.size(), &out.fcache);
    } else {
      g_error = "pt_predictor_output: cannot widen dtype '" + out.dtype +
                "' to float32; use pt_predictor_output_ex";
      return nullptr;
    }
  }
  return out.fcache.data();
}

void pt_predictor_destroy(void* pred) {
  Predictor* p = static_cast<Predictor*>(pred);
  if (p == nullptr) return;
  if (p->module != nullptr) {
    PyObject* r =
        PyObject_CallMethod(p->module, "destroy", "l", p->handle);
    Py_XDECREF(r);
    Py_DECREF(p->module);
  }
  delete p;
}

}  // extern "C"
