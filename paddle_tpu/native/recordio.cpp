// RecordIO: chunked, CRC'd, optionally zlib-compressed record file format.
//
// ≙ reference paddle/fluid/recordio/{header,chunk,scanner,writer}.{h,cc}
// (710 LoC C++ over snappy). Re-designed for a TPU host data plane: large
// sequential chunks (streaming-friendly for hundreds-of-MB/s NVMe reads
// feeding host->device transfers), zlib instead of snappy (in the base
// image), and a flat C API consumed from Python via ctypes (the reference
// used pybind, pybind/recordio.cc).
//
// Layout:
//   file  := magic8 "PTRIO1\0\0" chunk*
//   chunk := "CHNK" u32 n_records  u32 compressor(0 none|1 zlib)
//            u64 compressed_len u64 raw_len u32 crc32(payload) payload
//   raw payload := ( u32 len, bytes )*
//
// Build: compiled on first import by paddle_tpu/native/__init__.py
// (g++ -O2 -shared -fPIC recordio.cpp -lz).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr char kFileMagic[8] = {'P', 'T', 'R', 'I', 'O', '1', '\0', '\0'};
constexpr char kChunkMagic[4] = {'C', 'H', 'N', 'K'};

struct Writer {
  FILE* f = nullptr;
  int compressor = 1;
  size_t chunk_bytes = 1 << 20;  // flush threshold
  std::string buf;               // raw payload being accumulated
  uint32_t n_records = 0;
  std::string err;

  bool flush_chunk() {
    if (n_records == 0) return true;
    const unsigned char* payload =
        reinterpret_cast<const unsigned char*>(buf.data());
    uLongf out_len = 0;
    std::vector<unsigned char> zbuf;
    const unsigned char* out = payload;
    if (compressor == 1) {
      out_len = compressBound(buf.size());
      zbuf.resize(out_len);
      if (compress2(zbuf.data(), &out_len, payload, buf.size(),
                    Z_BEST_SPEED) != Z_OK) {
        err = "zlib compress failed";
        return false;
      }
      out = zbuf.data();
    } else {
      out_len = buf.size();
    }
    uint32_t crc =
        crc32(0L, reinterpret_cast<const Bytef*>(out), out_len);
    uint64_t clen = out_len, rlen = buf.size();
    if (fwrite(kChunkMagic, 1, 4, f) != 4 ||
        fwrite(&n_records, 4, 1, f) != 1 ||
        fwrite(&compressor, 4, 1, f) != 1 ||
        fwrite(&clen, 8, 1, f) != 1 || fwrite(&rlen, 8, 1, f) != 1 ||
        fwrite(&crc, 4, 1, f) != 1 ||
        fwrite(out, 1, clen, f) != clen) {
      err = "short write";
      return false;
    }
    buf.clear();
    n_records = 0;
    return true;
  }
};

struct Scanner {
  FILE* f = nullptr;
  uint64_t file_size = 0; // for validating length fields before allocating
  std::string chunk;      // decompressed payload of current chunk
  size_t pos = 0;         // cursor into chunk
  uint32_t remaining = 0; // records left in current chunk
  std::string record;     // last record returned
  std::string err;

  bool load_chunk() {
    char magic[4];
    if (fread(magic, 1, 4, f) != 4) return false;  // clean EOF
    if (memcmp(magic, kChunkMagic, 4) != 0) {
      err = "bad chunk magic";
      return false;
    }
    uint32_t n, comp, crc;
    uint64_t clen, rlen;
    if (fread(&n, 4, 1, f) != 1 || fread(&comp, 4, 1, f) != 1 ||
        fread(&clen, 8, 1, f) != 1 || fread(&rlen, 8, 1, f) != 1 ||
        fread(&crc, 4, 1, f) != 1) {
      err = "truncated chunk header";
      return false;
    }
    // validate lengths BEFORE allocating: a corrupted header must raise
    // IOError on the Python side, not std::bad_alloc -> terminate. The
    // compressed payload cannot exceed the file; the raw payload cannot
    // exceed zlib's max expansion (~1032x; 2048x leaves margin). For
    // uncompressed chunks raw == stored.
    bool bad = clen > file_size;
    if (comp == 1) {
      bad = bad || (clen == 0 && rlen != 0) ||
            (clen > 0 && rlen / clen > 2048);
    } else {
      bad = bad || rlen != clen;
    }
    if (bad) {
      err = "corrupt chunk length field";
      return false;
    }
    std::string raw(clen, '\0');
    if (fread(&raw[0], 1, clen, f) != clen) {
      err = "truncated chunk payload";
      return false;
    }
    if (crc32(0L, reinterpret_cast<const Bytef*>(raw.data()), clen) != crc) {
      err = "crc mismatch";
      return false;
    }
    if (comp == 1) {
      chunk.resize(rlen);
      uLongf dlen = rlen;
      if (uncompress(reinterpret_cast<Bytef*>(&chunk[0]), &dlen,
                     reinterpret_cast<const Bytef*>(raw.data()),
                     clen) != Z_OK || dlen != rlen) {
        err = "zlib uncompress failed";
        return false;
      }
    } else {
      chunk = std::move(raw);
    }
    pos = 0;
    remaining = n;
    return true;
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, int compressor, long chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  if (fwrite(kFileMagic, 1, 8, f) != 8) {
    fclose(f);
    return nullptr;
  }
  Writer* w = new Writer();
  w->f = f;
  w->compressor = compressor;
  if (chunk_bytes > 0) w->chunk_bytes = static_cast<size_t>(chunk_bytes);
  return w;
}

int rio_writer_write(void* handle, const char* data, long len) {
  Writer* w = static_cast<Writer*>(handle);
  uint32_t l = static_cast<uint32_t>(len);
  w->buf.append(reinterpret_cast<const char*>(&l), 4);
  w->buf.append(data, len);
  w->n_records++;
  if (w->buf.size() >= w->chunk_bytes) return w->flush_chunk() ? 0 : -1;
  return 0;
}

int rio_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int rc = w->flush_chunk() ? 0 : -1;
  fclose(w->f);
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  char magic[8];
  if (fread(magic, 1, 8, f) != 8 || memcmp(magic, kFileMagic, 8) != 0) {
    fclose(f);
    return nullptr;
  }
  Scanner* s = new Scanner();
  s->f = f;
  long pos = ftell(f);
  fseek(f, 0, SEEK_END);
  s->file_size = static_cast<uint64_t>(ftell(f));
  fseek(f, pos, SEEK_SET);
  return s;
}

// Returns pointer to record bytes (valid until next call) or null at
// EOF/error; *len receives the size, or -1 on error (see rio_scanner_error).
const char* rio_scanner_next(void* handle, long* len) {
  Scanner* s = static_cast<Scanner*>(handle);
  while (s->remaining == 0) {
    if (!s->load_chunk()) {
      *len = s->err.empty() ? 0 : -1;
      return nullptr;
    }
  }
  if (s->pos + 4 > s->chunk.size()) {
    s->err = "corrupt record length";
    *len = -1;
    return nullptr;
  }
  uint32_t l;
  memcpy(&l, s->chunk.data() + s->pos, 4);
  s->pos += 4;
  if (s->pos + l > s->chunk.size()) {
    s->err = "corrupt record payload";
    *len = -1;
    return nullptr;
  }
  s->record.assign(s->chunk.data() + s->pos, l);
  s->pos += l;
  s->remaining--;
  *len = static_cast<long>(l);
  return s->record.data();
}

const char* rio_scanner_error(void* handle) {
  return static_cast<Scanner*>(handle)->err.c_str();
}

void rio_scanner_close(void* handle) {
  Scanner* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

}  // extern "C"
