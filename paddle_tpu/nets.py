"""Composite network helpers.

≙ reference python/paddle/fluid/nets.py: simple_img_conv_pool,
img_conv_group, sequence_conv_pool, glu, scaled_dot_product_attention.
"""

from __future__ import annotations

from . import layers

__all__ = ["simple_img_conv_pool", "img_conv_group", "glu",
           "scaled_dot_product_attention", "sequence_conv_pool"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, param_attr=None,
                         pool_type="max"):
    conv_out = layers.conv2d(input=input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr,
                             act=act)
    return layers.pool2d(input=conv_out, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True,
                   use_mkldnn=False):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _to_list(v):
        if hasattr(v, "__len__"):
            return list(v)
        return [v] * len(conv_num_filter)

    conv_padding = _to_list(conv_padding)
    conv_filter_size = _to_list(conv_filter_size)
    param_attr = param_attr if isinstance(param_attr, list) else \
        [param_attr] * len(conv_num_filter)
    conv_with_batchnorm = _to_list(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _to_list(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(input=tmp, num_filters=conv_num_filter[i],
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i], param_attr=param_attr[i],
                            act=local_conv_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(input=tmp, pool_size=pool_size,
                         pool_type=pool_type, pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    return layers.sequence_pool(input=conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """nets.py scaled_dot_product_attention: [B, T, D] multi-head attention
    composed from matmul/softmax ops (the 2018 formulation)."""
    if num_heads != 1:
        d = queries.shape[-1]
        head_dim = d // num_heads

        def split_heads(x):
            reshaped = layers.reshape(x, [0 if s == -1 else s for s in
                                          (x.shape[0], x.shape[1], num_heads,
                                           x.shape[2] // num_heads)])
            return layers.transpose(reshaped, [0, 2, 1, 3])

        q, k, v = map(split_heads, (queries, keys, values))
    else:
        q, k, v = queries, keys, values
    scale = (q.shape[-1]) ** -0.5
    scores = layers.matmul(q, k, transpose_y=True, alpha=scale)
    weights = layers.softmax(scores)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    ctx = layers.matmul(weights, v)
    if num_heads != 1:
        ctx = layers.transpose(ctx, [0, 2, 1, 3])
        ctx = layers.reshape(ctx, [ctx.shape[0], ctx.shape[1],
                                   ctx.shape[2] * ctx.shape[3]])
    return ctx
