"""paddle_tpu.obs — the unified observability plane.

Three surfaces, one timeline:

  trace     structured spans (`trace.span(name, **attrs)`) with
            thread-local context propagation, a bounded ring buffer,
            and Chrome-trace-event output (tools/trace_dump.py writes a
            Perfetto-loadable file). Armed by PT_TRACE; near-zero cost
            off. Every plane — executor phases, trainer events,
            data-pipeline stages, the serving request lifecycle —
            emits onto it.
  metrics   the process-wide MetricsRegistry + the ONE Prometheus text
            renderer for every family (pt_serve_* / pt_decode_* /
            pt_data_* / pt_train_* / pt_model_*), plus TrainMetrics —
            the train-plane family the Trainer records into.
  drift     continuous predicted-vs-measured monitoring: the roofline
            `predict_step` recorded at compile time, measured step time
            folded into an EWMA per step, exported as
            pt_model_predicted_step_ms / pt_model_measured_step_ms /
            pt_model_drift_ratio on the same scrape.
  opprof    the per-op performance observatory: measured device time
            per program segment (the lowering's own run boundaries),
            distributed across ops by predicted cost share and JOINED
            to analysis/cost — the ranked laggard ledger behind
            tools/op_report.py, the pt_op_* family, and bench.py's
            op_attribution block. Opt-in profiling, never a hot-path
            hook.

See docs/observability.md.
"""

from . import opprof, trace
from .drift import MONITOR, DriftMonitor, observe_prediction, step_recorder
from .metrics import (REGISTRY, MetricsRegistry, TrainMetrics,
                      build_info_labels, global_snapshot,
                      render_prometheus, validate_exposition)

__all__ = ["trace", "opprof", "REGISTRY", "MetricsRegistry",
           "TrainMetrics", "render_prometheus", "validate_exposition",
           "global_snapshot", "build_info_labels", "MONITOR",
           "DriftMonitor", "observe_prediction", "step_recorder"]
