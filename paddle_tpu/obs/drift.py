"""Predicted-vs-measured drift monitor: the cost model's honesty as a
LIVE metric family.

PR 7/9/11 built a static prediction stack — roofline `predict_step`,
planner PlacementPlan predictions, the feed-wire leg — whose agreement
with reality is checked only by offline bench runs and the CI
rank-correlation gate. But predicted-vs-measured agreement IS the
product of a cost-model-driven system ("Synthesizing Optimal
Parallelism Placement and Reduction Strategies on Hierarchical Systems"
validates its model the same way): a plan whose prediction rots in
production — a new XLA version, a different co-tenant load, a thinner
feed pipe — should be visible on the same scrape the autoscaler reads,
not at the next release's bench run.

Mechanics:

  * at executor compile time (the same amortization point as the
    verifier and the HBM-budget gate — a pure host IR walk, never per
    step) the program's `predict_step` is recorded;
  * measured step time is the SETTLE-TO-SETTLE gap divided by the
    steps dispatched between two settles of the same program — the
    steady-state throughput reading. Under lazy pipelining a single
    run's dispatch->settle latency includes however long its handle
    sat unmaterialized (a guard health handle drained log_every
    windows later would read 10x), and queueing behind earlier
    windows; consecutive-settle gaps cancel both. Compile-miss runs
    reset the baseline instead of folding — a 43 s compile must not
    poison the EWMA — and the first settle after a (re)compile only
    seeds it;
  * the `pt_model_*` family exports predicted / measured / ratio plus
    the declared bound and the observed host share (the PhaseTimer's
    host_overhead_pct — "the model said compute-bound, the host
    disagrees" is exactly the drift an operator needs attributed).

Entries are bounded (LRU over program fingerprints) and weakly
registered on the unified metrics plane (obs/metrics.py REGISTRY,
section "model")."""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Optional

from .metrics import REGISTRY

__all__ = ["ProgramDrift", "DriftMonitor", "MONITOR",
           "observe_prediction", "step_recorder", "current_ratio",
           "DRIFT_ALPHA"]

#: EWMA smoothing factor: new = alpha * sample + (1 - alpha) * old.
#: 0.2 ~ a ~10-step memory — fast enough to see a regression within a
#: scrape interval, slow enough that one co-tenant burst doesn't flap
#: the ratio.
DRIFT_ALPHA = 0.2

#: LRU bound on tracked programs — a test suite compiling hundreds of
#: tiny programs must not grow the monitor (or the exposition) forever
MAX_PROGRAMS = 64


class ProgramDrift:
    """One program's predicted-vs-measured ledger."""

    def __init__(self, fingerprint: str):
        self.fingerprint = str(fingerprint)
        self._lock = threading.Lock()
        self.predicted_ms: Optional[float] = None
        self.bound: Optional[str] = None
        self.predicted_mfu: Optional[float] = None
        self.ewma_ms: Optional[float] = None
        self.steps = 0
        self._timer_ref: Optional[Callable] = None   # weakref to PhaseTimer
        #: cumulative steps DISPATCHED (cached runs only) — the settle
        #: baseline's step axis
        self._dispatched = 0
        #: (perf_counter, cumulative-steps) of the newest settle, or
        #: None right after a (re)compile — the next settle re-seeds
        self._baseline: Optional[tuple] = None

    def set_prediction(self, predicted_ms: float, bound: str,
                       predicted_mfu: Optional[float] = None) -> None:
        with self._lock:
            self.predicted_ms = float(predicted_ms)
            self.bound = str(bound)
            if predicted_mfu is not None:
                self.predicted_mfu = float(predicted_mfu)

    def attach_timer(self, timer) -> None:
        """Weakly remember the owning executor's PhaseTimer so the
        snapshot can report the OBSERVED host share beside the DECLARED
        bound."""
        with self._lock:
            self._timer_ref = weakref.ref(timer)

    def observe_step(self, step_ms: float) -> None:
        with self._lock:
            self._observe_locked(step_ms)

    def _observe_locked(self, step_ms: float) -> None:
        self.steps += 1
        if self.ewma_ms is None:
            self.ewma_ms = float(step_ms)
        else:
            self.ewma_ms = (DRIFT_ALPHA * float(step_ms)
                            + (1.0 - DRIFT_ALPHA) * self.ewma_ms)

    # -- settle-to-settle measurement (step_recorder's machinery) -----------
    def begin_run(self, n_steps: int) -> int:
        """A cached run of `n_steps` was dispatched; returns this run's
        cumulative-step position on the settle axis."""
        with self._lock:
            self._dispatched += max(int(n_steps), 1)
            return self._dispatched

    def reset_baseline(self) -> None:
        """A (re)compile happened: its wall time sits between settles
        and must not fold into the measured series — the next settle
        seeds a fresh baseline instead."""
        with self._lock:
            self._baseline = None

    def settle(self, cumulative: int) -> None:
        """A run that ended at `cumulative` dispatched steps settled:
        fold (gap since the previous settle) / (steps between) — the
        steady-state per-step time, immune to how late a lazy handle
        was materialized and to device queueing behind earlier runs."""
        now = time.perf_counter()
        with self._lock:
            if self._baseline is not None:
                t0, c0 = self._baseline
                if cumulative > c0:
                    self._observe_locked((now - t0) * 1e3
                                         / (cumulative - c0))
            if self._baseline is None or cumulative > self._baseline[1]:
                self._baseline = (now, cumulative)

    def snapshot(self) -> dict:
        with self._lock:
            host_share = None
            timer = self._timer_ref() if self._timer_ref else None
            predicted, measured = self.predicted_ms, self.ewma_ms
            bound, mfu, steps = self.bound, self.predicted_mfu, self.steps
        if timer is not None:
            try:
                host_share = timer.snapshot().get("host_overhead_pct")
            except Exception:   # noqa: BLE001 — snapshot must not raise
                host_share = None
        ratio = (round(measured / predicted, 4)
                 if predicted and measured else None)
        return {
            "fingerprint": self.fingerprint,
            "predicted_step_ms": (round(predicted, 6)
                                  if predicted is not None else None),
            "measured_step_ms": (round(measured, 6)
                                 if measured is not None else None),
            "drift_ratio": ratio,
            "bound": bound,
            "predicted_mfu": (round(mfu, 4) if mfu is not None else None),
            "host_share_pct": host_share,
            "steps": steps,
        }


class DriftMonitor:
    """Bounded fingerprint -> ProgramDrift map; entries self-register
    on the metrics plane under their short fingerprint."""

    def __init__(self, registry=REGISTRY, max_programs: int = MAX_PROGRAMS):
        self._registry = registry
        self._max = max_programs
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, ProgramDrift]" = OrderedDict()
        self._last_fp: Optional[str] = None

    @staticmethod
    def _short(fp: str) -> str:
        return str(fp)[:12]

    def entry(self, fingerprint: str) -> ProgramDrift:
        fp = str(fingerprint)
        with self._lock:
            e = self._entries.get(fp)
            if e is None:
                e = self._entries[fp] = ProgramDrift(fp)
                while len(self._entries) > self._max:
                    old_fp, old = self._entries.popitem(last=False)
                    # dropping the strong ref is enough — the registry
                    # holds it weakly — but unregister anyway so the
                    # name can't briefly resurrect via a live snapshot
                    self._registry.unregister("model", self._short(old_fp))
                self._registry.register("model", self._short(fp), e)
            else:
                self._entries.move_to_end(fp)
        return e

    def note_dispatch(self, fingerprint: str) -> None:
        """A run (or compile) of `fingerprint` is starting. When the
        process switches programs — a periodic eval, a second model —
        every OTHER entry's settle baseline is invalidated: their next
        settle gap would otherwise fold the interleaved program's wall
        time (or its first 43 s compile) into THEIR measured EWMA as a
        false drift spike. Steady single-program loops (the dominant
        case) pay one lock + compare. In a strictly-alternating regime
        no wall-gap measurement is honest, so none is recorded."""
        fp = str(fingerprint)
        with self._lock:
            if self._last_fp == fp:
                return
            self._last_fp = fp
            others = [e for k, e in self._entries.items() if k != fp]
        for e in others:
            e.reset_baseline()

    def current_ratio(self, fingerprint: str) -> Optional[float]:
        """READ-ONLY drift_ratio lookup for `fingerprint` — None when
        the program is untracked or either side of the ratio is missing.
        Unlike entry(), never creates (or LRU-touches) an entry: the
        Trainer's re-plan poll must observe the monitor, not grow it."""
        with self._lock:
            e = self._entries.get(str(fingerprint))
        if e is None:
            return None
        return e.snapshot().get("drift_ratio")

    def reset(self) -> None:
        with self._lock:
            for fp in list(self._entries):
                self._registry.unregister("model", self._short(fp))
            self._entries.clear()
            self._last_fp = None

    def snapshot(self) -> dict:
        with self._lock:
            entries = list(self._entries.values())
        return {self._short(e.fingerprint): e.snapshot() for e in entries}


#: the process-wide monitor the executors record into
MONITOR = DriftMonitor()


def observe_prediction(program, batch: int = 1, timer=None) -> None:
    """Record `predict_step` for this program (compile-time hook; a
    prediction failure must never cost a compile — an un-modeled
    program just shows measured-only). Called on compile MISSES, so it
    also resets the settle baseline: the compile's wall time sits
    between settles and must not fold into the measured series."""
    try:
        fp = program.fingerprint()
    except Exception:   # noqa: BLE001 — observability never kills a run
        return
    MONITOR.note_dispatch(fp)
    e = MONITOR.entry(fp)
    e.reset_baseline()
    if timer is not None:
        e.attach_timer(timer)
    try:
        from ..analysis.cost import predict_step
        pred = predict_step(program, batch=batch)
        e.set_prediction(pred.predicted_step_ms, pred.bound,
                         predicted_mfu=pred.predicted_mfu)
    except Exception:   # noqa: BLE001 — measured-only entry is still useful
        pass


def current_ratio(fingerprint: str) -> Optional[float]:
    """Module-level shorthand for MONITOR.current_ratio (the Trainer's
    re-plan trigger reads through it)."""
    return MONITOR.current_ratio(fingerprint)


def step_recorder(fingerprint: str, n_steps: int = 1):
    """One-shot per-run recorder: call the returned closure when the
    dispatched run SETTLES (block_until_ready returned / the first
    LazyFetch materialized). Folds the settle-to-settle gap over the
    steps between (ProgramDrift.settle) into the program's EWMA;
    repeated calls (several handles of one run) are deduped."""
    MONITOR.note_dispatch(fingerprint)
    e = MONITOR.entry(fingerprint)
    cumulative = e.begin_run(n_steps)
    fired = [False]

    def settled() -> None:
        if fired[0]:
            return
        fired[0] = True
        e.settle(cumulative)

    return settled
