"""The unified metrics plane: one registry, one Prometheus renderer.

Before this module, three subsystems each hand-rolled their own metric
registry and exposition glue — serving (`serving/metrics.py`
ModelMetrics/DecodeMetrics + the text renderer), the data plane
(`data/metrics.py` weakref pipeline registry), and the decode engine —
and the training loop exported NOTHING. The ROADMAP's autoscaler/router
consumes "the unified metrics plane": this module is that plane.

  MetricsRegistry   process-wide, weakref-valued registry of metric
                    providers grouped into SECTIONS (data / train /
                    model). A provider is anything with `.snapshot() ->
                    dict`. Weak references: an abandoned pipeline or
                    trainer must not be pinned (or keep reporting)
                    because it once registered — the data plane's
                    registry semantics, generalized.
  render_prometheus the ONE text-exposition renderer (version 0.0.4)
                    for every family: pt_serve_* / pt_decode_* /
                    pt_data_* / pt_train_* / pt_model_*. serving/
                    metrics.py re-exports it, so the existing HTTP
                    scrape (`GET /v1/metrics?format=prometheus`) now
                    carries the training and drift families beside the
                    serving ones.
  TrainMetrics      the pt_train_* provider: step time p50/p95,
                    examples/s, last loss, guard skip/rollback
                    counters, checkpoint/epoch/compile events. The
                    Trainer records into one per `train()` call.
  validate_exposition
                    conformance checker for the exposition format
                    (# TYPE present, label escaping, no duplicate
                    series) — the CI `obs` leg and the conformance
                    test both call it, so a malformed line fails as a
                    named finding, not as a scraper mystery.

Snapshot-merge semantics are preserved from the pre-consolidation code:
`ServingMetrics.snapshot()` still returns its own models/decode
sections and merges the registry's sections on top — one scrape, every
plane.
"""

from __future__ import annotations

import os
import re
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

__all__ = ["MetricsRegistry", "REGISTRY", "TrainMetrics",
           "render_prometheus", "validate_exposition", "percentiles",
           "global_snapshot", "build_info_labels"]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class MetricsRegistry:
    """Named sections of weakly-held metric providers. `snapshot()`
    merges every live provider into {section: {name: snapshot}} —
    the shape `render_prometheus` consumes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sections: Dict[str, "weakref.WeakValueDictionary"] = {}

    def register(self, section: str, name: str, provider) -> None:
        """Re-using a (section, name) replaces the previous registrant —
        a rebuilt pipeline/trainer is the same timeline to an operator,
        like a reloaded serving model."""
        with self._lock:
            sec = self._sections.get(section)
            if sec is None:
                sec = self._sections[section] = \
                    weakref.WeakValueDictionary()
            sec[name] = provider

    def register_unique(self, section: str, base_name: str,
                        provider) -> str:
        """Atomic register-if-absent: returns the name actually used —
        `base_name`, or the first free numeric-suffix variant when
        another LIVE provider already holds it. Unlike register(),
        concurrent callers can never silently shadow each other (the
        probe and the insert share one lock hold)."""
        with self._lock:
            sec = self._sections.get(section)
            if sec is None:
                sec = self._sections[section] = \
                    weakref.WeakValueDictionary()
            name, n = base_name, 1
            while sec.get(name) is not None \
                    and sec.get(name) is not provider:
                n += 1
                name = f"{base_name}-{n}"
            sec[name] = provider
            return name

    def unregister(self, section: str, name: str) -> None:
        with self._lock:
            sec = self._sections.get(section)
            if sec is not None:
                sec.pop(name, None)

    def providers(self, section: str) -> Dict[str, object]:
        with self._lock:
            sec = self._sections.get(section)
            return dict(sec) if sec is not None else {}

    def snapshot(self) -> Dict[str, Dict[str, dict]]:
        with self._lock:
            live = {s: dict(sec) for s, sec in self._sections.items()}
        out: Dict[str, Dict[str, dict]] = {}
        for section, providers in live.items():
            if not providers:
                continue
            out[section] = {name: p.snapshot()
                            for name, p in sorted(providers.items())}
        return out


#: the process-wide registry every plane reports through
REGISTRY = MetricsRegistry()


def global_snapshot() -> dict:
    """The registry's merged snapshot — what a scrape sees for the
    non-serving planes (serving merges this into its own snapshot)."""
    return REGISTRY.snapshot()


# ---------------------------------------------------------------------------
# shared percentile helper (was serving/metrics._percentiles)
# ---------------------------------------------------------------------------

def percentiles(samples: List[float],
                qs=(0.50, 0.95, 0.99)) -> Dict[str, Optional[float]]:
    """p50/p95/p99 by nearest-rank over a sorted copy, in ms."""
    if not samples:
        return {f"p{int(q * 100)}_ms": None for q in qs}
    s = sorted(samples)
    n = len(s)

    def rank(q: float) -> float:
        i = min(n - 1, max(0, int(round(q * (n - 1)))))
        return round(s[i] * 1000.0, 3)

    return {f"p{int(q * 100)}_ms": rank(q) for q in qs}


# ---------------------------------------------------------------------------
# the train-plane provider (pt_train_*)
# ---------------------------------------------------------------------------

#: per-metric ring for step-time percentiles — same bound rationale as
#: the serving reservoirs: recent is what an operator wants, memory
#: must not grow with step count
TRAIN_RESERVOIR = 2048


class TrainMetrics:
    """One training run's counters + step-time reservoir. Thread-safe:
    the train loop records while HTTP scrapes read."""

    def __init__(self, name: str = "trainer",
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._clock()
            self.steps = 0
            self.examples = 0
            self.epochs = 0
            self.anomalies = 0      # guard skip events (bad steps seen)
            self.rollbacks = 0      # guard rollback restores
            self.checkpoints = 0
            self.compile_events = 0
            self.loss: Optional[float] = None
            self.grad_norm: Optional[float] = None
            self._step_ms: deque = deque(maxlen=TRAIN_RESERVOIR)

    # -- recording ----------------------------------------------------------
    def observe_step(self, step_ms: Optional[float] = None, n: int = 1,
                     examples: int = 0) -> None:
        """A completed step window: step count and examples ALWAYS
        count; the per-step wall sample joins the percentile reservoir
        only when given (the Trainer passes None for windows whose
        lazy fetches haven't materialized yet — under log_every > 1
        only materialize boundaries carry an honest wall reading, the
        same dispatch-vs-settle distinction obs/drift.py makes)."""
        with self._lock:
            self.steps += int(n)
            self.examples += int(examples)
            if step_ms is not None:
                self._step_ms.append(step_ms / 1000.0)  # reservoir in s

    def observe_loss(self, value: float) -> None:
        with self._lock:
            self.loss = float(value)

    def observe_grad_norm(self, value: float) -> None:
        """Optional: populated when the caller fetches a grad-norm
        metric (the guard's in-graph flag is boolean — the norm itself
        is not fetched by default)."""
        with self._lock:
            self.grad_norm = float(value)

    def observe_compiles(self, total: int) -> None:
        """Cumulative compile events of THIS training run (the Trainer
        passes the executor-lifetime delta since train() started,
        summed across guard-rollback re-entries) — recorded
        monotonic."""
        with self._lock:
            self.compile_events = max(self.compile_events, int(total))

    def on_anomaly(self) -> None:
        with self._lock:
            self.anomalies += 1

    def on_rollback(self) -> None:
        with self._lock:
            self.rollbacks += 1

    def on_checkpoint(self) -> None:
        with self._lock:
            self.checkpoints += 1

    def on_epoch(self) -> None:
        with self._lock:
            self.epochs += 1

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            elapsed = max(self._clock() - self._t0, 1e-9)
            return {
                "name": self.name,
                "steps": self.steps,
                "examples": self.examples,
                "epochs": self.epochs,
                "anomalies": self.anomalies,
                "rollbacks": self.rollbacks,
                "checkpoints": self.checkpoints,
                "compile_events": self.compile_events,
                "loss": self.loss,
                "grad_norm": self.grad_norm,
                "examples_per_sec": round(self.examples / elapsed, 2),
                "steps_per_sec": round(self.steps / elapsed, 3),
                "window_s": round(elapsed, 3),
                "step_time": percentiles(list(self._step_ms),
                                         qs=(0.50, 0.95)),
            }


# ---------------------------------------------------------------------------
# Prometheus text exposition (version 0.0.4) — the ONE renderer
# ---------------------------------------------------------------------------

#: ModelMetrics counters exported as pt_serve_<key>; monotonic ones get
#: the conventional _total suffix
_SERVE_COUNTERS = ("received", "completed", "failed", "shed_overload",
                   "shed_deadline", "batches", "reloads")
_SERVE_GAUGES = ("queue_depth", "batch_fill_ratio", "qps")
_DECODE_COUNTERS = ("received", "completed", "failed", "shed_overload",
                    "shed_deadline", "evictions", "resumes", "prefills",
                    "prefill_tokens", "decode_steps", "tokens_out")
_DECODE_GAUGES = ("tokens_per_sec", "slot_occupancy", "active", "waiting",
                  "kv_blocks_in_use", "kv_blocks_capacity",
                  "kv_high_water")
#: KV-economics families (serving/decode/prefix.py + spec.py): prefix
#: sharing exports as pt_kv_*, speculative decoding as pt_spec_* —
#: snapshot keys carry the kv_/spec_ prefix already, so the family name
#: IS the key
_KV_COUNTERS = ("kv_shared_hits", "kv_shared_tokens", "kv_cow_copies")
_KV_GAUGES = ("kv_blocks_shared", "kv_blocks_indexed")
_SPEC_COUNTERS = ("spec_steps", "spec_drafted", "spec_accepted",
                  "spec_fallbacks")
_SPEC_GAUGES = ("spec_acceptance_rate",)
#: data-plane (input pipeline) counters/gauges exported as pt_data_*
#: (data/metrics.py PipelineMetrics.snapshot). wire_bytes/raw_bytes/
#: codec_ratio are the on-wire feed codec's accounting (data/codec.py)
_DATA_COUNTERS = ("batches", "samples")
_DATA_GAUGES = ("batches_per_sec", "samples_per_sec", "workers",
                "wire_bytes", "raw_bytes", "codec_ratio")
#: train-plane counters/gauges exported as pt_train_* (TrainMetrics)
_TRAIN_COUNTERS = ("steps", "examples", "epochs", "anomalies",
                   "rollbacks", "checkpoints", "compile_events")
_TRAIN_GAUGES = ("examples_per_sec", "steps_per_sec", "loss",
                 "grad_norm")
#: drift-monitor gauges exported as pt_model_* (obs/drift.py)
_MODEL_GAUGES = ("predicted_step_ms", "measured_step_ms", "drift_ratio",
                 "host_share_pct")
#: per-op attribution fields exported as pt_op_* (obs/opprof.py):
#: the coverage/total gauges per profiled program, plus the top-K
#: laggard rows by measured share
_OP_GAUGES = ("coverage_pct", "total_measured_ms", "fused_step_ms")
_OP_ROW_GAUGES = ("measured_ms", "predicted_ms", "share_pct", "mfu_pct")


#: (jax_version, detected_chip) memo — jax.devices() forces backend
#: init, far too heavy to pay per scrape; both are process constants.
#: The PT_COST_CHIP override and the armed-knob label stay live (knobs
#: toggle at runtime), so only the expensive detection is cached.
_BUILD_INFO_MEMO: Optional[tuple] = None


def build_info_labels() -> Dict[str, str]:
    """Labels of the pt_build_info info-series: what produced the
    numbers a scrape carries — jax version, the chip the cost model
    prices for (PT_COST_CHIP override or the detected device kind), and
    every ARMED PT_* knob from the flags registry. The value is a
    constant 1; identity lives in the labels (the Prometheus
    build_info convention)."""
    global _BUILD_INFO_MEMO
    if _BUILD_INFO_MEMO is None:
        try:
            import jax
            jax_version = jax.__version__
        except Exception:   # noqa: BLE001 — a scrape must never fail
            jax_version = "unknown"
        try:
            import jax
            detected = getattr(jax.devices()[0], "device_kind", "") \
                or jax.default_backend()
        except Exception:   # noqa: BLE001
            detected = "unknown"
        _BUILD_INFO_MEMO = (jax_version, detected)
    jax_version, detected = _BUILD_INFO_MEMO
    chip = os.environ.get("PT_COST_CHIP", "").strip() or detected
    try:
        from ..flags import ENV_KNOBS
        armed = ",".join(f"{k}={os.environ[k]}" for k in sorted(ENV_KNOBS)
                         if os.environ.get(k, "") != "")
    except Exception:   # noqa: BLE001
        armed = ""
    labels = {"jax": jax_version, "chip": chip, "knobs": armed}
    try:
        # the ambient cost-model calibration's content hash (mtime-
        # memoized inside calibrate — stays live across refits); empty
        # when PT_CALIB_PATH is unarmed or the artifact fails its floors
        from ..analysis.calibrate import active_version
        labels["calibration"] = active_version() or ""
    except Exception:   # noqa: BLE001 — a scrape must never fail
        labels["calibration"] = ""
    return labels


def render_prometheus(snapshot: dict) -> str:
    """Render a merged metrics snapshot (ServingMetrics.snapshot() /
    global_snapshot()) as Prometheus text exposition (version 0.0.4).
    None values are omitted — absence is the Prometheus idiom for 'no
    observation yet', not 0."""
    lines: List[str] = []
    typed: set = set()

    def esc(v) -> str:
        # the 0.0.4 format requires \ " and newline escaped in label
        # values; names are caller-controlled strings
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def emit(metric: str, labels: Dict[str, str], value,
             kind: str = "gauge") -> None:
        if value is None:
            return
        if metric not in typed:
            typed.add(metric)
            lines.append(f"# TYPE {metric} {kind}")
        lab = ",".join(f'{k}="{esc(v)}"' for k, v in labels.items())
        # full precision: %g's 6 significant digits would freeze large
        # counters between scrapes, breaking rate() on the very
        # throughput series this exposition exists for. repr = shortest
        # round-trip form.
        val = float(value)
        text = str(int(val)) if val.is_integer() else repr(val)
        lines.append(f"{metric}{{{lab}}} {text}")

    def serve_labels(name: str, snap: dict) -> Dict[str, str]:
        # the model label comes from the snapshot itself (the merge key
        # may be namespaced, e.g. the fleet's "r0/ranker"), and a
        # replica id — stamped by ServingMetrics(replica=...) in
        # multi-engine processes — becomes a label so two replicas
        # serving one model name are distinct series, not duplicates
        labels = {"model": str(snap.get("model", name))}
        if snap.get("replica"):
            labels["replica"] = str(snap["replica"])
        return labels

    # identity first: one constant-1 info series whose labels say what
    # produced every number below — jax version, priced chip, armed knobs
    emit("pt_build_info", build_info_labels(), 1)
    for name, snap in sorted(snapshot.get("models", {}).items()):
        base = serve_labels(name, snap)
        for key in _SERVE_COUNTERS:
            emit(f"pt_serve_{key}_total", base, snap.get(key),
                 "counter")
        for key in _SERVE_GAUGES:
            emit(f"pt_serve_{key}", base, snap.get(key))
        for phase, pcts in snap.get("latency", {}).items():
            for q in ("p50", "p95", "p99"):
                emit("pt_serve_latency_ms",
                     dict(base, phase=phase, quantile=q),
                     pcts.get(f"{q}_ms"))
        for key, val in snap.get("phases", {}).items():
            if key.endswith("_s"):
                emit("pt_serve_phase_seconds_total",
                     dict(base, phase=key[:-2]), val, "counter")
    for name, snap in sorted(snapshot.get("decode", {}).items()):
        base = serve_labels(name, snap)
        for key in _DECODE_COUNTERS:
            emit(f"pt_decode_{key}_total", base, snap.get(key),
                 "counter")
        for key in _DECODE_GAUGES:
            emit(f"pt_decode_{key}", base, snap.get(key))
        for key in _KV_COUNTERS + _SPEC_COUNTERS:
            emit(f"pt_{key}_total", base, snap.get(key), "counter")
        for key in _KV_GAUGES + _SPEC_GAUGES:
            emit(f"pt_{key}", base, snap.get(key))
        for key in ("prefill_s", "decode_s"):
            emit("pt_decode_phase_seconds_total",
                 dict(base, phase=key[:-2]), snap.get(key),
                 "counter")
    for name, snap in sorted(snapshot.get("fleet", {}).items()):
        # the replica-tier family (serving/fleet/): pool size +
        # per-replica health gauges, dispatch/shed/scale counters
        fl = {"fleet": str(snap.get("name", name))}
        emit("pt_fleet_replicas", fl, snap.get("replicas"))
        for key in ("completed", "failed", "failovers", "rebuilds"):
            emit(f"pt_fleet_{key}_total", fl, snap.get(key), "counter")
        for policy, n in sorted((snap.get("dispatched") or {}).items()):
            emit("pt_fleet_dispatch_total", dict(fl, policy=policy), n,
                 "counter")
        for cls, n in sorted((snap.get("sheds") or {}).items()):
            emit("pt_fleet_sheds_total",
                 dict(fl, **{"class": str(cls), "kind": "overload"}), n,
                 "counter")
        for cls, n in sorted((snap.get("sheds_deadline") or {}).items()):
            emit("pt_fleet_sheds_total",
                 dict(fl, **{"class": str(cls), "kind": "deadline"}), n,
                 "counter")
        for direction, n in sorted(
                (snap.get("scale_events") or {}).items()):
            emit("pt_fleet_scale_events_total",
                 dict(fl, direction=direction), n, "counter")
        for cls, n in sorted((snap.get("queue_depths") or {}).items()):
            emit("pt_fleet_queue_depth",
                 dict(fl, **{"class": str(cls)}), n)
        for rid, h in sorted((snap.get("replica_health") or {}).items()):
            rl = dict(fl, replica=str(rid))
            emit("pt_fleet_replica_queue_depth", rl,
                 h.get("queue_depth"))
            emit("pt_fleet_replica_ewma_ms", rl, h.get("ewma_ms"))
            emit("pt_fleet_replica_healthy", rl,
                 1 if h.get("healthy") else 0)
    for name, snap in sorted(snapshot.get("data", {}).items()):
        for key in _DATA_COUNTERS:
            emit(f"pt_data_{key}_total", {"pipeline": name},
                 snap.get(key), "counter")
        for key in _DATA_GAUGES:
            emit(f"pt_data_{key}", {"pipeline": name}, snap.get(key))
        for stage, st in snap.get("stages", {}).items():
            emit("pt_data_stage_seconds_total",
                 {"pipeline": name, "stage": stage}, st.get("busy_s"),
                 "counter")
            emit("pt_data_stage_occupancy",
                 {"pipeline": name, "stage": stage}, st.get("occupancy"))
    for name, snap in sorted(snapshot.get("train", {}).items()):
        for key in _TRAIN_COUNTERS:
            emit(f"pt_train_{key}_total", {"trainer": name},
                 snap.get(key), "counter")
        for key in _TRAIN_GAUGES:
            emit(f"pt_train_{key}", {"trainer": name}, snap.get(key))
        for q, val in (snap.get("step_time") or {}).items():
            emit("pt_train_step_time_ms",
                 {"trainer": name, "quantile": q[:-3]}, val)
    for name, snap in sorted(snapshot.get("model", {}).items()):
        for key in _MODEL_GAUGES:
            emit(f"pt_model_{key}", {"program": name}, snap.get(key))
        emit("pt_model_steps_total", {"program": name},
             snap.get("steps"), "counter")
        if snap.get("bound") is not None:
            # declared roofline bound as an info-style series: the label
            # carries the enum, the value is a constant 1
            emit("pt_model_bound",
                 {"program": name, "bound": snap["bound"]}, 1)
    for name, snap in sorted(snapshot.get("op", {}).items()):
        # per-op attribution (obs/opprof.py): the coverage gauge says
        # how much of the profiled step is attributed to cost-model-
        # covered ops; the top-K laggards ride as labeled rows
        for key in _OP_GAUGES:
            emit(f"pt_op_{key}", {"program": name}, snap.get(key))
        for row in snap.get("top_ops") or []:
            labels = {"program": name, "op": str(row.get("name")),
                      "type": str(row.get("type"))}
            for key in _OP_ROW_GAUGES:
                emit(f"pt_op_{key}", labels, row.get(key))
    for name, snap in sorted(snapshot.get("calib", {}).items()):
        # the calibration loop (analysis/calibrate.py + the Trainer's
        # drift-triggered re-plan): closure count, the current sustain
        # streak against the armed threshold, and the calibration
        # identity in play as an info-style series
        cl = {"trainer": str(name)}
        emit("pt_calib_replans_total", cl, snap.get("replans"), "counter")
        for key in ("drift_streak", "threshold", "last_drift_ratio"):
            emit(f"pt_calib_{key}", cl, snap.get(key))
        if snap.get("calibration_version"):
            emit("pt_calib_info",
                 dict(cl, version=str(snap["calibration_version"])), 1)
    for name, snap in sorted(snapshot.get("elastic", {}).items()):
        # the elastic supervisor (resilience/elastic.py): restart /
        # reshard counters, accumulated downtime, and the degraded-mode
        # chip gauges (current vs the fleet the run was launched for)
        el = {"supervisor": str(snap.get("name", name))}
        for key in ("restarts", "reshards"):
            emit(f"pt_elastic_{key}_total", el, snap.get(key), "counter")
        emit("pt_elastic_downtime_seconds_total", el,
             snap.get("downtime_s"), "counter")
        for key in ("current_chips", "target_chips"):
            emit(f"pt_elastic_{key}", el, snap.get(key))
        for site, n in sorted((snap.get("restarts_by_site") or {}).items()):
            emit("pt_elastic_restart_site_total", dict(el, site=str(site)),
                 n, "counter")
    for name, snap in sorted(snapshot.get("orch", {}).items()):
        # the host-level orchestrator (resilience/orchestrator.py):
        # live-worker and lease-age gauges, evictions split by recorded
        # cause (worker_crash vs heartbeat_loss — dead vs hung), and the
        # recovery clock (evict -> survivors beating on the new round)
        ol = {"orchestrator": str(snap.get("name", name))}
        for key in ("workers_live", "workers_total", "rounds",
                    "current_chips", "target_chips"):
            emit(f"pt_orch_{key}", ol, snap.get(key))
        emit("pt_orch_lease_age_seconds", ol, snap.get("lease_age_max_s"))
        emit("pt_orch_detect_seconds", ol, snap.get("last_detect_s"))
        emit("pt_orch_last_recovery_seconds", ol,
             snap.get("last_recovery_s"))
        emit("pt_orch_recoveries_total", ol, snap.get("recoveries"),
             "counter")
        emit("pt_orch_recovery_seconds_total", ol,
             snap.get("recovery_s_total"), "counter")
        for cause, n in sorted((snap.get("evictions_by_cause") or {})
                               .items()):
            emit("pt_orch_evictions_total", dict(ol, cause=str(cause)),
                 n, "counter")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# exposition conformance (the CI `obs` leg's check)
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*")
_LABEL_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\\\|\\"|\\n)*)"')


def validate_exposition(text: str) -> List[str]:
    """Check Prometheus text-format (0.0.4) conformance: every sample
    line parses (`name{labels} value`), every metric has a `# TYPE`
    line BEFORE its first sample, label values are correctly escaped,
    no duplicate series. Returns problems (empty = conformant)."""
    problems: List[str] = []
    typed: set = set()
    seen_series: set = set()
    for i, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                if parts[3] not in ("counter", "gauge", "histogram",
                                    "summary", "untyped"):
                    problems.append(f"line {i}: unknown TYPE {parts[3]!r}")
                if parts[2] in typed:
                    problems.append(
                        f"line {i}: duplicate TYPE for {parts[2]!r}")
                typed.add(parts[2])
            continue
        m = _NAME_RE.match(line)
        if m is None:
            problems.append(f"line {i}: unparsable sample {line!r}")
            continue
        name = m.group(0)
        rest = line[m.end():]
        labels = ""
        if rest.startswith("{"):
            close = rest.find("}")
            if close < 0:
                problems.append(f"line {i}: unterminated label set")
                continue
            labels = rest[1:close]
            rest = rest[close + 1:]
            consumed = _LABEL_RE.sub("", labels).replace(",", "").strip()
            if consumed:
                problems.append(
                    f"line {i}: malformed/unescaped labels {labels!r}")
        value = rest.strip().split()[0] if rest.strip() else ""
        try:
            float(value)
        except ValueError:
            problems.append(f"line {i}: non-numeric value {value!r}")
        if name not in typed:
            problems.append(
                f"line {i}: sample for {name!r} has no preceding # TYPE")
        series = (name, tuple(sorted(_LABEL_RE.findall(labels))))
        if series in seen_series:
            problems.append(f"line {i}: duplicate series {name}"
                            f"{{{labels}}}")
        seen_series.add(series)
    return problems
