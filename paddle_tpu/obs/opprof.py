"""Per-op performance observatory: measured device time, attributed to
program ops and JOINED to the static cost model.

The drift monitor (obs/drift.py) compares predicted-vs-measured at
whole-step granularity: it can say "this program runs 1.4x slower than
the roofline", but not WHICH ops are the laggards — and the conv-family
MFU push (ROADMAP: 31% -> 45% on ResNet-50) needs a named, quantified
laggard list, not a step-level ratio. This module builds that
attribution loop:

  1. segment block 0 at the SAME maximal-run boundaries the traced
     lowering executes (core/lowering.iter_op_runs — reuse, not a new
     analysis; remat-tagged runs stay atomic so their vjp recomputes
     exactly like the real step's), coalescing adjacent unit runs up to
     PT_OPPROF_SEG_OPS ops so the compile count stays bounded;
  2. compile each segment ONCE and time min-of-PT_OPPROF_REPEATS
     settled runs (block_until_ready) on real feeds + real scope state —
     robust on the CPU tier-1, no profiler parsing required. Forward
     segments of a training program are additionally timed through
     jax.vjp, so each segment's BACKWARD is measured too (a segment
     whose vjp cannot build falls back to the cost model's convention
     — 2x forward, 3x for remat runs — flagged `bwd_modeled`);
  3. distribute each segment's measured time across its member ops by
     their predicted cost share (analysis/cost.op_roofline_ms — the
     same per-op roofline that fills the predicted column, so the join
     is self-consistent). A segment whose members are ALL uncovered by
     the cost model is flagged a GAP: its time still appears in the
     ledger, but the attribution-coverage gauge drops below 100% — the
     `uncovered_ops` lesson, attribution gaps visible, never silently
     zero.

Each ledger row carries {op type, name, predicted_ms, measured_ms,
per-op MFU, declared bound, share of step}. Surfaces:

  * `tools/op_report.py` — the ranked laggard table CLI (`--top K`,
    `--check` schema/floor validation via analysis/artifacts.py);
  * `publish()` — a `pt_op_*` metric family (top-K laggards by measured
    share + the attribution-coverage gauge) on the unified exposition;
  * bench.py training configs emit an `op_attribution` block;
  * with PT_TRACE armed, the measured per-op intervals merge into the
    Chrome-trace timeline via trace.complete() (cat="opprof"), so a
    PT_TRACE_DIR dump shows host spans and device attribution in one
    Perfetto view.

Profiling is OPT-IN (a profiling run, never an executor hook): the
PT_TRACE-disabled hot path pays nothing for this module's existence.
Single-chip only — a sharded program's per-op attribution needs the
device profiler, not host segment timing.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..analysis import cost as _cost
from ..core.program import Program, default_main_program
from ..flags import env_knob_int as _knob_int

__all__ = ["OpRow", "SegmentTiming", "OpLedger", "profile_program",
           "publish", "OpProfMetrics", "REPEATS_ENV", "SEG_OPS_ENV",
           "TOPK_ENV"]

REPEATS_ENV = "PT_OPPROF_REPEATS"
SEG_OPS_ENV = "PT_OPPROF_SEG_OPS"
TOPK_ENV = "PT_OPPROF_TOPK"

DEFAULT_REPEATS = 3
DEFAULT_SEG_OPS = 16
DEFAULT_TOPK = 5


def _rnd(v, n: int = 5):
    return round(v, n) if v is not None else None


# ---------------------------------------------------------------------------
# ledger records
# ---------------------------------------------------------------------------

@dataclass
class OpRow:
    """One program op's predicted/measured join."""

    index: int                    # block-0 op index
    op_type: str
    name: str                     # primary output var (the op's identity)
    phase: str                    # forward | optimizer
    segment: int                  # owning segment id
    predicted_ms: float           # per-op roofline (train total: fwd+bwd)
    measured_ms: Optional[float]  # attributed share (fwd+bwd), None if
    #                               the segment could not be measured
    measured_fwd_ms: Optional[float] = None
    measured_bwd_ms: Optional[float] = None
    mxu_flops: int = 0            # train-total MXU flops (MFU numerator)
    mfu_pct: Optional[float] = None            # measured per-op MFU
    predicted_mfu_pct: Optional[float] = None
    bound: str = "bandwidth"      # per-op roofline leg
    share_pct: Optional[float] = None          # share of profiled step
    covered: bool = True          # cost-model coverage of THIS op

    def to_dict(self) -> dict:
        return {"index": self.index, "type": self.op_type,
                "name": self.name, "phase": self.phase,
                "segment": self.segment,
                "predicted_ms": _rnd(self.predicted_ms),
                "measured_ms": _rnd(self.measured_ms),
                "measured_fwd_ms": _rnd(self.measured_fwd_ms),
                "measured_bwd_ms": _rnd(self.measured_bwd_ms),
                "mfu_pct": _rnd(self.mfu_pct, 2),
                "predicted_mfu_pct": _rnd(self.predicted_mfu_pct, 2),
                "bound": self.bound,
                "share_pct": _rnd(self.share_pct, 3),
                "covered": self.covered}


@dataclass
class SegmentTiming:
    """One compiled-and-timed op range [start, stop)."""

    seg_id: int
    start: int
    stop: int
    phase: str                    # forward | optimizer
    tag: Optional[str]            # remat_scope tag (atomic runs)
    op_types: List[str]
    measured_fwd_ms: Optional[float] = None
    measured_bwd_ms: Optional[float] = None
    bwd_modeled: bool = False     # vjp unavailable: bwd = 2x fwd
    gap: bool = False             # every member uncovered by the model
    error: Optional[str] = None   # segment could not compile/run

    @property
    def measured_ms(self) -> Optional[float]:
        if self.measured_fwd_ms is None:
            return None
        return self.measured_fwd_ms + (self.measured_bwd_ms or 0.0)

    def to_dict(self) -> dict:
        return {"seg_id": self.seg_id, "start": self.start,
                "stop": self.stop, "phase": self.phase, "tag": self.tag,
                "n_ops": len(self.op_types),
                "op_types": list(self.op_types),
                "measured_fwd_ms": (round(self.measured_fwd_ms, 5)
                                    if self.measured_fwd_ms is not None
                                    else None),
                "measured_bwd_ms": (round(self.measured_bwd_ms, 5)
                                    if self.measured_bwd_ms is not None
                                    else None),
                "bwd_modeled": self.bwd_modeled, "gap": self.gap,
                "error": self.error}


@dataclass
class OpLedger:
    """The ranked predicted-vs-measured join for one program."""

    program: str
    batch: int
    chip: str
    train: bool
    rows: List[OpRow] = field(default_factory=list)
    segments: List[SegmentTiming] = field(default_factory=list)
    total_measured_ms: float = 0.0
    total_predicted_ms: float = 0.0
    coverage_pct: float = 100.0   # share of measured time attributed to
    #                               cost-model-covered segments
    fused_step_ms: Optional[float] = None   # the real one-dispatch step
    uncovered_ops: List[str] = field(default_factory=list)
    #: full program fingerprint (not the 12-char display name) — the
    #: calibration fit stamps it into the artifact's provenance so a
    #: program-specific calibration can refuse a foreign program
    fingerprint: Optional[str] = None

    def ranked(self) -> List[OpRow]:
        """Rows by measured time, laggards first (unmeasured rows last,
        by predicted)."""
        return sorted(self.rows,
                      key=lambda r: (r.measured_ms is None,
                                     -(r.measured_ms or 0.0),
                                     -r.predicted_ms))

    def top(self, k: int = DEFAULT_TOPK) -> List[OpRow]:
        return self.ranked()[:max(k, 1)]

    def summary(self, top: Optional[int] = None) -> dict:
        """The compact block bench.py embeds and publish() exports."""
        k = top if top is not None else _knob_int(TOPK_ENV, DEFAULT_TOPK)
        return {
            "program": self.program,
            "coverage_pct": round(self.coverage_pct, 2),
            "segments_errored": sum(1 for s in self.segments if s.error),
            "total_measured_ms": round(self.total_measured_ms, 4),
            "fused_step_ms": (round(self.fused_step_ms, 4)
                              if self.fused_step_ms is not None else None),
            "top_ops": [
                {"name": r.name, "type": r.op_type,
                 "measured_ms": (round(r.measured_ms, 5)
                                 if r.measured_ms is not None else None),
                 "predicted_ms": round(r.predicted_ms, 5),
                 "share_pct": (round(r.share_pct, 2)
                               if r.share_pct is not None else None),
                 "mfu_pct": (round(r.mfu_pct, 2)
                             if r.mfu_pct is not None else None),
                 "bound": r.bound}
                for r in self.top(k)],
        }

    def to_dict(self) -> dict:
        return {
            "program": self.program, "batch": self.batch,
            "chip": self.chip, "train": self.train,
            "fingerprint": self.fingerprint,
            "total_measured_ms": round(self.total_measured_ms, 4),
            "total_predicted_ms": round(self.total_predicted_ms, 4),
            "coverage_pct": round(self.coverage_pct, 2),
            "fused_step_ms": (round(self.fused_step_ms, 4)
                              if self.fused_step_ms is not None else None),
            "uncovered_ops": list(self.uncovered_ops),
            "segments": [s.to_dict() for s in self.segments],
            "rows": [r.to_dict() for r in self.ranked()],
        }


# ---------------------------------------------------------------------------
# segmentation (the lowering's own boundaries, coalesced)
# ---------------------------------------------------------------------------

def _segments_for(ops, fwd_stop: int, n_ops: int, seg_ops: int):
    """(start, stop, phase, tag) segments: the lowering's maximal runs
    (core/lowering.iter_op_runs), with adjacent UNIT runs coalesced up
    to `seg_ops` ops so the per-segment compile count stays bounded.
    Remat-tagged runs are atomic (their vjp must recompute like the
    real step), the autodiff pseudo-op is skipped, and no segment
    crosses the forward/optimizer boundary."""
    from ..core.lowering import iter_op_runs
    out = []

    def emit_phase(start, stop, phase):
        pend_i = None
        pend_n = 0
        for i, j, tag in iter_op_runs(ops, start, stop):
            if tag is not None:
                if pend_i is not None:
                    out.append((pend_i, i, phase, None))
                    pend_i = None
                out.append((i, j, phase, tag))
                continue
            if pend_i is None:
                pend_i, pend_n = i, 0
            pend_n += j - i
            if pend_n >= seg_ops:
                out.append((pend_i, j, phase, None))
                pend_i = None
        if pend_i is not None:
            out.append((pend_i, stop, phase, None))

    emit_phase(0, fwd_stop, "forward")
    if fwd_stop < n_ops:
        emit_phase(fwd_stop + 1, n_ops, "optimizer")
    return out


# ---------------------------------------------------------------------------
# profiling
# ---------------------------------------------------------------------------

def _time_call(fn, args, repeats: int):
    """Compile/warm once, then min of `repeats` settled runs, in ms.
    Returns (ms, warm_output)."""
    import jax
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(max(repeats, 1)):
        t0 = time.perf_counter()
        r = fn(*args)
        jax.block_until_ready(r)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3, out


def _synthesize(block, name: str, batch: int):
    """Zeros shaped like VarDesc `name` at its device dtype — how the
    profiler fills inputs no earlier segment produced (the @GRAD feeds
    of an optimizer segment, fetch-threaded pools)."""
    import jax.numpy as jnp
    from ..core.types import device_dtype, np_dtype
    v = block.var(name)
    shape = tuple(batch if int(d) == -1 else int(d) for d in (v.shape or ()))
    return jnp.zeros(shape, np_dtype(device_dtype(v.dtype)))


def _seg_reads_writes(ops, start: int, stop: int):
    reads: List[str] = []
    defined: set = set()
    writes: List[str] = []
    for op in ops[start:stop]:
        for n in op.input_names():
            if n not in defined and n not in reads:
                reads.append(n)
        for n in op.output_names():
            defined.add(n)
            if n not in writes:
                writes.append(n)
    return reads, writes


def _make_seg_fn(ops, start: int, stop: int, block, in_names, out_names,
                 amp):
    """A pure fn(dict of inputs) -> tuple(outputs) tracing ops[start:
    stop] through the SAME run_op_range the executor's lowering uses
    (remat runs checkpoint identically)."""
    import jax
    from ..core import lowering
    from ..core.registry import ExecContext

    def seg_fn(vals: Dict[str, object]):
        ctx = ExecContext(jax.random.PRNGKey(0), is_test=False)
        ctx.amp_dtype = amp
        e = dict(vals)
        e = lowering.run_op_range(ops, start, stop, e, ctx, block)
        return tuple(e[n] for n in out_names)

    return seg_fn


def _vjp_ms(seg_fn, inputs, warm_outs, repeats: int):
    """Measured forward+backward ms of one segment: jax.vjp over the
    float outputs with unit cotangents, float-only grads returned (int
    inputs produce float0 cotangents jit cannot ship)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    float_idx = [i for i, y in enumerate(warm_outs)
                 if jnp.issubdtype(jnp.result_type(y), jnp.floating)]
    if not float_idx:
        raise ValueError("no float outputs to differentiate")

    def fwd_float(vals):
        ys = seg_fn(vals)
        return tuple(ys[i] for i in float_idx)

    f0 = jax.dtypes.float0

    def fwdbwd(vals, cts):
        ys, pull = jax.vjp(fwd_float, vals)
        grads = pull(cts)
        flat = [g for g in jax.tree_util.tree_leaves(grads)
                if g.dtype != f0]
        return ys, tuple(flat)

    # shape/dtype-only inspection of the warm outputs — no host sync
    cts = tuple(np.ones(np.shape(warm_outs[i]), warm_outs[i].dtype)
                for i in float_idx)
    ms, _ = _time_call(jax.jit(fwdbwd), (inputs, cts), repeats)
    return ms


def _fused_step_ms(program, feed_arrays, state, repeats: int):
    """The real one-dispatch step (build_step_fn, no fetches), for the
    honesty line beside the profiled sum: separately-compiled segments
    lose cross-segment fusion and pay per-dispatch overhead, so the
    profiled total is an upper bound on the fused step."""
    import jax
    from ..core import lowering
    step, _ = lowering.build_step_fn(program, list(feed_arrays), [],
                                     sorted(state))
    fn = jax.jit(step)
    rng = jax.random.PRNGKey(0)
    ms, _ = _time_call(fn, (dict(state), dict(feed_arrays), rng), repeats)
    return ms


def profile_program(program: Optional[Program] = None,
                    feed: Optional[dict] = None, scope=None,
                    batch: Optional[int] = None,
                    repeats: Optional[int] = None,
                    seg_ops: Optional[int] = None, chip=None,
                    name: Optional[str] = None,
                    fused_step: bool = True,
                    publish_metrics: bool = True) -> OpLedger:
    """Measure + attribute one program's per-op device time.

    feed: host arrays for the program's data vars (missing ones are
    synthesized as zeros). scope: holds the persistable state (a scope
    the startup program initialized); absent vars synthesize as zeros —
    timing does not depend on values. batch: substituted for dynamic -1
    dims (default: inferred from the first feed array's leading dim).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..core.lowering import AUTODIFF_OP
    from ..core.types import device_dtype, np_dtype
    from . import trace as obs_trace

    program = program or default_main_program()
    block = program.global_block
    ops = block.ops
    amp = program.amp_dtype
    feed = dict(feed or {})
    repeats = repeats if repeats is not None else _knob_int(
        REPEATS_ENV, DEFAULT_REPEATS)
    seg_ops = seg_ops if seg_ops is not None else _knob_int(
        SEG_OPS_ENV, DEFAULT_SEG_OPS)
    chip = chip or _cost.resolve_chip()
    if batch is None:
        batch = next((int(np.shape(v)[0]) for v in feed.values()
                      if np.shape(v)), 1)

    bwd_idx = next((i for i, o in enumerate(ops)
                    if o.type == AUTODIFF_OP), None)
    train = bwd_idx is not None
    fwd_stop = bwd_idx if bwd_idx is not None else len(ops)

    # -- the starting environment: feeds + scope state ----------------------
    env: Dict[str, object] = {}
    for fname, val in feed.items():
        try:
            v = block.var(fname)
        except KeyError:
            continue
        arr = np.asarray(val)  # host-sync: ok — host feed conversion
        want = np_dtype(device_dtype(v.dtype))
        if arr.dtype != want:
            arr = arr.astype(want)
        env[fname] = jnp.asarray(arr)
    state: Dict[str, object] = {}
    read_names = {n for op in ops for n in op.input_names()}
    for vname in sorted(read_names):
        try:
            v = block.var(vname)
        except KeyError:
            continue
        if not v.persistable or vname in env:
            continue
        sv = scope.find_var(vname) if scope is not None \
            and scope.has_var(vname) else None
        state[vname] = sv if sv is not None else _synthesize(block, vname,
                                                             batch)
    env.update(state)
    # mirror the lowering's AMP entry: f32 feeds and declared params run
    # at the compute dtype inside the forward; the f32 masters return
    # for the optimizer suffix below
    orig_params: Dict[str, object] = {}
    if amp is not None and train:
        from ..core.types import CODEC_SCALE_SUFFIX
        adt = jnp.dtype(amp)
        for k in list(feed):
            if k in env and not k.endswith(CODEC_SCALE_SUFFIX) \
                    and jnp.result_type(env[k]) == jnp.float32:
                env[k] = env[k].astype(adt)
        for p in ops[bwd_idx].attrs.get("params", ()):
            if p in env and jnp.result_type(env[p]) == jnp.float32:
                orig_params[p] = env[p]
                env[p] = env[p].astype(adt)

    # -- per-op predicted costs --------------------------------------------
    ctx = _cost._Ctx(block, batch, amp)
    op_costs: Dict[int, _cost.OpCost] = {}
    for i, op in enumerate(ops):
        if op.type == AUTODIFF_OP:
            continue
        try:
            op_costs[i] = _cost._op_cost_ctx(op, ctx)
        except KeyError:
            op_costs[i] = _cost.OpCost(covered=False)

    segments: List[SegmentTiming] = []
    rows: List[OpRow] = []
    uncovered: List[str] = []

    seg_specs = _segments_for(ops, fwd_stop, len(ops), seg_ops)
    restored_masters = False
    for seg_id, (start, stop, phase, tag) in enumerate(seg_specs):
        if phase == "optimizer" and not restored_masters:
            env.update(orig_params)   # optimizer updates the f32 masters
            restored_masters = True
        seg = SegmentTiming(seg_id, start, stop, phase, tag,
                            [ops[k].type for k in range(start, stop)])
        reads, writes = _seg_reads_writes(ops, start, stop)
        # synthesize anything no earlier segment produced (@GRAD feeds,
        # loss-scale scalars) — zeros, value-independent timing
        for rname in reads:
            if rname in env:
                continue
            try:
                env[rname] = _synthesize(block, rname, batch)
            except KeyError:
                pass
        in_names = [n for n in reads if n in env]
        seg_fn = None
        warm = None
        for names in (in_names, sorted(env)):
            # sub-block ops (dynamic_rnn/while) read captured values the
            # OpDesc does not declare; retry with the full environment
            try:
                fn = _make_seg_fn(ops, start, stop, block, names, writes,
                                  amp)
                inputs = {n: env[n] for n in names}
                ms, warm = _time_call(jax.jit(fn), (inputs,), repeats)
                seg_fn, seg.measured_fwd_ms = fn, ms
                break
            except Exception as e:   # noqa: BLE001 — per-segment fallback
                seg.error = f"{type(e).__name__}: {e}"
        if seg_fn is not None:
            seg.error = None
            env.update(zip(writes, warm))
            if train and phase == "forward":
                try:
                    seg.measured_bwd_ms = max(
                        _vjp_ms(seg_fn, inputs, warm, repeats)
                        - seg.measured_fwd_ms, 0.0)
                except Exception:   # noqa: BLE001 — model the convention:
                    # 2x forward, 3x for remat runs (the backward re-runs
                    # their forward once more) — the same multipliers the
                    # attribution weights below use
                    seg.measured_bwd_ms = (
                        3.0 if tag is not None else 2.0
                    ) * seg.measured_fwd_ms
                    seg.bwd_modeled = True
        member_costs = {k: op_costs.get(k, _cost.OpCost(covered=False))
                        for k in range(start, stop)}
        seg.gap = bool(member_costs) and all(
            not c.covered for c in member_costs.values())
        segments.append(seg)

        # -- join: distribute measured time by predicted cost share --------
        remat = tag is not None
        fwd_w: Dict[int, float] = {}
        bwd_w: Dict[int, float] = {}
        op_bound: Dict[int, str] = {}
        for k, c in member_costs.items():
            ms_k, op_bound[k] = _cost.op_roofline_ms(c, chip)
            fwd_w[k] = ms_k
            # backward ~ 2x forward; remat segments re-run their forward
            # once more inside the backward (recompute)
            bwd_w[k] = ms_k * (3.0 if remat else 2.0)
        sum_fw = sum(fwd_w.values())
        sum_bw = sum(bwd_w.values())
        n_members = max(len(member_costs), 1)
        for k, c in member_costs.items():
            op = ops[k]
            outs = op.output_names()
            is_fwd_phase = phase == "forward"
            pred_bwd = bwd_w[k] if (train and is_fwd_phase) else 0.0
            predicted = fwd_w[k] + pred_bwd
            mf = mb = measured = None
            if seg.measured_fwd_ms is not None:
                fshare = (fwd_w[k] / sum_fw if sum_fw > 0
                          else 1.0 / n_members)
                mf = seg.measured_fwd_ms * fshare
                if seg.measured_bwd_ms is not None:
                    bshare = (bwd_w[k] / sum_bw if sum_bw > 0
                              else 1.0 / n_members)
                    mb = seg.measured_bwd_ms * bshare
                measured = mf + (mb or 0.0)
            mxu = c.mxu_flops * (3 if (train and is_fwd_phase) else 1)
            bound = op_bound[k]
            # measured per-op MFU: capped at the hardware ceiling — a
            # cost-share slice smaller than the op's own compute floor
            # is an attribution artifact, and >100% MFU is impossible
            mfu = (min(100.0, 100.0 * mxu / (measured / 1e3)
                       / chip.peak_flops)
                   if measured else None)
            pmfu = (100.0 * mxu / (predicted / 1e3) / chip.peak_flops
                    if predicted > 0 else None)
            rows.append(OpRow(
                index=k, op_type=op.type,
                name=outs[0] if outs else f"{op.type}.{k}",
                phase=phase, segment=seg_id, predicted_ms=predicted,
                measured_ms=measured, measured_fwd_ms=mf,
                measured_bwd_ms=mb, mxu_flops=mxu, mfu_pct=mfu,
                predicted_mfu_pct=pmfu, bound=bound,
                share_pct=None, covered=c.covered))
            if not c.covered and op.type not in uncovered:
                uncovered.append(op.type)

    total_measured = sum(s.measured_ms or 0.0 for s in segments)
    total_predicted = sum(r.predicted_ms for r in rows)
    gap_ms = sum(s.measured_ms or 0.0 for s in segments if s.gap)
    if total_measured > 0:
        coverage = 100.0 * (total_measured - gap_ms) / total_measured
        for r in rows:
            if r.measured_ms is not None:
                r.share_pct = 100.0 * r.measured_ms / total_measured
    else:
        # nothing measured: 100% would let a run where EVERY segment
        # failed sail through coverage gates with zero actual readings —
        # exactly the silently-zero failure mode this module exists to
        # prevent. Any gap or error reports 0.
        coverage = (0.0 if any(s.gap or s.error for s in segments)
                    else 100.0)

    fused_ms = None
    if fused_step:
        try:
            feed_arrays = {k: env[k] for k in feed if k in env}
            fused_ms = _fused_step_ms(program, feed_arrays, state, repeats)
        except Exception:   # noqa: BLE001 — honesty line, never fatal
            fused_ms = None

    try:
        fp = str(program.fingerprint())
    except Exception:   # noqa: BLE001
        fp = None
    pname = name or (fp[:12] if fp else "program")
    ledger = OpLedger(program=pname, batch=batch, chip=chip.name,
                      train=train, rows=rows, segments=segments,
                      total_measured_ms=total_measured,
                      total_predicted_ms=total_predicted,
                      coverage_pct=coverage, fused_step_ms=fused_ms,
                      uncovered_ops=uncovered, fingerprint=fp)

    # merge the measured intervals into the Chrome-trace timeline: with
    # PT_TRACE armed (and PT_TRACE_DIR set for the device profile), the
    # Perfetto view shows host spans and device attribution together
    if obs_trace.enabled():
        for s in segments:
            if s.measured_ms is not None:
                obs_trace.complete(
                    f"opprof:seg{s.seg_id}", s.measured_ms / 1e3,
                    cat="opprof", phase=s.phase, n_ops=len(s.op_types),
                    gap=s.gap)
        for r in ledger.top(_knob_int(TOPK_ENV, DEFAULT_TOPK)):
            if r.measured_ms is not None:
                obs_trace.complete(
                    f"op:{r.op_type}:{r.name}", r.measured_ms / 1e3,
                    cat="opprof", predicted_ms=round(r.predicted_ms, 5),
                    bound=r.bound)

    if publish_metrics:
        publish(ledger)
    return ledger


# ---------------------------------------------------------------------------
# pt_op_* metric family
# ---------------------------------------------------------------------------

class OpProfMetrics:
    """A frozen ledger summary as a metrics provider: top-K laggards by
    measured share + the attribution-coverage gauge, rendered as the
    pt_op_* family by obs/metrics.render_prometheus."""

    def __init__(self, name: str, summary: dict):
        self.name = name
        self._summary = summary

    def snapshot(self) -> dict:
        return dict(self._summary)


#: strong refs — the REGISTRY holds providers weakly, and a published
#: ledger must outlive the profiling call that produced it. LRU-bounded
#: like the drift monitor: a long-lived service profiling rebuilt
#: programs (fingerprint changes with any graph change) must not grow
#: memory — or the scrape — forever with rows for dead programs.
MAX_PUBLISHED = 64
_PUBLISHED: "OrderedDict[str, OpProfMetrics]" = OrderedDict()


def publish(ledger: OpLedger, name: Optional[str] = None) -> OpProfMetrics:
    """Register the ledger's summary on the unified metrics plane
    (section "op") — one scrape then carries the laggard list beside
    pt_train_* / pt_model_*."""
    from .metrics import REGISTRY
    key = name or ledger.program
    prov = OpProfMetrics(key, ledger.summary())
    _PUBLISHED[key] = prov
    _PUBLISHED.move_to_end(key)
    while len(_PUBLISHED) > MAX_PUBLISHED:
        old_key, _old = _PUBLISHED.popitem(last=False)
        REGISTRY.unregister("op", old_key)
    REGISTRY.register("op", key, prov)
    return prov
