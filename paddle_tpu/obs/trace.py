"""Structured tracing: the span core of the unified observability plane.

Every plane of the runtime — executor phases, trainer step/epoch/
checkpoint events, data-pipeline stages, the serving request lifecycle —
times itself already; what was missing is ONE causal timeline they all
land on. A `span` is a named, timed interval with attributes; finished
spans become Chrome-trace events (the JSON the Perfetto / chrome://
tracing UIs load natively, written by tools/trace_dump.py) in a bounded
process-wide ring buffer, so "why was this step/request slow" is
answerable from one artifact instead of four metric snapshots.

Design constraints, in order:

  1. near-zero cost off. Tracing is armed by ``PT_TRACE`` (read per
     call — one dict lookup — so it can be toggled at runtime); when
     off, ``span()`` returns a shared no-op and ``emit`` paths return
     before building anything. The documented budget is <= 1% on the
     disabled path (bench.py emits the measured ``trace_overhead_pct``
     per training config; tests pin a per-call bound).
  2. bounded memory. Events land in a ring (``PT_TRACE_BUF`` events,
     default 16384, re-read whenever the ring is recreated) — a long
     run_loop keeps the NEWEST window, it never grows.
  3. thread-correct. The active-span stack is thread-local: spans
     opened on a serving dispatcher thread or a map_batches worker can
     never parent under another thread's trainer step. Cross-thread
     causality is EXPLICIT: capture `current_context()` where the work
     is submitted and pass it as ``parent=`` (or enter
     ``use_context()``) where it runs — the serving batcher does
     exactly this to thread a request id from HTTP ingress through the
     dispatcher.

Clocks are monotonic (`time.perf_counter`), with one process-wide
origin, so events from every thread and plane share one timeline.

``PT_TRACE_DIR`` additionally arms `device_profile()` — a
`jax.profiler.trace` session writing device-side op attribution (the
per-op `jax.named_scope`s from core/lowering.py) next to the host-side
spans; the Trainer enters it around the training loop.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional

__all__ = ["span", "instant", "complete", "enabled", "current_context",
           "use_context", "active_stack", "events", "drain", "reset",
           "new_id", "device_profile", "postmortem_dump", "ENABLE_ENV",
           "BUF_ENV", "DIR_ENV", "DEFAULT_BUF"]

ENABLE_ENV = "PT_TRACE"
BUF_ENV = "PT_TRACE_BUF"
DIR_ENV = "PT_TRACE_DIR"
DEFAULT_BUF = 16384

#: values of PT_TRACE that mean "off" (mirrors flags._Flags bool parse)
_OFF = ("", "0", "false", "no", "off")

#: one timeline origin for every thread and plane
_T0 = time.perf_counter()

_ids = itertools.count(1)          # span/trace ids (next() is atomic)
_ring_lock = threading.Lock()
_ring: Optional[deque] = None      # created lazily; maxlen from env


class _TLS(threading.local):
    def __init__(self):
        self.stack: List["Span"] = []     # open spans, innermost last
        self.ctx: Optional[dict] = None   # inherited cross-thread context


_tls = _TLS()


def enabled() -> bool:
    """Is tracing armed? One env-dict lookup — cheap enough to call on
    every would-be span, and toggleable at runtime (tests, bench A/B)."""
    return os.environ.get(ENABLE_ENV, "0").strip().lower() not in _OFF


def new_id() -> int:
    """A fresh process-unique id (request ids, trace ids)."""
    return next(_ids)


def _now_us() -> float:
    return (time.perf_counter() - _T0) * 1e6


def _buf_size() -> int:
    raw = os.environ.get(BUF_ENV, "").strip()
    if not raw:
        return DEFAULT_BUF
    try:
        n = int(raw)
    except ValueError:
        return DEFAULT_BUF
    return n if n > 0 else DEFAULT_BUF


def _append(event: dict) -> None:
    global _ring
    with _ring_lock:
        if _ring is None:
            _ring = deque(maxlen=_buf_size())
        _ring.append(event)


def _event(name: str, cat: str, ph: str, ts_us: float, dur_us: float,
           trace_id: Optional[int], span_id: Optional[int],
           parent_id: Optional[int], attrs: Optional[dict]) -> dict:
    args: Dict[str, object] = dict(attrs) if attrs else {}
    if trace_id is not None:
        args["trace_id"] = trace_id
    if span_id is not None:
        args["span_id"] = span_id
    if parent_id is not None:
        args["parent_id"] = parent_id
    ev = {"name": name, "cat": cat, "ph": ph,
          "ts": round(ts_us, 1), "pid": os.getpid(),
          "tid": threading.get_ident(), "args": args}
    if ph == "X":
        ev["dur"] = round(dur_us, 1)
    else:
        ev["s"] = "t"   # instant scope: thread
    return ev


class _Noop:
    """Shared no-op span for the disabled path: supports the context
    protocol and the Span surface, allocates nothing per use."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **attrs):
        return self


NOOP = _Noop()


class Span:
    """One open interval on this thread's stack. Entering pushes it
    (children parent under it); exiting pops and emits the Chrome-trace
    "X" event. Create via `span()`."""

    __slots__ = ("name", "cat", "attrs", "trace_id", "span_id",
                 "parent_id", "_t0")

    def __init__(self, name: str, cat: str, attrs: dict,
                 parent: Optional[dict]):
        self.name = name
        self.cat = cat
        self.attrs = attrs
        top = _tls.stack[-1] if _tls.stack else None
        if top is not None:
            self.trace_id, self.parent_id = top.trace_id, top.span_id
        else:
            ctx = parent if parent is not None else _tls.ctx
            if ctx:
                self.trace_id = ctx.get("trace_id")
                self.parent_id = ctx.get("span_id")
            else:
                self.trace_id, self.parent_id = new_id(), None
        self.span_id = new_id()

    def annotate(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._t0 = _now_us()
        _tls.stack.append(self)
        return self

    def __exit__(self, *exc):
        # defensive pop: a mis-nested exit must not corrupt the stack
        if _tls.stack and _tls.stack[-1] is self:
            _tls.stack.pop()
        elif self in _tls.stack:
            _tls.stack.remove(self)
        t1 = _now_us()
        _append(_event(self.name, self.cat, "X", self._t0,
                       t1 - self._t0, self.trace_id, self.span_id,
                       self.parent_id, self.attrs))
        return False


def span(name: str, cat: str = "app", parent: Optional[dict] = None,
         **attrs):
    """Open a span: ``with trace.span("step", cat="train", epoch=e):``.
    Returns the shared no-op when tracing is off. `parent` (a
    `current_context()` dict) overrides the thread's inherited context
    when this thread's stack is empty — explicit cross-thread
    causality."""
    if not enabled():
        return NOOP
    return Span(name, cat, dict(attrs), parent)


def instant(name: str, cat: str = "app", parent: Optional[dict] = None,
            **attrs) -> None:
    """A zero-duration marker (guard anomaly, eviction, epoch edge)."""
    if not enabled():
        return
    ctx = _context_or(parent)
    _append(_event(name, cat, "i", _now_us(), 0.0,
                   ctx.get("trace_id") if ctx else None, new_id(),
                   ctx.get("span_id") if ctx else None, attrs))


def complete(name: str, dur_s: float, cat: str = "app",
             parent: Optional[dict] = None, end_ts: Optional[float] = None,
             **attrs) -> None:
    """Emit an already-measured interval ending now (or at `end_ts`, a
    `time.perf_counter()` reading) — the hook the existing timers use:
    PhaseTimer.add / PipelineMetrics.add know a duration, not a span
    object. Parented like span(): this thread's stack, else `parent`,
    else the inherited context."""
    if not enabled():
        return
    end_us = (_now_us() if end_ts is None
              else (end_ts - _T0) * 1e6)
    ctx = _context_or(parent)
    _append(_event(name, cat, "X", end_us - dur_s * 1e6, dur_s * 1e6,
                   ctx.get("trace_id") if ctx else None, new_id(),
                   ctx.get("span_id") if ctx else None, attrs))


def _context_or(parent: Optional[dict]) -> Optional[dict]:
    if _tls.stack:
        top = _tls.stack[-1]
        return {"trace_id": top.trace_id, "span_id": top.span_id}
    if parent is not None:
        return parent
    return _tls.ctx


def current_context() -> Optional[dict]:
    """{"trace_id", "span_id"} of the innermost open span on THIS
    thread (or the inherited context), or None. Capture it where work
    is submitted; pass it as `parent=` / `use_context()` where the work
    runs on another thread."""
    return _context_or(None)


def current_attrs() -> dict:
    """Provenance view of the innermost open span: its ids plus its
    attributes (a trainer step span carries epoch=/step=). Empty when
    tracing is off or no span is open — callers layer their own
    plumbing only in that case (the LazyFetch provenance contract)."""
    if not _tls.stack:
        return {}
    top = _tls.stack[-1]
    out = dict(top.attrs)
    out["span"] = f"{top.cat}:{top.name}#{top.span_id}"
    out["trace_id"] = top.trace_id
    return out


@contextmanager
def use_context(ctx: Optional[dict]):
    """Adopt a captured context as this thread's root parent (worker
    threads executing submitted work)."""
    prev = _tls.ctx
    _tls.ctx = ctx
    try:
        yield
    finally:
        _tls.ctx = prev


def active_stack() -> List[dict]:
    """This thread's open spans, outermost first — what the step
    watchdog attaches to a StepHungError dump (which phase/stage/
    request was in flight when the step hung)."""
    return [{"name": s.name, "cat": s.cat, "span_id": s.span_id,
             "trace_id": s.trace_id, "attrs": dict(s.attrs)}
            for s in _tls.stack]


def events() -> List[dict]:
    """Snapshot of the ring buffer (oldest first), non-destructive."""
    with _ring_lock:
        return list(_ring) if _ring is not None else []


def drain() -> List[dict]:
    """Pop every buffered event (tools/trace_dump.py's source)."""
    global _ring
    with _ring_lock:
        out = list(_ring) if _ring is not None else []
        _ring = None
    return out


def reset(buf: Optional[int] = None) -> None:
    """Clear the buffer; the next event re-reads PT_TRACE_BUF (or uses
    `buf`) for the ring size."""
    global _ring
    with _ring_lock:
        _ring = deque(maxlen=int(buf)) if buf else None


def postmortem_dump(tag: str, error: Optional[str] = None) -> Optional[str]:
    """Crash-forensics mini-bundle: when PT_TRACE_DIR is set, write the
    trace ring (non-destructive snapshot), this thread's active span
    stack, and the merged metrics snapshot as ONE JSON file beside the
    jax.profiler dir — the Trainer calls this when it escalates
    StepAnomalyError / StepHungError, so the evidence of the dying run
    (which step, which spans were open, what every gauge last read)
    survives the process. Returns the path, or None when unarmed; never
    raises — forensics must not mask the original error."""
    out_dir = os.environ.get(DIR_ENV, "").strip()
    if not out_dir:
        return None
    try:
        import json
        from .metrics import global_snapshot
        doc = {"reason": str(tag), "error": error, "pid": os.getpid(),
               "unix_time": time.time(),
               "active_spans": active_stack(),
               "trace_events": events(),
               "metrics": global_snapshot()}
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(
            out_dir, f"pt_postmortem_{os.getpid()}_{tag}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)
        return path
    except Exception:   # noqa: BLE001 — never mask the escalating error
        return None


@contextmanager
def device_profile():
    """jax.profiler.trace session under PT_TRACE_DIR (and PT_TRACE on):
    device-side op attribution written beside the host-side spans. A
    no-op when unarmed; profiler failures never break the caller (the
    Trainer wraps its whole loop in this)."""
    log_dir = os.environ.get(DIR_ENV, "").strip()
    if not log_dir or not enabled():
        yield
        return
    try:
        import jax
        prof = jax.profiler.trace(log_dir)
        prof.__enter__()
    except Exception:   # noqa: BLE001 — observability must not kill runs
        yield
        return
    try:
        yield
    finally:
        try:
            prof.__exit__(None, None, None)
        except Exception:   # noqa: BLE001
            pass
