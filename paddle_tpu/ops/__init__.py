"""Operator library. Importing this package registers all ops.

≙ reference paddle/fluid/operators/ (~264 registered op types; static
registration via REGISTER_OPERATOR, op_registry.h:136). Here registration is
import-time Python decoration — same effect, no static-initializer dance.
"""

from . import math_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import control_ops  # noqa: F401
from . import flow_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import beam_ops  # noqa: F401
from . import crf_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import pipeline_ops  # noqa: F401
from . import moe_ops  # noqa: F401
from . import volumetric_ops  # noqa: F401
from . import fused_ops  # noqa: F401

from ..core.registry import registered_ops  # noqa: F401
