"""Attention ops.

The reference has no attention op (2018): attention is composed from
mul/softmax ops (python/paddle/fluid/nets.py scaled_dot_product_attention,
tests/book machine_translation attention decoder). Here attention is a
first-class op so the TPU lowering can pick the right kernel:

* single chip / no sp axis — flash-attention Pallas kernel on TPU,
  XLA reference path elsewhere (kernels/flash_attention.py);
* mesh with an `sp` axis — ring attention (ppermute ring over ICI) or
  Ulysses all-to-all sequence parallelism (parallel/ring.py), entered via
  shard_map *inside* the jitted program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

from ..core.registry import register_op


def _sdpa_infer(op, block):
    q = block.var(op.input("Q")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = q.shape, q.dtype


@register_op("scaled_dot_product_attention", infer_shape=_sdpa_infer)
def scaled_dot_product_attention(ctx, ins, attrs):
    """Q,K,V: [B, S, H, D]. Optional BiasMask input: additive [.., Sq, Sk].

    attrs:
      causal:  bool
      scale:   float; 0.0 means 1/sqrt(D)
      sp_mode: "none" | "ring" | "ulysses" — how to use a mesh `sp` axis
    """
    from ..kernels.flash_attention import dot_product_attention
    from ..parallel.ring import ring_attention, ulysses_attention
    from ..parallel.mesh import DP, SP, TP

    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins["BiasMask"][0] if ins.get("BiasMask") else None
    causal = bool(attrs.get("causal", False))
    scale = attrs.get("scale", 0.0) or None
    sp_mode = attrs.get("sp_mode", "none")

    mesh = ctx.mesh
    sp = mesh.shape.get(SP, 1) if mesh is not None else 1
    tp = mesh.shape.get(TP, 1) if mesh is not None else 1
    hdim = TP if (tp > 1 and q.shape[2] % tp == 0) else None
    heads_local = q.shape[2] // (tp if hdim else 1)
    use_sp = sp_mode in ("ring", "ulysses") and sp > 1
    if use_sp:
        # sp was explicitly requested for a multi-chip sp mesh — falling
        # back to full attention would silently reintroduce the O(S²)
        # per-device profile sp exists to avoid, so unmet preconditions
        # are errors (shapes are static: this fires at trace time).
        problems = []
        if bias is not None:
            problems.append("explicit bias/mask is unsupported under sp")
        if q.shape[1] != k.shape[1]:
            problems.append(f"sq={q.shape[1]} != sk={k.shape[1]}")
        if q.shape[1] % sp:
            problems.append(f"seq {q.shape[1]} not divisible by sp={sp}")
        if sp_mode == "ulysses" and heads_local % sp:
            problems.append(f"{heads_local} local heads not divisible by "
                            f"sp={sp} (ulysses shards heads)")
        if problems:
            raise ValueError(
                f"scaled_dot_product_attention(sp_mode={sp_mode!r}) cannot "
                f"shard over sp={sp}: " + "; ".join(problems))
    if not use_sp:
        out = dot_product_attention(q, k, v, bias, causal=causal,
                                    scale=scale)
        # name the output so remat_scope(policy="save_attn") can keep it
        # as a saved primal (the expensive flash forward is then NOT
        # recomputed in the backward; the saved value is O(S·D))
        from jax.ad_checkpoint import checkpoint_name
        out = checkpoint_name(out, "flash_attn_out")
        return {"Out": [out]}

    dp = mesh.shape.get(DP, 1)
    bdim = DP if (dp > 1 and q.shape[0] % dp == 0) else None
    # batch on dp, sequence on sp, heads on tp (each head independent)
    spec = PartitionSpec(bdim, SP, hdim, None)
    inner = ring_attention if sp_mode == "ring" else ulysses_attention

    def local(q, k, v):
        return inner(q, k, v, axis_name=SP, causal=causal, scale=scale)

    from ..core.compat import shard_map
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec, check_vma=False)
    # same tag as the single-chip path so remat_scope(policy="save_attn")
    # keeps the (ring/ulysses) attention output instead of silently
    # degrading to full recompute under sp
    from jax.ad_checkpoint import checkpoint_name
    return {"Out": [checkpoint_name(fn(q, k, v), "flash_attn_out")]}


# ---------------------------------------------------------------------------
# Paged decode ops (serving/decode): one token per sequence slot against a
# block-paged KV pool. Inference-only — no grad rule needed; the decode
# program is built is_test and never differentiated.
# ---------------------------------------------------------------------------

def _paged_write_infer(op, block):
    for pool_in, pool_out in (("KPool", "KOut"), ("VPool", "VOut")):
        src = block.var(op.input(pool_in)[0])
        dst = block.var(op.output(pool_out)[0])
        dst.shape, dst.dtype = src.shape, src.dtype


@register_op("paged_kv_write", infer_shape=_paged_write_infer)
def paged_kv_write(ctx, ins, attrs):
    """Scatter each slot's new K/V row ([S, 1, H, D]) into its page of the
    pool ([NB, BS, H, D]) at position ContextLens-1. Slots with
    ContextLens 0 write into the reserved null block 0."""
    from ..kernels.flash_attention import paged_kv_update

    k, v = ins["K"][0], ins["V"][0]
    ko, vo = paged_kv_update(ins["KPool"][0], ins["VPool"][0],
                             k[:, 0], v[:, 0],
                             ins["BlockTables"][0], ins["ContextLens"][0])
    return {"KOut": [ko], "VOut": [vo]}


def _paged_attn_infer(op, block):
    q = block.var(op.input("Q")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = q.shape, q.dtype


@register_op("paged_attention", infer_shape=_paged_attn_infer)
def paged_attention(ctx, ins, attrs):
    """Q: [S, 1, H, D] (one decode token per slot) against the paged pool
    through the per-slot block table; ContextLens is the span INCLUDING
    the just-written token. Pallas kernel on TPU shapes, gather-based XLA
    reference elsewhere (kernels/flash_attention.py)."""
    from ..kernels.flash_attention import paged_decode_attention

    q = ins["Q"][0]
    scale = attrs.get("scale", 0.0) or None
    out = paged_decode_attention(q[:, 0], ins["KPool"][0], ins["VPool"][0],
                                 ins["BlockTables"][0],
                                 ins["ContextLens"][0], scale=scale)
    return {"Out": [out[:, None]]}
