"""Beam-search ops, dense TPU formulation.

≙ reference beam_search_op.cc / beam_search_decode_op.cc. The reference
keeps candidate sets in 2-level LoDTensors and does per-sequence heap
selection on the host; here beams live on a fixed [B, W] lane layout so one
`lax.top_k` over the flattened [B, W*V] joint scores does the selection on
device, inside the decode scan, with no host round-trip.

Conventions:
  * `pre_ids` [B, W] int — token chosen by each beam at the previous step.
  * `pre_scores` [B, W] float — accumulated log-prob per beam.
  * `scores` [B, W, V] float — this step's distribution per beam
    (probabilities by default; `log_probs=True` if already in log domain).
  * finished beams (pre_ids == end_id) are frozen: their only continuation
    is end_id at unchanged score, mirroring beam_search_op.cc's pruning of
    ended hypotheses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op

_NEG_INF = -1e9


@register_op("beam_search")
def beam_search(ctx, ins, attrs):
    """One beam expansion step (≙ BeamSearch::operator() beam_search_op.cc).

    Outputs: selected_ids [B, W], selected_scores [B, W], parent_idx [B, W]
    (which source beam each selected hypothesis extends — the dense
    equivalent of the LoD the reference threads through its candidates).
    """
    pre_ids = ins["pre_ids"][0]
    pre_scores = ins["pre_scores"][0]
    scores = ins["scores"][0]
    W = int(attrs["beam_size"])
    end_id = int(attrs["end_id"])
    B, Wi, V = scores.shape

    logp = scores if attrs.get("log_probs", False) else jnp.log(
        jnp.maximum(scores, 1e-20))
    pre_ids2 = pre_ids.reshape(B, Wi)
    finished = pre_ids2 == end_id
    total = pre_scores.reshape(B, Wi, 1) + logp
    # frozen beams: only end_id survives, score carried through unchanged
    onehot_end = jnp.arange(V)[None, None, :] == end_id
    frozen = jnp.where(onehot_end, pre_scores.reshape(B, Wi, 1), _NEG_INF)
    total = jnp.where(finished[:, :, None], frozen, total)

    flat = total.reshape(B, Wi * V)
    sel_scores, flat_idx = jax.lax.top_k(flat, W)
    parent = (flat_idx // V).astype(jnp.int32)
    sel_ids = (flat_idx % V).astype(pre_ids.dtype)
    return {"selected_ids": [sel_ids], "selected_scores": [sel_scores],
            "parent_idx": [parent]}


@register_op("beam_search_decode")
def beam_search_decode(ctx, ins, attrs):
    """Backtrack stacked per-step selections into full sentences
    (≙ BeamSearchDecoder::Backtrace, beam_search_decode_op.cc).

    Inputs: Ids [B, T, W], ParentIdx [B, T, W], Scores [B, T, W] (per-step
    accumulated scores; final sentence score = last step's).
    Outputs: SentenceIds [B, W, T] (end_id-padded after termination),
    SentenceScores [B, W].
    """
    ids = ins["Ids"][0]
    parents = ins["ParentIdx"][0].astype(jnp.int32)
    scores = ins["Scores"][0]
    end_id = int(attrs["end_id"])
    B, T, W = ids.shape

    ids_tm = jnp.moveaxis(ids, 1, 0)        # [T, B, W]
    par_tm = jnp.moveaxis(parents, 1, 0)

    def back(beam_ptr, step):
        step_ids, step_par = step
        tok = jnp.take_along_axis(step_ids, beam_ptr, axis=1)
        prev = jnp.take_along_axis(step_par, beam_ptr, axis=1)
        return prev, tok

    init_ptr = jnp.tile(jnp.arange(W, dtype=jnp.int32)[None, :], (B, 1))
    _, toks_rev = jax.lax.scan(back, init_ptr, (ids_tm[::-1], par_tm[::-1]))
    sent = jnp.moveaxis(toks_rev[::-1], 0, 2)   # [B, W, T]

    # pad everything after the first end_id with end_id
    is_end = sent == end_id
    seen_end = jnp.cumsum(is_end.astype(jnp.int32), axis=2) - is_end.astype(jnp.int32)
    sent = jnp.where(seen_end > 0, jnp.asarray(end_id, sent.dtype), sent)
    final_scores = scores[:, -1, :]
    return {"SentenceIds": [sent], "SentenceScores": [final_scores]}


@register_op("sequence_mask")
def sequence_mask(ctx, ins, attrs):
    """sequence_mask: lengths [B] -> [B, maxlen] 0/1 mask (dense analogue of
    the LoD boundary info every LoD op consults implicitly)."""
    from .sequence_ops import time_mask
    x = ins["X"][0]
    if ins.get("MaxLenRef"):
        maxlen = ins["MaxLenRef"][0].shape[1]   # static at trace time
    else:
        maxlen = int(attrs["maxlen"])
    dtype = attrs.get("out_dtype", "float32")
    return {"Y": [time_mask(x.reshape(-1), maxlen, dtype)]}


@register_op("batch_gather")
def batch_gather(ctx, ins, attrs):
    """Per-row gather: X [B, W, ...], Index [B, K] -> [B, K, ...]. The dense
    analogue of the beam-state reorder the reference performs implicitly by
    threading LoD through beam_search_op's selected candidates (and of
    DynamicRNN memories' need_reorder path, control_flow.py:1313)."""
    x = ins["X"][0]
    idx = ins["Index"][0].astype(jnp.int32)
    idxe = idx.reshape(idx.shape + (1,) * (x.ndim - 2))
    return {"Out": [jnp.take_along_axis(x, idxe, axis=1)]}


@register_op("lod_reset")
def lod_reset(ctx, ins, attrs):
    """lod_reset_op.cc: re-associate data with a new sequence structure. On
    the padded representation the structure lives in VarDesc metadata, so
    the device computation is identity; the front-end layer rewires the
    @SEQ_LEN companion."""
    return {"Out": [ins["X"][0]]}
