"""Comparison, logical, and scalar-control ops.

≙ reference paddle/fluid/operators/{compare_op, logical_op, increment_op,
is_empty_op}. Block-structured control flow (while/conditional_block) lives
in ops/flow_ops.py since it needs sub-block lowering.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from .math_ops import broadcast_y_to_x


def _cmp_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, "bool"


def _register_compare(name, fn):
    def compute(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        return {"Out": [fn(x, broadcast_y_to_x(x, y, attrs.get("axis", -1)))]}
    register_op(name, infer_shape=_cmp_infer)(compute)


_register_compare("less_than", jnp.less)
_register_compare("less_equal", jnp.less_equal)
_register_compare("greater_than", jnp.greater)
_register_compare("greater_equal", jnp.greater_equal)
_register_compare("equal", jnp.equal)
_register_compare("not_equal", jnp.not_equal)


def _logical_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, "bool"


def _register_logical(name, fn, unary=False):
    def compute(ctx, ins, attrs):
        if unary:
            return {"Out": [fn(ins["X"][0])]}
        return {"Out": [fn(ins["X"][0], ins["Y"][0])]}
    register_op(name, infer_shape=_logical_infer)(compute)


_register_logical("logical_and", jnp.logical_and)
_register_logical("logical_or", jnp.logical_or)
_register_logical("logical_xor", jnp.logical_xor)
_register_logical("logical_not", jnp.logical_not, unary=True)


@register_op("increment")
def increment(ctx, ins, attrs):
    x = ins["X"][0]
    # dtype-preserving (increment_op.cc): an int counter must stay int —
    # loop carries depend on it
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


@register_op("is_empty")
def is_empty(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.asarray(x.size == 0)]}
