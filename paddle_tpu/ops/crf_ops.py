"""Structured-prediction ops: linear-chain CRF, Viterbi decoding, CTC loss,
CTC alignment, chunk evaluation.

≙ reference linear_chain_crf_op.cc, crf_decoding_op.cc, warpctc_op.cc
(external warp-ctc dynload), ctc_align_op.cu, chunk_eval_op.cc. The
reference runs these on the host or via hand-written CUDA/warp-ctc; here
each is a log-domain lax.scan over the padded time axis — fully
differentiable through scan's VJP (the reference needed warp-ctc's
hand-written gradient; CTC grads here come from jax.grad for free).

Transition layout follows the reference (linear_chain_crf_op.h):
Transition [N+2, N] with row 0 = start weights, row 1 = end weights,
rows 2.. = the N x N transition matrix.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .sequence_ops import time_mask

_NEG = -1e30


def _split_transition(w):
    return w[0], w[1], w[2:]  # start, end, trans[N,N]


@register_op("linear_chain_crf")
def linear_chain_crf(ctx, ins, attrs):
    """Negative log-likelihood of a linear-chain CRF
    (≙ LinearChainCRFOpKernel::Compute, linear_chain_crf_op.h).

    Emission [B,T,N], Transition [N+2,N], Label [B,T] or [B,T,1] int,
    SeqLen [B] -> LogLikelihood [B,1] (the reference's output name; its
    value is the *negative* log-likelihood used directly as the cost)."""
    em = ins["Emission"][0]
    w = ins["Transition"][0].astype(em.dtype)
    label = ins["Label"][0]
    seq_len = ins["SeqLen"][0]
    if label.ndim == 3:
        label = label.reshape(label.shape[:2])
    label = label.astype(jnp.int32)
    B, T, N = em.shape
    start, end, trans = _split_transition(w)
    mask = time_mask(seq_len, T, em.dtype)              # [B,T]
    t_idx = jnp.arange(T)

    # ---- log partition via forward algorithm -------------------------------
    alpha0 = start[None, :] + em[:, 0, :]               # [B,N]

    def fwd(alpha, inp):
        em_t, m = inp                                   # [B,N], [B]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + trans[None], axis=1) + em_t
        return jnp.where(m[:, None] > 0, nxt, alpha), None

    em_tm = jnp.moveaxis(em, 1, 0)
    alpha, _ = jax.lax.scan(fwd, alpha0, (em_tm[1:], mask.T[1:]))
    log_z = jax.nn.logsumexp(alpha + end[None, :], axis=1)     # [B]

    # ---- gold path score ---------------------------------------------------
    em_score = jnp.sum(
        jnp.take_along_axis(em, label[:, :, None], axis=2)[..., 0] * mask,
        axis=1)
    pair_valid = mask[:, 1:]                                  # [B,T-1]
    tr_score = jnp.sum(trans[label[:, :-1], label[:, 1:]] * pair_valid, axis=1)
    last_idx = jnp.maximum(seq_len - 1, 0).astype(jnp.int32)
    last_lbl = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = em_score + tr_score + start[label[:, 0]] + end[last_lbl]

    nll = (log_z - gold)[:, None]
    return {"LogLikelihood": [nll], "Alpha": [alpha],
            "EmissionExps": [jnp.exp(em)],
            "TransitionExps": [jnp.exp(w)]}


@register_op("crf_decoding")
def crf_decoding(ctx, ins, attrs):
    """Viterbi decode (≙ CRFDecodingOpKernel, crf_decoding_op.h).
    Without Label: ViterbiPath [B,T] = best tag ids (0 beyond length).
    With Label: [B,T] 0/1 where 1 marks positions whose decoded tag equals
    the label within the sequence (the reference's error-marking mode)."""
    em = ins["Emission"][0]
    w = ins["Transition"][0].astype(em.dtype)
    seq_len = ins["SeqLen"][0]
    B, T, N = em.shape
    start, end, trans = _split_transition(w)
    mask = time_mask(seq_len, T, em.dtype)

    delta0 = start[None, :] + em[:, 0, :]

    def vit(delta, inp):
        em_t, m = inp
        scores = delta[:, :, None] + trans[None]        # [B,N,N]
        best_prev = jnp.argmax(scores, axis=1)          # [B,N]
        nxt = jnp.max(scores, axis=1) + em_t
        keep = m[:, None] > 0
        return (jnp.where(keep, nxt, delta),
                jnp.where(keep, best_prev,
                          jnp.arange(N, dtype=best_prev.dtype)[None, :]))

    em_tm = jnp.moveaxis(em, 1, 0)
    delta, backptr = jax.lax.scan(vit, delta0, (em_tm[1:], mask.T[1:]))
    # backptr [T-1,B,N]; identity rows where step was masked
    last_tag = jnp.argmax(delta + end[None, :], axis=1).astype(jnp.int32)

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev.astype(jnp.int32), tag

    first_tag, tags_rev = jax.lax.scan(back, last_tag, backptr[::-1])
    path = jnp.concatenate([first_tag[None], tags_rev[::-1]], axis=0).T  # [B,T]
    # int32 on device: tag ids / hit flags never approach 2^31, and JAX
    # without x64 would silently truncate int64 anyway (executor feeds are
    # canonicalized the same way in core/executor.py).
    path = (path * mask.astype(path.dtype)).astype(jnp.int32)

    if ins.get("Label"):
        label = ins["Label"][0]
        if label.ndim == 3:
            label = label.reshape(label.shape[:2])
        hit = (path == label.astype(path.dtype)) & (mask > 0)
        return {"ViterbiPath": [hit.astype(jnp.int32)]}
    return {"ViterbiPath": [path]}


@register_op("warpctc")
def warpctc(ctx, ins, attrs):
    """CTC loss (≙ warpctc_op.cc, which dynloads Baidu warp-ctc). Log-domain
    alpha recursion over the extended blank-interleaved label, one lax.scan
    over time for the whole batch; gradients come from autodiff rather than
    warp-ctc's hand-written backward.

    Logits [B,T,C] raw (softmax applied internally, as warp-ctc does),
    Label [B,L] int (padded), LogitsLen [B], LabelLen [B] -> Loss [B,1]."""
    logits = ins["Logits"][0]
    labels = ins["Label"][0]
    if labels.ndim == 3:
        labels = labels.reshape(labels.shape[:2])
    labels = labels.astype(jnp.int32)
    logit_len = ins["LogitsLen"][0].astype(jnp.int32)
    label_len = ins["LabelLen"][0].astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    B, T, C = logits.shape
    L = labels.shape[1]
    S = 2 * L + 1

    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # extended sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((B, S), blank, jnp.int32).at[:, 1::2].set(labels)
    s_idx = jnp.arange(S)
    valid_s = s_idx[None, :] < (2 * label_len[:, None] + 1)
    # skip-transition allowed: s>=2, ext[s] != blank, ext[s] != ext[s-2]
    can_skip = (s_idx[None, :] >= 2) & (ext != blank) & \
        (ext != jnp.roll(ext, 2, axis=1))

    def emit(t):
        return jnp.take_along_axis(logp[:, t, :], ext, axis=1)  # [B,S]

    alpha = jnp.full((B, S), _NEG)
    alpha = alpha.at[:, 0].set(logp[:, 0, blank])
    alpha = alpha.at[:, 1].set(jnp.where(label_len > 0,
                                         emit(0)[:, 1], _NEG))

    def step(alpha, inp):
        logp_t, live = inp                              # [B,C], [B]
        em_t = jnp.take_along_axis(logp_t, ext, axis=1)
        prev1 = jnp.concatenate(
            [jnp.full((B, 1), _NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate(
            [jnp.full((B, 2), _NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, _NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        nxt = jnp.where(valid_s, merged + em_t, _NEG)
        return jnp.where(live[:, None] > 0, nxt, alpha), None

    live = time_mask(logit_len, T, jnp.float32).T[1:]   # [T-1,B]
    alpha, _ = jax.lax.scan(step, alpha, (jnp.moveaxis(logp, 1, 0)[1:], live))

    end1 = jnp.take_along_axis(alpha, (2 * label_len)[:, None], axis=1)[:, 0]
    end2_idx = jnp.maximum(2 * label_len - 1, 0)
    end2 = jnp.where(label_len > 0,
                     jnp.take_along_axis(alpha, end2_idx[:, None],
                                         axis=1)[:, 0], _NEG)
    total = jnp.logaddexp(end1, end2)
    # infeasible label/time combinations (e.g. repeats needing more frames
    # than available): warp-ctc reports zero cost and zero gradient rather
    # than a saturated sentinel; `where` cuts the gradient path too
    feasible = total > _NEG / 2
    loss = jnp.where(feasible, -total, 0.0)
    if attrs.get("norm_by_times", False):
        # the reference (warpctc_op.h) scales only the *gradient* by 1/T,
        # leaving the reported Loss untouched — reproduce that through
        # autodiff with a value-preserving, grad-scaling identity
        t = jnp.maximum(logit_len, 1).astype(loss.dtype)
        scaled = loss / t
        loss = scaled + jax.lax.stop_gradient(loss - scaled)
    return {"Loss": [loss[:, None]],
            "WarpCTCGrad": [jnp.zeros_like(logits)]}


@register_op("ctc_align")
def ctc_align(ctx, ins, attrs):
    """CTC greedy alignment (≙ ctc_align_op.cu): merge repeats, drop
    blanks. Input [B,T] int + SeqLen; Output [B,T] left-compacted ids
    padded with `padding_value`, plus OutLen [B]."""
    x = ins["Input"][0]
    if x.ndim == 3:
        x = x.reshape(x.shape[:2])
    seq_len = ins["SeqLen"][0]
    blank = int(attrs.get("blank", 0))
    pad = int(attrs.get("padding_value", 0))
    B, T = x.shape
    m = time_mask(seq_len, T, jnp.bool_)
    prev = jnp.concatenate([jnp.full((B, 1), -1, x.dtype), x[:, :-1]], axis=1)
    keep = (x != prev) & (x != blank) & m
    # stable left-compaction: order keeps first, preserving time order
    order = jnp.argsort(~keep, axis=1, stable=True)
    compact = jnp.take_along_axis(x, order, axis=1)
    out_len = keep.sum(axis=1).astype(jnp.int32)
    pos = jnp.arange(T)[None, :]
    out = jnp.where(pos < out_len[:, None], compact,
                    jnp.asarray(pad, x.dtype))
    return {"Output": [out], "OutLen": [out_len]}


def _chunk_marks(tags, types, scheme):
    """Per-position chunk start/end flags from scheme-coded labels.
    Encoding follows chunk_eval_op.h: label = type * num_tag + tag with
    tag order B,I (IOB) / I,E (IOE) / B,I,E,S (IOBES); `plain` = every
    position its own chunk."""
    if scheme == "plain":
        return jnp.ones_like(tags, bool), jnp.ones_like(tags, bool)
    prev_types = jnp.concatenate([jnp.full_like(types[:, :1], -1),
                                  types[:, :-1]], axis=1)
    next_types = jnp.concatenate([types[:, 1:],
                                  jnp.full_like(types[:, :1], -1)], axis=1)
    prev_tags = jnp.concatenate([jnp.full_like(tags[:, :1], -1),
                                 tags[:, :-1]], axis=1)
    next_tags = jnp.concatenate([tags[:, 1:],
                                 jnp.full_like(tags[:, :1], -1)], axis=1)
    if scheme == "IOB":      # tags: B=0, I=1
        start = (tags == 0) | ((tags == 1) & ((prev_types != types) |
                                              (prev_tags == -1)))
        end = ((next_tags == 0) | (next_types != types) | (next_tags == -1))
    elif scheme == "IOE":    # tags: I=0, E=1
        start = ((prev_tags == -1) | (prev_types != types) |
                 (prev_tags == 1))
        end = (tags == 1) | (next_types != types) | (next_tags == -1)
    elif scheme == "IOBES":  # B=0, I=1, E=2, S=3
        start = (tags == 0) | (tags == 3)
        end = (tags == 2) | (tags == 3)
    else:
        raise ValueError(f"unknown chunk scheme {scheme}")
    return start, end


@register_op("chunk_eval")
def chunk_eval(ctx, ins, attrs):
    """Chunk-level precision/recall/F1 (≙ chunk_eval_op.h). A chunk is
    correct when inference and label agree on (start, end, type). Matching
    is fully vectorized: each end position is annotated with its chunk's
    start via a running cummax over start positions."""
    inf = ins["Inference"][0]
    lab = ins["Label"][0]
    if inf.ndim == 3:
        inf = inf.reshape(inf.shape[:2])
    if lab.ndim == 3:
        lab = lab.reshape(lab.shape[:2])
    seq_len = ins["SeqLen"][0]
    num_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = list(attrs.get("excluded_chunk_types", []) or [])
    num_tag = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}[scheme]
    B, T = inf.shape
    m = time_mask(seq_len, T, jnp.bool_)
    pos = jnp.arange(T)[None, :]

    def analyze(x):
        x = x.astype(jnp.int32)
        inside = (x >= 0) & (x < num_types * num_tag) & m
        tags = jnp.where(inside, x % num_tag, -1)
        types = jnp.where(inside, x // num_tag, -1)
        for ex in excluded:
            inside = inside & (types != ex)
        start, end = _chunk_marks(tags, types, scheme)
        start = start & inside
        end = end & inside
        # start index of the chunk covering each position
        run_start = jax.lax.cummax(jnp.where(start, pos, -1), axis=1)
        return start, end, types, run_start, inside

    i_s, i_e, i_ty, i_run, i_in = analyze(inf)
    l_s, l_e, l_ty, l_run, l_in = analyze(lab)
    num_inf = i_e.sum()
    num_lab = l_e.sum()
    correct = (i_e & l_e & (i_run == l_run) & (i_ty == l_ty)).sum()

    p = correct / jnp.maximum(num_inf, 1)
    r = correct / jnp.maximum(num_lab, 1)
    f1 = jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)
    as_f = lambda v: jnp.asarray(v, jnp.float32).reshape(1)
    # int32 chosen explicitly: per-batch chunk counts are bounded by B*T
    # (far below 2^31); jnp.int64 without x64 truncates with a warning.
    as_i = lambda v: jnp.asarray(v, jnp.int32).reshape(1)
    return {"Precision": [as_f(p)], "Recall": [as_f(r)],
            "F1-Score": [as_f(f1)],
            "NumInferChunks": [as_i(num_inf)],
            "NumLabelChunks": [as_i(num_lab)],
            "NumCorrectChunks": [as_i(correct)]}
