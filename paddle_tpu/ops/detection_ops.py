"""Detection/vision op family.

≙ reference paddle/fluid/operators/detection/ (prior_box_op, box_coder_op,
multiclass_nms_op, bipartite_match_op, target_assign_op, mine_hard_examples
_op, box_clip, anchor_generator_op) + roi_pool_op. The reference's kernels
produce VARIABLE-size outputs carried in LoD; XLA needs static shapes, so
every op here is re-designed dense: fixed capacities with validity masks
(-1 labels / zero padding), the standard TPU detection formulation — and
batch/box loops become vectorized lax ops, never host loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op, same_shape


# ---------------------------------------------------------------------------
# Prior / anchor generation
# ---------------------------------------------------------------------------

def _prior_box_infer(op, block):
    x = block.var(op.input("Input")[0])
    h, w = x.shape[-2], x.shape[-1]
    n_ar = len(_expand_ars(op.attrs))
    n_priors = n_ar * len(op.attrs["min_sizes"]) + len(
        op.attrs.get("max_sizes", []))
    for slot in ("Boxes", "Variances"):
        v = block.var(op.output(slot)[0])
        v.shape = (h, w, n_priors, 4)
        v.dtype = "float32"


def _expand_ars(attrs):
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", [1.0]):
        if not any(abs(ar - x) < 1e-6 for x in ars):
            ars.append(float(ar))
            if attrs.get("flip", False):
                ars.append(1.0 / float(ar))
    return ars


@register_op("prior_box", infer_shape=_prior_box_infer)
def prior_box(ctx, ins, attrs):
    """prior_box_op.cc: SSD prior boxes per feature-map cell.

    Boxes/Variances: [H, W, n_priors, 4] in normalized xmin,ymin,xmax,ymax.
    """
    x, image = ins["Input"][0], ins["Image"][0]
    fh, fw = x.shape[-2], x.shape[-1]
    ih, iw = image.shape[-2], image.shape[-1]
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", [])]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    step_w = float(attrs.get("step_w", 0.0)) or iw / fw
    step_h = float(attrs.get("step_h", 0.0)) or ih / fh
    offset = float(attrs.get("offset", 0.5))
    ars = _expand_ars(attrs)

    widths, heights = [], []
    for ms in min_sizes:
        for ar in ars:
            widths.append(ms * (ar ** 0.5))
            heights.append(ms / (ar ** 0.5))
    for ms, mxs in zip(min_sizes, max_sizes):
        widths.append((ms * mxs) ** 0.5)
        heights.append((ms * mxs) ** 0.5)
    widths = jnp.asarray(widths, jnp.float32)      # [P]
    heights = jnp.asarray(heights, jnp.float32)

    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * step_w   # [W]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * step_h   # [H]
    cxg = cx[None, :, None]    # [1, W, 1]
    cyg = cy[:, None, None]    # [H, 1, 1]
    wg = widths[None, None, :] / 2.0
    hg = heights[None, None, :] / 2.0
    boxes = jnp.stack(jnp.broadcast_arrays(
        (cxg - wg) / iw, (cyg - hg) / ih,
        (cxg + wg) / iw, (cyg + hg) / ih), axis=-1)  # [H, W, P, 4]
    if attrs.get("clip", False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), boxes.shape)
    return {"Boxes": [boxes], "Variances": [var]}


def _anchor_infer(op, block):
    x = block.var(op.input("Input")[0])
    h, w = x.shape[-2], x.shape[-1]
    n = len(op.attrs["anchor_sizes"]) * len(op.attrs["aspect_ratios"])
    for slot in ("Anchors", "Variances"):
        v = block.var(op.output(slot)[0])
        v.shape = (h, w, n, 4)
        v.dtype = "float32"


@register_op("anchor_generator", infer_shape=_anchor_infer)
def anchor_generator(ctx, ins, attrs):
    """anchor_generator_op.cc (Faster-RCNN anchors, absolute coords)."""
    x = ins["Input"][0]
    fh, fw = x.shape[-2], x.shape[-1]
    sizes = jnp.asarray([float(s) for s in attrs["anchor_sizes"]])
    ars = jnp.asarray([float(a) for a in attrs["aspect_ratios"]])
    stride = [float(s) for s in attrs.get("stride", [16.0, 16.0])]
    variances = [float(v) for v in attrs.get("variances",
                                             [0.1, 0.1, 0.2, 0.2])]
    offset = float(attrs.get("offset", 0.5))

    ar_sqrt = jnp.sqrt(ars)                        # [A]
    ws = (sizes[None, :] / ar_sqrt[:, None]).reshape(-1)   # [A*S]
    hs = (sizes[None, :] * ar_sqrt[:, None]).reshape(-1)
    cx = (jnp.arange(fw, dtype=jnp.float32) + offset) * stride[0]
    cy = (jnp.arange(fh, dtype=jnp.float32) + offset) * stride[1]
    cxg = cx[None, :, None]
    cyg = cy[:, None, None]
    anchors = jnp.stack(jnp.broadcast_arrays(
        cxg - ws / 2, cyg - hs / 2, cxg + ws / 2, cyg + hs / 2), axis=-1)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
    return {"Anchors": [anchors], "Variances": [var]}


# ---------------------------------------------------------------------------
# Box arithmetic
# ---------------------------------------------------------------------------

def _center_form(boxes):
    w = boxes[..., 2] - boxes[..., 0]
    h = boxes[..., 3] - boxes[..., 1]
    return (boxes[..., 0] + w / 2, boxes[..., 1] + h / 2, w, h)


@register_op("box_coder")
def box_coder(ctx, ins, attrs):
    """box_coder_op.cc: encode targets against priors, or decode offsets.

    PriorBox [M,4], TargetBox encode: [M,4] / decode: [N,M,4] (or [M,4]).
    """
    prior = ins["PriorBox"][0]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    one = 0.0 if norm else 1.0

    pcx, pcy, pw, ph = _center_form(prior)
    pw = pw + one
    ph = ph + one
    if pvar is None:
        pvar = jnp.ones(prior.shape[-1:], prior.dtype)

    if code_type.startswith("encode"):
        tcx, tcy, tw, th = _center_form(target)
        tw = tw + one
        th = th + one
        out = jnp.stack([
            (tcx - pcx) / pw / pvar[..., 0],
            (tcy - pcy) / ph / pvar[..., 1],
            jnp.log(jnp.maximum(tw / pw, 1e-10)) / pvar[..., 2],
            jnp.log(jnp.maximum(th / ph, 1e-10)) / pvar[..., 3]], axis=-1)
    else:
        t = target
        squeeze = t.ndim == 2
        if squeeze:
            t = t[None]
        cx = pvar[..., 0] * t[..., 0] * pw + pcx
        cy = pvar[..., 1] * t[..., 1] * ph + pcy
        w = jnp.exp(pvar[..., 2] * t[..., 2]) * pw
        h = jnp.exp(pvar[..., 3] * t[..., 3]) * ph
        out = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - one, cy + h / 2 - one], axis=-1)
        if squeeze:
            out = out[0]
    return {"OutputBox": [out]}


@register_op("box_clip")
def box_clip(ctx, ins, attrs):
    """box_clip_op.cc: clip boxes into [0, im-1] per image (ImInfo [N,3])."""
    boxes, im_info = ins["Input"][0], ins["ImInfo"][0]
    h = im_info[..., 0] / im_info[..., 2] - 1.0
    w = im_info[..., 1] / im_info[..., 2] - 1.0
    h = h.reshape(h.shape + (1,) * (boxes.ndim - h.ndim))
    w = w.reshape(w.shape + (1,) * (boxes.ndim - w.ndim))
    x0 = jnp.clip(boxes[..., 0::2], 0.0, w)
    y0 = jnp.clip(boxes[..., 1::2], 0.0, h)
    out = jnp.stack([x0[..., 0], y0[..., 0], x0[..., 1], y0[..., 1]],
                    axis=-1)
    return {"Output": [out]}


def _iou_matrix(a, b):
    """[N,4] x [M,4] -> [N,M] IoU (normalized corner boxes)."""
    ax0, ay0, ax1, ay1 = a[:, 0], a[:, 1], a[:, 2], a[:, 3]
    bx0, by0, bx1, by1 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    ix0 = jnp.maximum(ax0[:, None], bx0[None, :])
    iy0 = jnp.maximum(ay0[:, None], by0[None, :])
    ix1 = jnp.minimum(ax1[:, None], bx1[None, :])
    iy1 = jnp.minimum(ay1[:, None], by1[None, :])
    iw = jnp.maximum(ix1 - ix0, 0.0)
    ih = jnp.maximum(iy1 - iy0, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((ax1 - ax0) * (ay1 - ay0), 0.0)
    area_b = jnp.maximum((bx1 - bx0) * (by1 - by0), 0.0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


# ---------------------------------------------------------------------------
# Matching / assignment (SSD training pipeline)
# ---------------------------------------------------------------------------

def _greedy_match(s, match_type="bipartite", thresh=0.5):
    """Greedy bipartite matching of one [N, M] similarity matrix: repeat
    N times taking the global argmax and retiring its row+column (exactly
    bipartite_match_op.cc's loop as a lax.scan). Returns per column the
    matched row index (-1 unmatched) and similarity. 'per_prediction'
    additionally matches any free column whose best row similarity
    exceeds thresh (the SSD rule)."""
    N, M = s.shape

    def body(carry, _):
        s_cur, row_of_col, dist_of_col = carry
        flat = s_cur.reshape(-1)
        idx = jnp.argmax(flat)
        r, c = idx // M, idx % M
        v = flat[idx]
        take = v > 0.0
        row_of_col = jnp.where(take & (jnp.arange(M) == c), r, row_of_col)
        dist_of_col = jnp.where(take & (jnp.arange(M) == c), v, dist_of_col)
        s_cur = jnp.where(take & ((jnp.arange(N)[:, None] == r)
                                  | (jnp.arange(M)[None, :] == c)),
                          -1.0, s_cur)
        return (s_cur, row_of_col, dist_of_col), None

    init = (s, jnp.full((M,), -1, jnp.int32), jnp.zeros((M,), s.dtype))
    (_, row_of_col, dist_of_col), _ = jax.lax.scan(body, init, None,
                                                   length=N)
    if match_type == "per_prediction":
        best_row = jnp.argmax(s, axis=0).astype(jnp.int32)   # [M]
        best_val = jnp.max(s, axis=0)
        extra = (row_of_col < 0) & (best_val > thresh)
        row_of_col = jnp.where(extra, best_row, row_of_col)
        dist_of_col = jnp.where(extra, best_val, dist_of_col)
    return row_of_col, dist_of_col


@register_op("bipartite_match")
def bipartite_match(ctx, ins, attrs):
    """bipartite_match_op.cc on a [B, N, M] similarity matrix — see
    _greedy_match for the dense redesign."""
    sim = ins["DistMat"][0]
    if sim.ndim == 2:
        sim = sim[None]
    match_type = attrs.get("match_type", "bipartite")
    thresh = float(attrs.get("dist_threshold", 0.5))
    rows, dists = jax.vmap(
        lambda s: _greedy_match(s, match_type, thresh))(sim)
    return {"ColToRowMatchIndices": [rows], "ColToRowMatchDist": [dists]}


@register_op("target_assign")
def target_assign(ctx, ins, attrs):
    """target_assign_op.cc: gather per-prior targets by match indices.

    X [B, N, K] row features (gt boxes/labels), MatchIndices [B, M] row
    index per prior (-1 unmatched) -> Out [B, M, K], OutWeight [B, M, 1]
    (1 for matched, mismatch_value rows get weight 0 ... reference puts
    mismatch_value into Out and 0 weight).
    """
    x = ins["X"][0]
    match = ins["MatchIndices"][0]
    mismatch_value = attrs.get("mismatch_value", 0)
    if x.ndim == 2:
        x = x[None]
    B, N, K = x.shape
    safe = jnp.maximum(match, 0)
    out = jnp.take_along_axis(x, safe[:, :, None].astype(jnp.int32),
                              axis=1)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, out, jnp.asarray(mismatch_value, x.dtype))
    weight = matched.astype(jnp.float32)
    return {"Out": [out], "OutWeight": [weight]}


@register_op("mine_hard_examples")
def mine_hard_examples(ctx, ins, attrs):
    """mine_hard_examples_op.cc (max_negative mode): keep the hardest
    negatives at neg_pos_ratio per image.

    ClsLoss [B, M], MatchIndices [B, M] -> UpdatedMatchIndices where
    selected negatives STAY -1 and unselected negatives become -2 (our
    dense convention; reference emits a NegIndices LoD tensor instead),
    plus NegMask [B, M] float for loss masking.
    """
    cls_loss = ins["ClsLoss"][0]
    match = ins["MatchIndices"][0]
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    B, M = cls_loss.shape
    is_neg = match < 0
    n_pos = jnp.sum(~is_neg, axis=1)                     # [B]
    n_neg = jnp.minimum((n_pos * ratio).astype(jnp.int32),
                        jnp.sum(is_neg, axis=1))
    neg_loss = jnp.where(is_neg, cls_loss, -jnp.inf)     # [B, M]
    order = jnp.argsort(-neg_loss, axis=1)
    rank_of = jnp.argsort(order, axis=1)                 # rank per column
    selected = (rank_of < n_neg[:, None]) & is_neg
    return {"NegMask": [selected.astype(jnp.float32)],
            "UpdatedMatchIndices": [jnp.where(is_neg & ~selected,
                                              -2, match)]}


# ---------------------------------------------------------------------------
# NMS / output decoding
# ---------------------------------------------------------------------------

@register_op("multiclass_nms")
def multiclass_nms(ctx, ins, attrs):
    """multiclass_nms_op.cc, dense TPU redesign.

    BBoxes [B, M, 4], Scores [B, C, M] -> Out [B, keep_top_k, 6]
    rows = (label, score, xmin, ymin, xmax, ymax); invalid rows have
    label -1 (the reference emits variable-length LoD results instead).
    Per class: score threshold + top-k + O(k²) IoU suppression — the
    standard static-shape NMS (no data-dependent shapes anywhere).
    """
    bboxes, scores = ins["BBoxes"][0], ins["Scores"][0]
    score_thresh = float(attrs.get("score_threshold", 0.01))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 16))
    bg_label = int(attrs.get("background_label", 0))
    B, C, M = scores.shape
    k = min(nms_top_k, M)

    def nms_one_class(boxes, cls_scores):
        # [M,4], [M] -> (scores [k], boxes [k,4], valid [k])
        s = jnp.where(cls_scores > score_thresh, cls_scores, -jnp.inf)
        top_s, top_i = jax.lax.top_k(s, k)
        top_b = boxes[top_i]
        iou = _iou_matrix(top_b, top_b)
        valid0 = top_s > -jnp.inf

        def body(keep, i):
            # drop i if any higher-scored kept box overlaps > threshold
            over = (iou[i] > nms_thresh) & (jnp.arange(k) < i) & keep
            keep = keep.at[i].set(keep[i] & ~jnp.any(over))
            return keep, None

        keep, _ = jax.lax.scan(body, valid0, jnp.arange(k))
        return jnp.where(keep, top_s, -jnp.inf), top_b, keep

    def one_image(boxes, img_scores):
        # vmap classes; mask background by forcing its scores to -inf
        cls_ids = jnp.arange(C)
        cls_scores = jnp.where((cls_ids == bg_label)[:, None], -jnp.inf,
                               img_scores)
        s, b, kmask = jax.vmap(nms_one_class, in_axes=(None, 0))(
            boxes, cls_scores)                     # [C,k], [C,k,4], [C,k]
        flat_s = s.reshape(-1)
        flat_b = b.reshape(-1, 4)
        flat_l = jnp.broadcast_to(cls_ids[:, None], (C, k)).reshape(-1)
        kk = min(keep_top_k, flat_s.shape[0])
        top_s, top_i = jax.lax.top_k(flat_s, kk)
        rows = jnp.concatenate([
            jnp.where(top_s > -jnp.inf, flat_l[top_i], -1)[:, None]
               .astype(jnp.float32),
            jnp.where(top_s > -jnp.inf, top_s, 0.0)[:, None],
            flat_b[top_i]], axis=1)
        return rows

    out = jax.vmap(one_image)(bboxes, scores)
    return {"Out": [out]}


@register_op("ssd_loss")
def ssd_loss(ctx, ins, attrs):
    """The SSD multibox loss (≙ layers/detection.py ssd_loss, which
    composes iou_similarity → bipartite_match → target_assign →
    mine_hard_examples → conf/loc losses as ~10 ops; here the pipeline is
    one fused op — same math, one XLA computation).

    Location [B,M,4] (encoded offsets), Confidence [B,M,C], GtBox [B,G,4]
    (normalized corners; all-zero rows = padding), GtLabel [B,G,1] int,
    PriorBox [M,4], PriorBoxVar [M,4] → Loss [B,1].
    """
    loc = ins["Location"][0]
    conf = ins["Confidence"][0]
    gt_box = ins["GtBox"][0]
    gt_label = ins["GtLabel"][0]
    prior = ins["PriorBox"][0]
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else \
        jnp.asarray([0.1, 0.1, 0.2, 0.2], loc.dtype)
    thresh = float(attrs.get("overlap_threshold", 0.5))
    neg_ratio = float(attrs.get("neg_pos_ratio", 3.0))
    bg = int(attrs.get("background_label", 0))
    conf_w = float(attrs.get("conf_loss_weight", 1.0))
    loc_w = float(attrs.get("loc_loss_weight", 1.0))
    B, M, C = conf.shape
    G = gt_box.shape[1]
    if gt_label.ndim == 3:
        gt_label = gt_label[..., 0]
    gt_valid = jnp.any(jnp.abs(gt_box) > 0, axis=-1)          # [B,G]

    pcx, pcy, pw, ph = _center_form(prior)

    def one(loc_i, conf_i, gts, labels, valid):
        sim = _iou_matrix(gts, prior) * valid[:, None]         # [G,M]
        # SSD matching = greedy bipartite pass (every gt gets a prior,
        # collisions resolved like bipartite_match_op.cc) + threshold pass
        match, _ = _greedy_match(sim, "per_prediction", thresh)
        matched = match >= 0
        safe = jnp.maximum(match, 0)

        # conf loss: targets = matched gt label else background
        tgt = jnp.where(matched, labels[safe].astype(jnp.int32), bg)
        logp = jax.nn.log_softmax(conf_i.astype(jnp.float32), axis=-1)
        ce = -jnp.take_along_axis(logp, tgt[:, None], axis=-1)[:, 0]  # [M]
        # hard negative mining at neg_ratio
        n_pos = jnp.sum(matched)
        neg_loss = jnp.where(matched, -jnp.inf, ce)
        order = jnp.argsort(-neg_loss)
        rank = jnp.argsort(order)
        n_neg = jnp.minimum((n_pos * neg_ratio).astype(jnp.int32),
                            jnp.sum(~matched))
        neg_sel = (rank < n_neg) & ~matched
        conf_loss = jnp.sum(jnp.where(matched | neg_sel, ce, 0.0))

        # loc loss: smooth-l1 on encoded matched gt vs predicted offsets
        g = gts[safe]                                          # [M,4]
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-10)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-10)
        enc = jnp.stack([(gcx - pcx) / pw / pvar[..., 0],
                         (gcy - pcy) / ph / pvar[..., 1],
                         jnp.log(gw / pw) / pvar[..., 2],
                         jnp.log(gh / ph) / pvar[..., 3]], axis=-1)
        diff = jnp.abs(loc_i - enc)
        sl1 = jnp.sum(jnp.where(diff < 1.0, 0.5 * diff * diff, diff - 0.5),
                      axis=-1)
        loc_loss = jnp.sum(jnp.where(matched, sl1, 0.0))

        denom = jnp.maximum(n_pos.astype(jnp.float32), 1.0)
        return (conf_w * conf_loss + loc_w * loc_loss) / denom

    losses = jax.vmap(one)(loc, conf, gt_box, gt_label, gt_valid)
    return {"Loss": [losses[:, None]]}


# ---------------------------------------------------------------------------
# ROI pooling
# ---------------------------------------------------------------------------

@register_op("roi_pool")
def roi_pool(ctx, ins, attrs):
    """roi_pool_op.cc: max-pool each ROI into a fixed [Ph, Pw] grid.

    X [N, C, H, W]; ROIs [R, 5] = (batch_idx, x0, y0, x1, y1) in input
    coords (the dense stand-in for the reference's LoD roi batching).
    Masked-max formulation: every bin takes max over the cells whose
    center falls in the bin's integer span — vectorized, differentiable.
    """
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x0 = jnp.round(roi[1] * scale)
        y0 = jnp.round(roi[2] * scale)
        x1 = jnp.round(roi[3] * scale)
        y1 = jnp.round(roi[4] * scale)
        rw = jnp.maximum(x1 - x0 + 1.0, 1.0)
        rh = jnp.maximum(y1 - y0 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = x[b]                                  # [C, H, W]

        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        hstart = jnp.floor(iy * bin_h) + y0         # [ph]
        hend = jnp.ceil((iy + 1) * bin_h) + y0
        wstart = jnp.floor(ix * bin_w) + x0         # [pw]
        wend = jnp.ceil((ix + 1) * bin_w) + x0
        ymask = (ys[None, :] >= hstart[:, None]) & (ys[None, :] < hend[:, None])
        xmask = (xs[None, :] >= wstart[:, None]) & (xs[None, :] < wend[:, None])
        mask = ymask[:, None, :, None] & xmask[None, :, None, :]  # [ph,pw,H,W]
        vals = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        out = jnp.max(vals, axis=(-1, -2))          # [C, ph, pw]
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return {"Out": [jax.vmap(one_roi)(rois.astype(jnp.float32))]}


@register_op("roi_align")
def roi_align(ctx, ins, attrs):
    """roi_align_op.cc: average of bilinear samples per bin (sampling
    ratio fixed at 2x2, the common setting)."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    S = 2  # samples per bin axis

    def bilinear(img, y, yx):
        # clamp sample coords into the image so border ROIs interpolate
        # instead of extrapolating (roi_align_op.cc clamps the same way)
        y = jnp.clip(y, 0.0, H - 1.0)
        yx = jnp.clip(yx, 0.0, W - 1.0)
        y0 = jnp.clip(jnp.floor(y), 0, H - 1)
        x0 = jnp.clip(jnp.floor(yx), 0, W - 1)
        y1 = jnp.clip(y0 + 1, 0, H - 1)
        x1 = jnp.clip(x0 + 1, 0, W - 1)
        ly, lx = y - y0, yx - x0
        y0i, x0i, y1i, x1i = (y0.astype(jnp.int32), x0.astype(jnp.int32),
                              y1.astype(jnp.int32), x1.astype(jnp.int32))
        v = (img[:, y0i, x0i] * (1 - ly) * (1 - lx)
             + img[:, y0i, x1i] * (1 - ly) * lx
             + img[:, y1i, x0i] * ly * (1 - lx)
             + img[:, y1i, x1i] * ly * lx)
        return v

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x0, y0, x1, y1 = roi[1] * scale, roi[2] * scale, roi[3] * scale, \
            roi[4] * scale
        rw = jnp.maximum(x1 - x0, 1.0)
        rh = jnp.maximum(y1 - y0, 1.0)
        bin_h, bin_w = rh / ph, rw / pw
        img = x[b]
        iy = jnp.arange(ph, dtype=jnp.float32)[:, None, None, None]
        ix = jnp.arange(pw, dtype=jnp.float32)[None, :, None, None]
        sy = jnp.arange(S, dtype=jnp.float32)[None, None, :, None]
        sx = jnp.arange(S, dtype=jnp.float32)[None, None, None, :]
        yy = y0 + (iy + (sy + 0.5) / S) * bin_h    # [ph,1,S,1]
        xx = x0 + (ix + (sx + 0.5) / S) * bin_w    # [1,pw,1,S]
        yy = jnp.broadcast_to(yy, (ph, pw, S, S)).reshape(-1)
        xx = jnp.broadcast_to(xx, (ph, pw, S, S)).reshape(-1)
        v = bilinear(img, yy, xx)                  # [C, ph*pw*S*S]
        v = v.reshape(C, ph, pw, S * S).mean(-1)
        return v

    return {"Out": [jax.vmap(one_roi)(rois.astype(jnp.float32))]}


# ---------------------------------------------------------------------------
# EAST geometry-map decoding + detection mAP
# ---------------------------------------------------------------------------

@register_op("polygon_box_transform",
             infer_shape=same_shape("Input", "Output"))
def polygon_box_transform(ctx, ins, attrs):
    """detection/polygon_box_transform_op.cc: decode an EAST-style geometry
    map [N, geo_ch, H, W] of per-pixel offsets into absolute vertex
    coordinates: x-offset channels become col_idx - in, y-offset channels
    row_idx - in. The reference's parity test is on the FLATTENED
    batch*channel index ((n*G + g) % 2, polygon_box_transform_op.cc:43-46),
    so with an odd channel count the x/y role alternates per batch item —
    reproduced exactly."""
    x = ins["Input"][0]
    n, g, h, w = x.shape
    cols = jnp.broadcast_to(jnp.arange(w, dtype=x.dtype), (h, w))
    rows = jnp.broadcast_to(jnp.arange(h, dtype=x.dtype)[:, None], (h, w))
    flat_idx = (jnp.arange(n)[:, None] * g + jnp.arange(g)[None, :])
    is_x = (flat_idx % 2 == 0)[:, :, None, None]
    grid = jnp.where(is_x, cols[None, None], rows[None, None])
    return {"Output": [grid.astype(x.dtype) - x]}


def _detection_map_infer(op, block):
    out = block.var(op.output("MAP")[0])
    out.shape = (1,)
    out.dtype = "float32"


@register_op("detection_map", infer_shape=_detection_map_infer)
def detection_map(ctx, ins, attrs):
    """detection_map_op.h: mean average precision over a batch of
    detections. Dense redesign of the LoD kernel: DetectRes [B, D, 6] =
    (label, score, xmin, ymin, xmax, ymax) with label==-1 padding rows;
    Label (ground truth) [B, G, 6] = (label, is_difficult, xmin, ymin,
    xmax, ymax) (or [B, G, 5] without the difficult column), label==-1
    padding. Greedy score-ordered matching (visited-once per gt,
    CalcTrueAndFalsePositive), then per-class AP by 'integral' or
    '11point' (CalcMAP). The reference's streaming Accum* state is played
    by metrics.DetectionMAP host-side; this op scores one batch.

    mAP averages classes that have >=1 countable gt box AND >=1 scored
    detection — the reference's behavior (classes absent from its
    true_pos map are skipped, detection_map_op.h:422-424).

    DELIBERATE DIVERGENCE (recorded in docs/design_decisions.md): the
    background class is excluded from the mean by INDEX. The reference's
    background check compares a class's positive COUNT to the label id
    (`label_num_pos == background_label`, detection_map_op.h:421) — a
    comparison that can never fire for classes in its map — so it
    effectively never excludes background. Here background_label behaves
    as it does in the sibling ops (multiclass_nms, ssd_loss): class ==
    background_label never enters the mean; pass background_label=-1 for
    the reference's include-everything behavior."""
    det = ins["DetectRes"][0].astype(jnp.float32)     # [B, D, 6]
    gt = ins["Label"][0].astype(jnp.float32)          # [B, G, 5|6]
    thresh = float(attrs.get("overlap_threshold", 0.5))
    eval_difficult = bool(attrs.get("evaluate_difficult", True))
    ap_type = attrs.get("ap_type", "integral")
    class_num = int(attrs["class_num"])
    background = int(attrs.get("background_label", 0))
    B, D, _ = det.shape
    G = gt.shape[1]

    if gt.shape[2] == 6:
        g_label, g_diff, g_box = gt[..., 0], gt[..., 1], gt[..., 2:6]
    else:
        g_label, g_box = gt[..., 0], gt[..., 1:5]
        g_diff = jnp.zeros_like(g_label)
    g_valid = g_label >= 0
    # a gt box is countable toward npos unless (difficult and not evaluated)
    g_count = g_valid & (eval_difficult | (g_diff < 0.5))
    # npos[c]: countable gt boxes per class
    g_onehot = jax.nn.one_hot(g_label.astype(jnp.int32), class_num)  # [B,G,C]
    npos = jnp.einsum("bg,bgc->c", g_count.astype(jnp.float32), g_onehot)

    d_label, d_score, d_box = det[..., 0], det[..., 1], det[..., 2:6]
    d_valid = d_label >= 0
    d_box = jnp.clip(d_box, 0.0, 1.0)  # ClipBBox

    def one_image(dl, ds, db, dv, gl, gd, gb, gv):
        # process detections in descending-score order (greedy matching)
        order = jnp.argsort(jnp.where(dv, -ds, jnp.inf))
        dl, ds, db, dv = dl[order], ds[order], db[order], dv[order]
        ious = _iou_matrix(db, gb)                    # [D, G]

        def body(visited, i):
            same = (gl == dl[i]) & gv
            iou_i = jnp.where(same, ious[i], -1.0)
            j = jnp.argmax(iou_i)
            max_iou = iou_i[j]
            matched = max_iou > thresh
            if eval_difficult:          # static attr: difficult gt count too
                diff_skip = jnp.zeros((), bool)
            else:
                diff_skip = matched & (gd[j] >= 0.5)
            tp = matched & (~diff_skip) & (~visited[j])
            fp = (~matched) | (matched & (~diff_skip) & visited[j])
            counted = dv[i] & (~diff_skip)
            visited = visited.at[j].set(visited[j] | (tp & dv[i]))
            return visited, (tp & counted, fp & counted, counted)

        _, (tp, fp, counted) = jax.lax.scan(
            body, jnp.zeros((G,), bool), jnp.arange(D))
        return dl, ds, tp, fp, counted

    dl, ds, tp, fp, counted = jax.vmap(one_image)(
        d_label, d_score, d_box, d_valid, g_label, g_diff, g_box, g_valid)
    dl, ds = dl.reshape(-1), ds.reshape(-1)           # [B*D]
    tp = tp.reshape(-1).astype(jnp.float32)
    fp = fp.reshape(-1).astype(jnp.float32)
    counted = counted.reshape(-1)

    # global sort by score desc; per-class cumulative TP/FP along it
    order = jnp.argsort(jnp.where(counted, -ds, jnp.inf))
    dl, tp, fp, counted = dl[order], tp[order], fp[order], counted[order]
    cls_mask = jax.nn.one_hot(dl.astype(jnp.int32), class_num) \
        * counted[:, None].astype(jnp.float32)        # [N, C]
    tp_cum = jnp.cumsum(tp[:, None] * cls_mask, axis=0)
    fp_cum = jnp.cumsum(fp[:, None] * cls_mask, axis=0)
    npos_safe = jnp.maximum(npos, 1.0)
    prec = tp_cum / jnp.maximum(tp_cum + fp_cum, 1e-9)   # [N, C]
    rec = tp_cum / npos_safe[None, :]

    has_det = cls_mask.sum(0) > 0
    scored = (npos > 0) & has_det                        # classes in the mean
    if 0 <= background < class_num:
        # the background class never enters the mean (its rows still consume
        # gt matches exactly as in the sibling ops, multiclass_nms/ssd_loss)
        scored = scored & (jnp.arange(class_num) != background)
    if ap_type == "11point":
        pts = jnp.arange(11, dtype=jnp.float32) / 10.0   # [11]
        at_pt = rec[:, :, None] >= pts[None, None, :]    # [N, C, 11]
        max_prec = jnp.max(jnp.where(at_pt, prec[:, :, None], 0.0), axis=0)
        ap = max_prec.mean(-1)                           # [C]
    else:  # integral: each TP adds precision-at-it * (1/npos)
        ap = jnp.sum(prec * tp[:, None] * cls_mask, axis=0) / npos_safe
    mean_ap = jnp.sum(jnp.where(scored, ap, 0.0)) / jnp.maximum(
        scored.sum().astype(jnp.float32), 1.0)
    return {"MAP": [mean_ap.reshape(1)]}
