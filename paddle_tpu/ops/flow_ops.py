"""Block-structured control flow ops: while / conditional_block / switch /
batch-wise if-else.

≙ reference paddle/fluid/operators/{while_op.cc, conditional_block_op.cc}
and the Switch/IfElse layers (python/paddle/fluid/layers/control_flow.py:
608, 1070, 1211). The reference interprets sub-blocks with nested
executors + StepScopes; here every sub-block is TRACED into the XLA
program under lax.while_loop / lax.cond / select chains — static shapes,
no host round-trips, differentiable where the construct allows.

Shared convention: a sub-block op's "carry"/"written" vars are outer-block
names its ops rebind; the op's outputs rebind those names in the enclosing
environment (SSA by rebinding, matching the reference's in-place variable
mutation semantics).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _run_sub(ctx, sub, env):
    from ..core import lowering
    lowering.run_op_range(sub.ops, 0, len(sub.ops), env, ctx, sub)
    return env


def _scalar_bool(v):
    return jnp.reshape(v, ()).astype(bool)


@register_op("while")
def while_op(ctx, ins, attrs):
    """while_op.cc → lax.while_loop over the sub-block.

    attrs: sub_block, cond (var name), loop_vars (outer names the body
    rewrites, cond included), max_iters (optional): when set, lowers to a
    fixed-length masked lax.scan instead — bounded, and differentiable in
    reverse mode (lax.while_loop is not; ≙ while_grad_op needs the
    reference's StepScope stack, here scan's native VJP).
    """
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    cond_name = attrs["cond"]
    carry_names = list(attrs["loop_vars"])
    outer_env = dict(ctx.env)
    carry0 = tuple(outer_env[n] for n in carry_names)
    max_iters = attrs.get("max_iters")

    def body_env(carry):
        env = dict(outer_env)
        env.update(zip(carry_names, carry))
        return env

    if max_iters is not None:
        def body(carry, _):
            env = body_env(carry)
            pred = _scalar_bool(env[cond_name])
            env = _run_sub(ctx, sub, env)
            new = tuple(jnp.where(pred, env[n], old)
                        for n, old in zip(carry_names, carry))
            return new, None
        final, _ = jax.lax.scan(body, carry0, None, length=int(max_iters))
    else:
        def cond_fn(carry):
            return _scalar_bool(dict(zip(carry_names, carry))[cond_name])

        def body_fn(carry):
            env = _run_sub(ctx, sub, body_env(carry))
            return tuple(env[n] for n in carry_names)

        final = jax.lax.while_loop(cond_fn, body_fn, carry0)
    return {"Out": list(final)}


@register_op("conditional_block")
def conditional_block(ctx, ins, attrs):
    """conditional_block_op.cc → lax.cond: the sub-block runs (is traced)
    in the true branch; written outer vars keep their prior values in the
    false branch."""
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    written = list(attrs["written_vars"])
    outer_env = dict(ctx.env)
    cond = _scalar_bool(ins["Cond"][0])

    def true_fn(vals):
        env = dict(outer_env)
        env.update(zip(written, vals))
        env = _run_sub(ctx, sub, env)
        return tuple(env[n] for n in written)

    def false_fn(vals):
        return vals

    prior = tuple(outer_env[n] for n in written)
    out = jax.lax.cond(cond, true_fn, false_fn, prior)
    return {"Out": list(out)}


@register_op("switch")
def switch_op(ctx, ins, attrs):
    """Switch layer (control_flow.py:1211): first-true case wins.

    Every case block is traced; outputs are selected with a reversed
    where-chain (default first, then later cases overridden by earlier
    true conds) — branch-free and SPMD-friendly, semantically identical
    to the reference's sequential conditional_block chain.
    """
    program = ctx.program
    sub_blocks = list(attrs["sub_blocks"])    # cases in declaration order
    has_default = attrs.get("has_default", False)
    written = list(attrs["written_vars"])
    conds = list(ins.get("Conds", []))        # one per non-default case
    outer_env = dict(ctx.env)

    prior = [outer_env[n] for n in written]
    results = []                              # per-case written values
    for b_idx in sub_blocks:
        sub = program.block(b_idx)
        env = _run_sub(ctx, sub, dict(outer_env))
        results.append([env[n] for n in written])

    n_cases = len(sub_blocks) - (1 if has_default else 0)
    out = list(results[-1]) if has_default else list(prior)
    for i in range(n_cases - 1, -1, -1):
        pred = _scalar_bool(conds[i])
        out = [jnp.where(pred, res, cur)
               for res, cur in zip(results[i], out)]
    return {"Out": out}


@register_op("ifelse")
def ifelse_op(ctx, ins, attrs):
    """IfElse layer (control_flow.py:1070): BATCH-wise branch select.

    The reference splits rows by cond, runs each branch on its slice, and
    merges. The TPU reading computes both branches on the full batch and
    row-selects — no dynamic shapes, identical results, and XLA dead-code
    eliminates anything cheap enough to not matter.
    """
    program = ctx.program
    true_sub = program.block(attrs["true_block"])
    false_sub = program.block(attrs["false_block"])
    out_pairs = list(attrs["output_pairs"])   # [(true_name, false_name)]
    cond = ins["Cond"][0]
    outer_env = dict(ctx.env)

    env_t = _run_sub(ctx, true_sub, dict(outer_env))
    env_f = _run_sub(ctx, false_sub, dict(outer_env))

    outs = []
    for t_name, f_name in out_pairs:
        tv, fv = env_t[t_name], env_f[f_name]
        c = cond.reshape((cond.shape[0],) + (1,) * (tv.ndim - 1))
        outs.append(jnp.where(c, tv, fv))
    return {"Out": outs}


@register_op("array_write")
def array_write(ctx, ins, attrs):
    """Dense tensor-array write (≙ lod_tensor_array write_to_array op,
    redesigned for static shapes): array is a [max_len, ...] buffer;
    row i is replaced. Differentiable."""
    arr, x, i = ins["Array"][0], ins["X"][0], ins["I"][0]
    idx = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [jax.lax.dynamic_update_index_in_dim(
        arr, x.astype(arr.dtype), idx, 0)]}


@register_op("array_read")
def array_read(ctx, ins, attrs):
    """Dense tensor-array read (≙ read_from_array op)."""
    arr, i = ins["Array"][0], ins["I"][0]
    idx = jnp.reshape(i, ()).astype(jnp.int32)
    return {"Out": [jax.lax.dynamic_index_in_dim(arr, idx, 0,
                                                 keepdims=False)]}


@register_op("array_length")
def array_length(ctx, ins, attrs):
    """lod_array_length_op.cc. Dense tensor arrays are fixed-capacity
    [max_len, ...] buffers (see array_write), so the runtime length is the
    write cursor the loop carries — the buffer's own length is its static
    capacity, returned here. While-loops that need the dynamic cursor
    already carry it as a loop var (layers/control_flow.py While)."""
    return {"Out": [jnp.asarray(ins["X"][0].shape[0], jnp.int32)]}
