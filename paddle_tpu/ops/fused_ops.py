"""Fused-block ops: the tuned-kernel tier above the generic op library.

≙ the reference's cuDNN tier (conv_cudnn_op.cu.cc — algorithm selection and
workspace tuning sitting above the im2col/math path) and its fusion passes
(fuse_elewise_add_act etc.): on TPU the equivalent lever is cross-op fusion
that XLA cannot perform because convolutions are HLO materialization
boundaries. See kernels/fused_block.py for the kernel design.

The `fused_bottleneck` op is semantically a conv1x1+BN+relu, conv3x3+BN+relu,
conv1x1+BN, +residual, relu chain (a stride-1 ResNet "rest" bottleneck) with
all three BNs in training mode.  On a single TPU device it lowers to the
Pallas chain; anywhere else (CPU tests, sharded meshes where GSPMD must
partition the program) it lowers to the same composition the individual ops
would have produced, so semantics — including running-stat updates and the
memory-lean BN VJP — are identical everywhere.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from .nn_ops import _bn_train, _conv2d, _conv2d_infer


def _fused_block_enabled(ctx) -> bool:
    mode = os.environ.get("PT_FUSED_BLOCK", "auto")
    if mode in ("0", "never"):
        return False
    if ctx is not None and getattr(ctx, "mesh", None) is not None:
        # GSPMD cannot partition an opaque Pallas call; sharded programs
        # take the composition path (same math, partitionable HLO)
        return False
    if mode in ("1", "always"):
        try:
            return jax.default_backend() in ("tpu", "axon")
        except Exception:  # pragma: no cover - backend probing never fatal
            return False
    # auto currently lowers to the composition: the round-5 A/B measured
    # the Pallas chain at 60.8 ms/batch vs 50.9 for XLA's op-by-op on the
    # full ResNet-50 step (P1 at 2.3x its traffic floor, 9-roll tap cost
    # in K2/B2, lane padding on the 14²/28² stages). Flip to the kernel
    # path per-shape once it wins its A/B — PT_FUSED_BLOCK=always forces
    # it for measurement.
    return False


def _conv(h, w, pad):
    return jax.lax.conv_general_dilated(
        h, w, (1, 1), [(pad, pad), (pad, pad)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def _compose_block(x, w1, w2, w3, bn_params, eps, momentum):
    """The unfused reference composition (identical math to emitting the
    individual conv2d/batch_norm/elementwise_add ops, memory-lean BN VJP
    included) — the fallback and the semantic definition of the op."""
    conv = _conv
    (g1, b1, m1, v1), (g2, b2, m2, v2), (g3, b3, m3, v3) = bn_params
    a1 = conv(x, w1, 0)
    h1, nm1, nv1, sm1, sv1 = _bn_train(a1, g1, b1, m1, v1, eps, momentum,
                                       True)
    a2 = conv(h1, w2, 1)
    h2, nm2, nv2, sm2, sv2 = _bn_train(a2, g2, b2, m2, v2, eps, momentum,
                                       True)
    a3 = conv(h2, w3, 0)
    h3, nm3, nv3, sm3, sv3 = _bn_train(a3, g3, b3, m3, v3, eps, momentum,
                                       False)
    out = jnp.maximum(h3 + x, 0)
    return out, (nm1, nv1, sm1, sv1, nm2, nv2, sm2, sv2, nm3, nv3, sm3, sv3)


def _fused_conv2d_infer(op, block):
    _conv2d_infer(op, block)              # same Input/Filter/Output slots
    out = block.var(op.output("Output")[0])
    c = out.shape[1]
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        if op.output(slot):
            v = block.var(op.output(slot)[0])
            v.shape, v.dtype = (c,), "float32"


@register_op("fused_conv2d", infer_shape=_fused_conv2d_infer)
def fused_conv2d(ctx, ins, attrs):
    """conv2d + batch_norm (+ elementwise_add) (+ relu) as ONE op — what
    analysis/fuse.py rewrites eligible chains into.  The conv itself is
    the same lowering as the standalone conv2d op (ops/nn_ops._conv2d,
    gconv formulation/layout machinery included); the difference is the
    EPILOGUE:

    * inference (is_test / use_global_stats): the BN is folded into the
      conv weights and bias (w' = w·γ·rsqrt(v+eps) per output channel,
      b' = β − m·γ·rsqrt(v+eps)) — the add/activation ride the same
      expression, stats pass through untouched;
    * training: batch stats + normalize + scale/shift (+add) (+relu) as
      a conv epilogue — the memory-lean _bn_train custom VJP (identical
      math and residuals to the unfused batch_norm op) or, when the
      measured per-shape gate says so, the Pallas epilogue kernels in
      kernels/fused_conv.py (same quintuple contract, own custom VJP).

    Running-stat rebinding (MeanOut/VarianceOut keep the BN's var names)
    and saved-stat outputs are exactly the unfused batch_norm's, so the
    fusion pass never changes state threading."""
    x, w = ins["Input"][0], ins["Filter"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean_in, var_in = ins["Mean"][0], ins["Variance"][0]
    addend = ins["Addend"][0] if ins.get("Addend") else None
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    relu = attrs.get("act", "") == "relu"

    if attrs.get("is_test", False) or attrs.get("use_global_stats", False):
        inv = jax.lax.rsqrt(var_in + eps)
        s = (scale * inv).astype(jnp.float32)
        wf = (w.astype(jnp.float32) * s.reshape(-1, 1, 1, 1)).astype(w.dtype)
        bias_f = (bias - mean_in * scale * inv).reshape(1, -1, 1, 1) \
            .astype(x.dtype)
        y = _conv2d(x, wf, attrs) + bias_f
        if addend is not None:
            y = y + addend
        if relu:
            y = jnp.maximum(y, 0)
        return {"Output": [y], "MeanOut": [mean_in],
                "VarianceOut": [var_in], "SavedMean": [mean_in],
                "SavedVariance": [var_in]}

    a = _conv2d(x, w, attrs)
    from ..kernels import fused_conv as _fc
    n, c, hh, ww = a.shape
    if _fc.epilogue_enabled(ctx, int(n), int(c), int(hh), int(ww),
                            str(a.dtype), relu=relu,
                            with_add=addend is not None):
        y, nm, nv, sm, sv = _fc.fused_conv_epilogue(
            a, scale, bias, mean_in, var_in, addend, eps, momentum, relu)
    elif addend is None:
        y, nm, nv, sm, sv = _bn_train(a, scale, bias, mean_in, var_in,
                                      eps, momentum, relu)
    else:
        y, nm, nv, sm, sv = _bn_train(a, scale, bias, mean_in, var_in,
                                      eps, momentum, False)
        y = y + addend
        if relu:
            y = jnp.maximum(y, 0)
    return {"Output": [y], "MeanOut": [nm], "VarianceOut": [nv],
            "SavedMean": [sm], "SavedVariance": [sv]}


def _fused_bottleneck_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, x.dtype
    w2 = block.var(op.input("W2")[0])
    c = w2.shape[0]
    cin = x.shape[1]
    for slot in ("MeanOut1", "VarOut1", "SavedMean1", "SavedVar1",
                 "MeanOut2", "VarOut2", "SavedMean2", "SavedVar2"):
        v = block.var(op.output(slot)[0])
        v.shape, v.dtype = (c,), "float32"
    for slot in ("MeanOut3", "VarOut3", "SavedMean3", "SavedVar3"):
        v = block.var(op.output(slot)[0])
        v.shape, v.dtype = (cin,), "float32"


@register_op("fused_bottleneck", infer_shape=_fused_bottleneck_infer)
def fused_bottleneck(ctx, ins, attrs):
    x = ins["X"][0]
    w1, w2, w3 = ins["W1"][0], ins["W2"][0], ins["W3"][0]
    bn_params = []
    for k in ("1", "2", "3"):
        bn_params.append((ins["Scale" + k][0], ins["Bias" + k][0],
                          ins["Mean" + k][0], ins["Variance" + k][0]))
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    n, cin, hh, ww = x.shape
    c = w1.shape[0]
    from .math_ops import harmonize
    w1 = harmonize(x, w1)
    w2 = harmonize(x, w2)
    w3 = harmonize(x, w3)

    if attrs.get("is_test", False):
        # inference: running stats, no stat updates (≙ batch_norm is_test),
        # kept in the same op so train/infer graphs share parameter names —
        # and the BN is FOLDED INTO THE CONV WEIGHTS (w' = w·γ/σ per output
        # channel, + bias), i.e. the op internalizes InferenceTranspiler's
        # conv→BN fold for the blocks its pattern-matcher no longer sees
        def conv_bn_folded(h, w, pad, g, b, m, v, act):
            inv = jax.lax.rsqrt(v + eps)
            s = (g * inv).astype(jnp.float32)
            wf = (w.astype(jnp.float32) * s.reshape(-1, 1, 1, 1)
                  ).astype(w.dtype)
            bias = (b - m * g * inv).reshape(1, -1, 1, 1).astype(h.dtype)
            y = _conv(h, wf, pad) + bias
            return jnp.maximum(y, 0) if act else y

        (g1, b1, m1, v1), (g2, b2, m2, v2), (g3, b3, m3, v3) = bn_params
        h1 = conv_bn_folded(x, w1, 0, g1, b1, m1, v1, True)
        h2 = conv_bn_folded(h1, w2, 1, g2, b2, m2, v2, True)
        h3 = conv_bn_folded(h2, w3, 0, g3, b3, m3, v3, False)
        out = jnp.maximum(h3 + x, 0)
        return {"Out": [out],
                "MeanOut1": [m1], "VarOut1": [v1],
                "SavedMean1": [m1], "SavedVar1": [v1],
                "MeanOut2": [m2], "VarOut2": [v2],
                "SavedMean2": [m2], "SavedVar2": [v2],
                "MeanOut3": [m3], "VarOut3": [v3],
                "SavedMean3": [m3], "SavedVar3": [v3]}

    min_s = int(os.environ.get("PT_FUSED_BLOCK_MIN_S", 196))
    use_pallas = (_fused_block_enabled(ctx) and hh == ww and n >= 8
                  and hh * ww >= min_s and cin % 128 == 0 and c % 64 == 0)
    if not use_pallas:
        out, st = _compose_block(x, w1, w2, w3, bn_params, eps, momentum)
        (nm1, nv1, sm1, sv1, nm2, nv2, sm2, sv2, nm3, nv3, sm3,
         sv3) = st
    else:
        from ..kernels.fused_block import fused_bottleneck_rest
        xr = x.reshape(n, cin, hh * ww)
        taps = jnp.transpose(w2, (2, 3, 0, 1)).reshape(9, c, c)
        (g1, b1, m1i, v1i), (g2, b2, m2i, v2i), (g3, b3, m3i,
                                                 v3i) = bn_params
        outs = fused_bottleneck_rest(
            xr, w1.reshape(c, cin), taps, w3.reshape(cin, c),
            g1.astype(jnp.float32), b1.astype(jnp.float32),
            g2.astype(jnp.float32), b2.astype(jnp.float32),
            g3.astype(jnp.float32), b3.astype(jnp.float32), hh, eps)
        out = outs[0].reshape(n, cin, hh, ww)
        sm1, sv1, sm2, sv2, sm3, sv3 = outs[1:]
        nm1 = momentum * m1i + (1 - momentum) * sm1
        nv1 = momentum * v1i + (1 - momentum) * sv1
        nm2 = momentum * m2i + (1 - momentum) * sm2
        nv2 = momentum * v2i + (1 - momentum) * sv2
        nm3 = momentum * m3i + (1 - momentum) * sm3
        nv3 = momentum * v3i + (1 - momentum) * sv3
    return {"Out": [out],
            "MeanOut1": [nm1], "VarOut1": [nv1],
            "SavedMean1": [sm1], "SavedVar1": [sv1],
            "MeanOut2": [nm2], "VarOut2": [nv2],
            "SavedMean2": [sm2], "SavedVar2": [sv2],
            "MeanOut3": [nm3], "VarOut3": [nv3],
            "SavedMean3": [sm3], "SavedVar3": [sv3]}
