"""Dense math ops: elementwise w/ axis broadcast, matmul/mul, reductions.

≙ reference paddle/fluid/operators/elementwise_*_op.* (broadcast rules in
elementwise_op_function.h), matmul_op/mul_op (cuBLAS via operators/math/blas.h),
reduce_*_op, cumsum, arg_max/min, top_k_op.cu, sum_op, scale_op, clip ops.
Every CUDA kernel becomes a jax.numpy/lax expression lowered by XLA onto the
MXU/VPU; no per-dtype kernel registrations are needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op, same_shape


# ---------------------------------------------------------------------------
# Elementwise binary with reference broadcast semantics
# (elementwise_op_function.h: Y's shape must be a contiguous subsequence of
# X's shape beginning at `axis`; axis=-1 means trailing-aligned)
# ---------------------------------------------------------------------------

def harmonize(x, y):
    """Mixed-precision rule: the Y (weight/bias) side follows X's float dtype.

    This is the in-op reading of the reference's fp16 transpiler
    (paddle/contrib/float16/float16_transpiler.py): activations may run in
    bfloat16 while master params stay float32; casts are inserted where the
    dtypes meet, and autodiff casts gradients back to the param dtype.
    """
    xt, yt = jnp.result_type(x), jnp.result_type(y)
    if xt != yt and jnp.issubdtype(xt, jnp.floating) and jnp.issubdtype(yt, jnp.floating):
        y = y.astype(xt)
    return y


def broadcast_y_to_x(x, y, axis: int):
    y = harmonize(x, y)
    xnd, ynd = jnp.ndim(x), jnp.ndim(y)
    if ynd == 0 or xnd == ynd:
        return y
    if axis == -1:
        axis = xnd - ynd
    new_shape = list(jnp.shape(y)) + [1] * (xnd - axis - ynd)
    return jnp.reshape(y, [1] * axis + new_shape)


def _ew_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, x.dtype


def _register_elementwise(name, fn):
    def compute(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        yb = broadcast_y_to_x(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, yb)]}
    register_op(name, infer_shape=_ew_infer)(compute)


_register_elementwise("elementwise_add", jnp.add)
_register_elementwise("elementwise_sub", jnp.subtract)
_register_elementwise("elementwise_mul", jnp.multiply)
_register_elementwise("elementwise_div", jnp.divide)
_register_elementwise("elementwise_max", jnp.maximum)
_register_elementwise("elementwise_min", jnp.minimum)
_register_elementwise("elementwise_pow", jnp.power)
_register_elementwise("elementwise_mod", jnp.mod)
_register_elementwise("elementwise_floordiv", jnp.floor_divide)


@register_op("scale", infer_shape=same_shape())
def scale(ctx, ins, attrs):
    """scale_op.cc: Out = scale * (X + bias_after_scale ? 0 : bias) ..."""
    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        return {"Out": [x * s + b]}
    return {"Out": [(x + b) * s]}


def _sum_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, x.dtype


@register_op("sum", infer_shape=_sum_infer)
def sum_op(ctx, ins, attrs):
    """sum_op.cc: add N tensors (grad-accumulation workhorse)."""
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


@register_op("sign", infer_shape=same_shape())
def sign(ctx, ins, attrs):
    return {"Out": [jnp.sign(ins["X"][0])]}


@register_op("clip", infer_shape=same_shape())
def clip(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], attrs["min"], attrs["max"])]}


@register_op("clip_by_norm", infer_shape=same_shape())
def clip_by_norm(ctx, ins, attrs):
    """clip_by_norm_op.cc: Out = X * max_norm / max(norm(X), max_norm)."""
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return {"Out": [x * (max_norm / jnp.maximum(norm, max_norm))]}


# ---------------------------------------------------------------------------
# matmul / mul
# ---------------------------------------------------------------------------

def _matmul_infer(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.input("Y")[0])
    out = block.var(op.output("Out")[0])
    xs, ys = list(x.shape), list(y.shape)
    if op.attrs.get("transpose_X"):
        xs[-2:] = xs[:-3:-1] if len(xs) >= 2 else xs
    if op.attrs.get("transpose_Y") and len(ys) >= 2:
        ys[-2], ys[-1] = ys[-1], ys[-2]
    if len(xs) >= 2 and len(ys) >= 2:
        batch = xs[:-2] or ys[:-2]
        out.shape = tuple(batch) + (xs[-2], ys[-1])
    out.dtype = x.dtype


@register_op("matmul", infer_shape=_matmul_infer)
def matmul(ctx, ins, attrs):
    """matmul_op.cc with transpose_X/transpose_Y and batched broadcasting.

    The contraction maps straight onto the MXU; alpha folds into the result.
    """
    x, y = ins["X"][0], ins["Y"][0]
    y = harmonize(x, y)
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


def _mul_infer(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.input("Y")[0])
    out = block.var(op.output("Out")[0])
    xn = op.attrs.get("x_num_col_dims", 1)
    yn = op.attrs.get("y_num_col_dims", 1)
    out.shape = tuple(x.shape[:xn]) + tuple(y.shape[yn:])
    out.dtype = x.dtype


@register_op("mul", infer_shape=_mul_infer)
def mul(ctx, ins, attrs):
    """mul_op.cc: flatten X to 2-D at x_num_col_dims, Y at y_num_col_dims,
    GEMM, then restore leading dims. This is the core of layers.fc.

    When Y is consumed whole (yn == 1, the fc/matmul-weight case) the
    flatten-GEMM-restore collapses to one dot_general contracting X's
    trailing dims — bit-identical results, but WITHOUT the B*S reshape:
    a reshape that merges a (dp, sp)-sharded batch/seq pair forces GSPMD
    to all-gather the full sequence on every matmul (measured on the
    virtual mesh: one [B, S, D] gather per mul before this, none after —
    tests/test_collectives_emitted.py)."""
    x, y = ins["X"][0], ins["Y"][0]
    y = harmonize(x, y)
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xshape, yshape = x.shape, y.shape
    if yn == 1 and len(xshape) - xn == 1 and xshape[-1] == yshape[0]:
        out = jax.lax.dot_general(
            x, y, (((len(xshape) - 1,), (0,)), ((), ())))
        return {"Out": [out]}
    # explicit sizes, no -1: jax.export's shape checks reject inferred dims
    x2 = jnp.reshape(x, (int(np.prod(xshape[:xn]) or 1),
                         int(np.prod(xshape[xn:]) or 1)))
    y2 = jnp.reshape(y, (int(np.prod(yshape[:yn]) or 1),
                         int(np.prod(yshape[yn:]) or 1)))
    out = x2 @ y2
    return {"Out": [jnp.reshape(out, tuple(xshape[:xn]) + tuple(yshape[yn:]))]}


# ---------------------------------------------------------------------------
# Reductions
# ---------------------------------------------------------------------------

def _reduce_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    dims = op.attrs.get("dim", [0])
    keep = op.attrs.get("keep_dim", False)
    if op.attrs.get("reduce_all", False):
        out.shape = (1,) if keep else ()
    else:
        dims = [d % len(x.shape) for d in dims] if x.shape else []
        if keep:
            out.shape = tuple(1 if i in dims else s for i, s in enumerate(x.shape))
        else:
            out.shape = tuple(s for i, s in enumerate(x.shape) if i not in dims)
    out.dtype = x.dtype


def _register_reduce(name, fn):
    def compute(ctx, ins, attrs):
        x = ins["X"][0]
        if attrs.get("reduce_all", False):
            axis = None
        else:
            axis = tuple(d % x.ndim for d in attrs.get("dim", [0]))
        return {"Out": [fn(x, axis=axis, keepdims=attrs.get("keep_dim", False))]}
    register_op(name, infer_shape=_reduce_infer)(compute)


_register_reduce("reduce_sum", jnp.sum)
_register_reduce("reduce_mean", jnp.mean)
_register_reduce("reduce_max", jnp.max)
_register_reduce("reduce_min", jnp.min)
_register_reduce("reduce_prod", jnp.prod)


def _mean_infer(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = (1,)
    out.dtype = block.var(op.input("X")[0]).dtype


@register_op("mean", infer_shape=_mean_infer)
def mean(ctx, ins, attrs):
    """mean_op.cc: all-reduce mean to a [1] tensor (the canonical loss head)."""
    return {"Out": [jnp.mean(ins["X"][0]).reshape((1,))]}


@register_op("cumsum", infer_shape=same_shape())
def cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x, axis = x.ravel(), 0
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        out = out - x
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    return {"Out": [out]}


def _arg_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    axis = op.attrs.get("axis", -1) % max(len(x.shape), 1)
    out.shape = tuple(s for i, s in enumerate(x.shape) if i != axis)
    out.dtype = "int64"


@register_op("arg_max", infer_shape=_arg_infer)
def arg_max(ctx, ins, attrs):
    return {"Out": [jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1))]}


@register_op("arg_min", infer_shape=_arg_infer)
def arg_min(ctx, ins, attrs):
    return {"Out": [jnp.argmin(ins["X"][0], axis=attrs.get("axis", -1))]}


def _topk_infer(op, block):
    x = block.var(op.input("X")[0])
    k = op.attrs["k"]
    shape = tuple(x.shape[:-1]) + (k,)
    out = block.var(op.output("Out")[0])
    idx = block.var(op.output("Indices")[0])
    out.shape, out.dtype = shape, x.dtype
    idx.shape, idx.dtype = shape, "int64"


@register_op("top_k", infer_shape=_topk_infer)
def top_k(ctx, ins, attrs):
    """top_k_op.cu's heap kernel ≙ lax.top_k (XLA sort-based, MXU-free)."""
    vals, idx = jax.lax.top_k(ins["X"][0], attrs["k"])
    return {"Out": [vals], "Indices": [idx]}


@register_op("accuracy")
def accuracy(ctx, ins, attrs):
    """accuracy_op.cu: fraction of rows whose top-k indices contain the label."""
    idx = ins["Indices"][0]
    label = ins["Label"][0].reshape((-1, 1))
    correct = jnp.any(idx == label, axis=1)
    total = correct.shape[0]
    num_correct = jnp.sum(correct.astype(jnp.int32))
    acc = num_correct.astype(jnp.float32) / float(total)
    return {"Accuracy": [acc.reshape((1,))],
            "Correct": [num_correct.reshape((1,))],
            "Total": [jnp.full((1,), total, jnp.int32)]}


@register_op("iou_similarity")
def iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    area = lambda b: jnp.maximum(b[..., 2] - b[..., 0], 0) * jnp.maximum(b[..., 3] - b[..., 1], 0)
    xi = x[:, None, :]
    yi = y[None, :, :]
    lt = jnp.maximum(xi[..., :2], yi[..., :2])
    rb = jnp.minimum(xi[..., 2:], yi[..., 2:])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area(xi) + area(yi) - inter
    return {"Out": [inter / jnp.maximum(union, 1e-10)]}
