"""Remaining op-family stragglers.

≙ reference paddle/fluid/operators/{nce_op, precision_recall_op,
mean_iou_op, row_conv_op, spp_op, pool_with_index (max_pool2d_with_index),
sequence_scatter_op, sequence_expand_as_op, bpr_loss_op,
positive_negative_pair_op, fake_quantize_op, fake_dequantize_op}.
Dense static-shape redesigns where the reference used LoD.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op, same_shape


@register_op("nce")
def nce(ctx, ins, attrs):
    """nce_op.cc: noise-contrastive estimation. Input [B,D], Label [B,T],
    Weight [V,D], Bias [V]. Uniform negative sampler (the reference's
    default), num_neg_samples negatives per row drawn from the traced PRNG
    stream. Cost [B,1] = binary logistic loss over pos + sampled neg."""
    x = ins["Input"][0]
    label = ins["Label"][0].astype(jnp.int32)
    w = ins["Weight"][0]
    b = ins["Bias"][0] if ins.get("Bias") else None
    k = int(attrs.get("num_neg_samples", 10))
    vocab = int(attrs.get("num_total_classes", w.shape[0]))
    B = x.shape[0]
    if label.ndim == 1:
        label = label[:, None]
    T = label.shape[1]

    neg = jax.random.randint(ctx.next_rng_key(), (B, k), 0, vocab)
    samples = jnp.concatenate([label, neg], axis=1)          # [B, T+k]
    sw = w[samples]                                          # [B, T+k, D]
    logits = jnp.einsum("bd,bsd->bs", x, sw)
    if b is not None:
        logits = logits + b[samples]
    # uniform noise probability -> constant log-odds correction
    logits = logits - jnp.log(jnp.asarray(k / vocab, logits.dtype))
    labels01 = jnp.concatenate(
        [jnp.ones((B, T)), jnp.zeros((B, k))], axis=1).astype(logits.dtype)
    ce = jnp.maximum(logits, 0) - logits * labels01 + jnp.log1p(
        jnp.exp(-jnp.abs(logits)))
    return {"Cost": [jnp.sum(ce, axis=1, keepdims=True)],
            "SampleLogits": [logits], "SampleLabels": [samples]}


@register_op("precision_recall")
def precision_recall(ctx, ins, attrs):
    """precision_recall_op.cc: per-class TP/FP/FN/TN from (MaxProbs'
    argmax) Indices + Labels, macro/micro precision/recall/F1 for the
    batch and for the accumulated states (StatesInfo [C,4] carried by the
    caller)."""
    indices = ins["Indices"][0].reshape(-1).astype(jnp.int32)
    labels = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    C = int(attrs["class_number"])
    weights = (ins["Weights"][0].reshape(-1)
               if ins.get("Weights") else jnp.ones_like(indices,
                                                        jnp.float32))
    cls = jnp.arange(C)
    pred_c = (indices[None, :] == cls[:, None]).astype(jnp.float32)  # [C,N]
    true_c = (labels[None, :] == cls[:, None]).astype(jnp.float32)
    wrow = weights[None, :]
    tp = jnp.sum(pred_c * true_c * wrow, axis=1)
    fp = jnp.sum(pred_c * (1 - true_c) * wrow, axis=1)
    fn = jnp.sum((1 - pred_c) * true_c * wrow, axis=1)
    tn = jnp.sum((1 - pred_c) * (1 - true_c) * wrow, axis=1)
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)       # [C,4]

    def metrics(states):
        tp_, fp_, tn_, fn_ = (states[:, 0], states[:, 1], states[:, 2],
                              states[:, 3])
        prec = jnp.where(tp_ + fp_ > 0, tp_ / (tp_ + fp_ + 1e-12), 0.0)
        rec = jnp.where(tp_ + fn_ > 0, tp_ / (tp_ + fn_ + 1e-12), 0.0)
        f1 = jnp.where(prec + rec > 0, 2 * prec * rec / (prec + rec + 1e-12),
                       0.0)
        macro = jnp.stack([prec.mean(), rec.mean(), f1.mean()])
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        mp = jnp.where(stp + sfp > 0, stp / (stp + sfp + 1e-12), 0.0)
        mr = jnp.where(stp + sfn > 0, stp / (stp + sfn + 1e-12), 0.0)
        mf = jnp.where(mp + mr > 0, 2 * mp * mr / (mp + mr + 1e-12), 0.0)
        return jnp.concatenate([macro, jnp.stack([mp, mr, mf])])

    accum = batch_states
    if ins.get("StatesInfo"):
        accum = accum + ins["StatesInfo"][0]
    return {"BatchMetrics": [metrics(batch_states)],
            "AccumMetrics": [metrics(accum)],
            "AccumStatesInfo": [accum]}


@register_op("mean_iou")
def mean_iou(ctx, ins, attrs):
    """mean_iou_op.cc: mean IoU over classes for segmentation maps."""
    pred = ins["Predictions"][0].reshape(-1).astype(jnp.int32)
    label = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    C = int(attrs["num_classes"])
    cls = jnp.arange(C)
    p = pred[None, :] == cls[:, None]
    l = label[None, :] == cls[:, None]
    inter = jnp.sum(p & l, axis=1).astype(jnp.float32)
    union = jnp.sum(p | l, axis=1).astype(jnp.float32)
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    mean = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    # reference semantics: correct/(wrong+correct) == per-class IoU, so
    # wrong = union - intersection (both pred- and label-side mismatches)
    wrong = (union - inter).astype(jnp.int32)
    correct = inter.astype(jnp.int32)
    return {"OutMeanIou": [mean.reshape(())],
            "OutWrong": [wrong], "OutCorrect": [correct]}


@register_op("row_conv", infer_shape=same_shape())
def row_conv(ctx, ins, attrs):
    """row_conv_op.cc (lookahead conv, DeepSpeech2): out[t] =
    sum_{j<k} filter[j] * x[t+j]. X dense [B,T,D], Filter [k,D]."""
    x, f = ins["X"][0], ins["Filter"][0]
    k = f.shape[0]
    B, T, D = x.shape
    pad = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = sum(pad[:, j:j + T, :] * f[j] for j in range(k))
    return {"Out": [out]}


def _spp_infer(op, block):
    x = block.var(op.input("X")[0])
    h = op.attrs["pyramid_height"]
    c = x.shape[1]
    bins = sum(4 ** i for i in range(h))
    out = block.var(op.output("Out")[0])
    out.shape = (x.shape[0], c * bins)
    out.dtype = x.dtype


@register_op("spp", infer_shape=_spp_infer)
def spp(ctx, ins, attrs):
    """spp_op.cc: spatial pyramid pooling — concat max/avg pools at bin
    grids 1x1, 2x2, 4x4, ... (pyramid_height levels), flattened."""
    import math
    x = ins["X"][0]
    h_levels = int(attrs["pyramid_height"])
    ptype = attrs.get("pooling_type", "max")
    B, C, H, W = x.shape
    outs = []
    for lvl in range(h_levels):
        n = 2 ** lvl
        # bin boundaries are static Python ints: slice per bin at trace
        # time (n*n small slices) instead of materializing a
        # [B,C,n,n,H,W] masked broadcast
        bins = []
        for by in range(n):
            ys, ye = math.floor(by * H / n), math.ceil((by + 1) * H / n)
            for bx in range(n):
                xs, xe = math.floor(bx * W / n), math.ceil((bx + 1) * W / n)
                cell = x[:, :, ys:ye, xs:xe]
                bins.append(cell.max((-1, -2)) if ptype == "max"
                            else cell.mean((-1, -2)))
        # channel-major within a level: [B, C, n*n] -> [B, C*n*n]
        outs.append(jnp.stack(bins, axis=-1).reshape(B, -1))
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register_op("max_pool2d_with_index")
def max_pool2d_with_index(ctx, ins, attrs):
    """pool_with_index: max pool + flat argmax indices (for unpooling)."""
    x = ins["X"][0]
    k = attrs["ksize"]
    k = (k, k) if isinstance(k, int) else tuple(k)
    s = attrs.get("strides", k)
    s = (s, s) if isinstance(s, int) else tuple(s)
    p = attrs.get("paddings", 0)
    p = (p, p) if isinstance(p, int) else tuple(p)
    B, C, H, W = x.shape
    oh = (H + 2 * p[0] - k[0]) // s[0] + 1
    ow = (W + 2 * p[1] - k[1]) // s[1] + 1
    pad = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
                  constant_values=-jnp.inf)
    # window index grids
    iy = jnp.arange(oh)[:, None] * s[0] + jnp.arange(k[0])[None, :]  # [oh,kh]
    ix = jnp.arange(ow)[:, None] * s[1] + jnp.arange(k[1])[None, :]
    win = pad[:, :, iy[:, None, :, None], ix[None, :, None, :]]
    # win: [B,C,oh,ow,kh,kw]
    flat = win.reshape(B, C, oh, ow, -1)
    arg = jnp.argmax(flat, axis=-1)
    out = jnp.max(flat, axis=-1)
    # convert window-local argmax to UNPADDED input flat index (H*W)
    ky, kx = arg // k[1], arg % k[1]
    gy = jnp.arange(oh)[None, None, :, None] * s[0] + ky - p[0]
    gx = jnp.arange(ow)[None, None, None, :] * s[1] + kx - p[1]
    idx = gy * W + gx
    return {"Out": [out], "Mask": [idx.astype(jnp.int32)]}


@register_op("sequence_scatter")
def sequence_scatter(ctx, ins, attrs):
    """sequence_scatter_op.cc, dense: X [N,D], Ids [N,L] int (pad -1),
    Updates [N,L] -> Out[i, Ids[i,j]] += Updates[i,j] (pads dropped)."""
    x, ids, upd = ins["X"][0], ins["Ids"][0].astype(jnp.int32), \
        ins["Updates"][0]
    if ids.ndim == 3 and ids.shape[-1] == 1:
        ids = ids[..., 0]
    if upd.ndim == 3 and upd.shape[-1] == 1:
        upd = upd[..., 0]
    D = x.shape[1]
    safe = jnp.where(ids >= 0, ids, D)  # OOB sentinel -> dropped

    def one(row, i_row, u_row):
        return row.at[i_row].add(u_row.astype(row.dtype), mode="drop")

    return {"Out": [jax.vmap(one)(x, safe, upd)]}


@register_op("sequence_expand_as")
def sequence_expand_as(ctx, ins, attrs):
    """sequence_expand_as_op.cc, dense: tile X rows [B,D] along Y's time
    axis -> [B,T,D] (≙ expanding each row to its ref sequence length; the
    dense form broadcasts to the padded T with masking downstream)."""
    x, y = ins["X"][0], ins["Y"][0]
    T = y.shape[1]
    return {"Out": [jnp.broadcast_to(x[:, None], (x.shape[0], T)
                                     + tuple(x.shape[1:]))]}


@register_op("bpr_loss")
def bpr_loss(ctx, ins, attrs):
    """bpr_loss_op.cc (Bayesian Personalized Ranking): for each row,
    -mean_j log sigmoid(score[label] - score[j]) over j != label."""
    x = ins["X"][0]
    label = ins["Label"][0].astype(jnp.int32)
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[:, 0]
    B, C = x.shape
    pos = jnp.take_along_axis(x, label[:, None], axis=1)     # [B,1]
    diff = pos - x                                           # [B,C]
    logsig = -jnp.maximum(-diff, 0) - jnp.log1p(jnp.exp(-jnp.abs(diff)))
    mask = jnp.arange(C)[None, :] != label[:, None]
    loss = -jnp.sum(jnp.where(mask, logsig, 0.0), axis=1,
                    keepdims=True) / (C - 1)
    return {"Y": [loss]}


@register_op("positive_negative_pair")
def positive_negative_pair(ctx, ins, attrs):
    """positive_negative_pair_op.cc: within each query, count prediction
    pairs ordered correctly / incorrectly / tied w.r.t. label order."""
    score = ins["Score"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    qid = ins["QueryID"][0].reshape(-1)
    same_q = qid[:, None] == qid[None, :]
    lbl_gt = label[:, None] > label[None, :]
    sc_diff = score[:, None] - score[None, :]
    considered = same_q & lbl_gt
    pos = jnp.sum(considered & (sc_diff > 0)).astype(jnp.float32)
    neg = jnp.sum(considered & (sc_diff < 0)).astype(jnp.float32)
    neu = jnp.sum(considered & (sc_diff == 0)).astype(jnp.float32)
    acc = (ins["AccumulatePositivePair"][0].reshape(())
           if ins.get("AccumulatePositivePair") else 0.0)
    accn = (ins["AccumulateNegativePair"][0].reshape(())
            if ins.get("AccumulateNegativePair") else 0.0)
    accu = (ins["AccumulateNeutralPair"][0].reshape(())
            if ins.get("AccumulateNeutralPair") else 0.0)
    return {"PositivePair": [(pos + acc).reshape((1,))],
            "NegativePair": [(neg + accn).reshape((1,))],
            "NeutralPair": [(neu + accu).reshape((1,))]}


@register_op("fake_quantize_abs_max", infer_shape=same_shape())
def fake_quantize_abs_max(ctx, ins, attrs):
    """fake_quantize_op.cc: symmetric abs-max quantize-dequantize in the
    forward (quant-aware training); straight-through in the backward."""
    x = ins["X"][0]
    bits = int(attrs.get("bit_length", 8))
    rng = float(2 ** (bits - 1) - 1)
    scale = jnp.max(jnp.abs(x))
    inv = jnp.where(scale > 0, rng / scale, 0.0)
    y = x * inv
    # straight-through estimator: forward = round(y), backward d/dx = inv
    q = y + jax.lax.stop_gradient(jnp.round(y) - y)
    return {"Out": [q], "OutScale": [scale.reshape((1,))]}


@register_op("fake_dequantize_max_abs", infer_shape=same_shape())
def fake_dequantize_max_abs(ctx, ins, attrs):
    """fake_dequantize_op.cc: out = x * scale / max_range."""
    x, scale = ins["X"][0], ins["Scale"][0]
    max_range = float(attrs.get("max_range", 127.0))
    return {"Out": [x.astype(jnp.float32) * scale.reshape(()) / max_range]}


def _feed_dequant_infer(op, block):
    """The wire-codec boundary, statically checked: Out keeps X's shape;
    the dtype derives from the declared out_dtype ONLY when X actually
    arrives at the policy's wire dtype. A boundary violation (the feed
    var re-widened, a mismatched policy) derives X's dtype instead, so
    the verifier's dtype-prop pass flags the recorded/derived
    disagreement at the op — the dtype narrowing is understood, never
    waved through."""
    from ..core.types import normalize_dtype, wire_dtype_of
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    want = wire_dtype_of(str(op.attrs.get("policy", "none")))
    if want is None or str(x.dtype) == want:
        out.dtype = normalize_dtype(op.attrs.get("out_dtype", "float32"))
    else:
        out.dtype = x.dtype


@register_op("feed_dequant", infer_shape=_feed_dequant_infer)
def feed_dequant(ctx, ins, attrs):
    """data/codec.py wire codec, traced into the step: the feed crossed
    the host->device pipe at the wire dtype (int8 payload + f32
    per-channel scale, or truncated bf16) and is decoded here, on
    device, as the program's first op. Under AMP the decoded value lands
    directly at the compute dtype — mirroring the executor's entry cast,
    so no f32 copy of the batch ever materializes."""
    from ..data.codec import decode_array
    x = ins["X"][0]
    out_dtype = str(attrs.get("out_dtype", "float32"))
    adt = getattr(ctx, "amp_dtype", None)
    if adt is not None and out_dtype == "float32":
        out_dtype = str(adt)
    policy = str(attrs.get("policy", "none"))
    scale = ins["Scale"][0] if ins.get("Scale") else None
    return {"Out": [decode_array(x, scale, policy, out_dtype)]}
