"""Mixture-of-Experts FFN with expert parallelism.

ADDITIVE capability (SURVEY §2.4 last row: the reference has no expert
parallelism; designed TPU-first). The classic dense/static MoE
formulation (Mesh-TensorFlow / Switch Transformer): top-k gating, a
FIXED per-expert capacity C, and one-hot dispatch/combine einsums — no
dynamic shapes anywhere, so XLA compiles it like any other op. The
stacked expert weights [E, ...] are sharded over the 'ep' mesh axis
(annotated by the layer); GSPMD turns the dispatch einsum into the
all-to-all that routes tokens to their expert's devices.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _moe_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, x.dtype
    aux = block.var(op.output("AuxLoss")[0])
    aux.shape, aux.dtype = (), "float32"


@register_op("moe_ffn", infer_shape=_moe_infer)
def moe_ffn(ctx, ins, attrs):
    """X [..., D]; GateW [D, E]; W1 [E, D, H]; B1 [E, H]; W2 [E, H, D];
    B2 [E, D] -> Out [..., D], AuxLoss [] (load-balancing, Switch
    Transformer eq. 4: E * sum_e f_e * p_e).

    top_k=1 (switch) or 2; capacity_factor bounds per-expert tokens at
    C = ceil(top_k * N / E * capacity_factor); overflow tokens pass
    through unchanged for their dropped slot (residual-friendly).
    """
    x = ins["X"][0]
    gate_w = ins["GateW"][0]
    w1, b1 = ins["W1"][0], ins["B1"][0]
    w2, b2 = ins["W2"][0], ins["B2"][0]
    top_k = int(attrs.get("top_k", 1))
    cap_f = float(attrs.get("capacity_factor", 1.25))
    act = attrs.get("act", "relu")

    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)                                   # [N, D]
    n = xt.shape[0]
    e = gate_w.shape[-1]
    c = max(int(math.ceil(top_k * n / e * cap_f)), 1)

    logits = (xt @ gate_w.astype(xt.dtype)).astype(jnp.float32)   # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)

    combine = jnp.zeros((n, e, c), jnp.float32)
    # iterative top-k assignment (k is 1 or 2: unrolled python loop)
    masked = probs
    counts = jnp.zeros((e,), jnp.int32)
    for _ in range(top_k):
        choice = jnp.argmax(masked, axis=-1)                # [N]
        gate = jnp.take_along_axis(masked, choice[:, None], 1)[:, 0]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [N, E]
        # position of each token within its chosen expert (cumsum order)
        pos = (jnp.cumsum(onehot, axis=0) - 1) + counts[None, :]  # [N, E]
        pos_tok = jnp.sum(pos * onehot, axis=1)             # [N]
        keep = pos_tok < c
        slot = jax.nn.one_hot(pos_tok, c, dtype=jnp.float32)     # [N, C]
        contrib = (gate * keep)[:, None, None] \
            * onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        combine = combine + contrib
        counts = counts + jnp.sum(onehot, axis=0)
        masked = masked * (1.0 - onehot.astype(jnp.float32))

    if top_k > 1:
        # GShard-style: top-k gates renormalized over the kept set (their
        # RELATIVE weights stay differentiable w.r.t. the router)
        denom = jnp.maximum(jnp.sum(combine, axis=(1, 2), keepdims=True),
                            1e-9)
        combine = combine / denom
    # top_k == 1 keeps the RAW gate probability (Switch Transformer:
    # out = p_i * expert_i(x)) — normalizing would make the weight
    # identically 1 and cut the router off from the task gradient
    dispatch = (combine > 0).astype(x.dtype)                # [N, E, C]

    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xt)     # [E, C, D]
    h = jnp.einsum("ecd,edh->ech", expert_in,
                   w1.astype(x.dtype)) + b1[:, None, :].astype(x.dtype)
    h = jnp.maximum(h, 0) if act == "relu" else jax.nn.gelu(h)
    expert_out = jnp.einsum("ech,ehd->ecd", h,
                            w2.astype(x.dtype)) + b2[:, None, :].astype(x.dtype)
    out = jnp.einsum("nec,ecd->nd", combine.astype(x.dtype), expert_out)

    # dropped tokens (no kept slot) pass through unchanged
    routed = jnp.sum(combine, axis=(1, 2)) > 0              # [N]
    out = jnp.where(routed[:, None], out, xt)

    # load-balancing aux loss: E * sum_e (fraction routed_e * mean prob_e)
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32)
    f_e = jnp.mean(top1, axis=0)
    p_e = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f_e * p_e)

    return {"Out": [out.reshape(lead + (d,))], "AuxLoss": [aux]}
