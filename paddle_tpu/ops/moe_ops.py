"""Mixture-of-Experts FFN with expert parallelism.

ADDITIVE capability (SURVEY §2.4 last row: the reference has no expert
parallelism; designed TPU-first). The classic dense/static MoE
formulation (Mesh-TensorFlow / Switch Transformer): top-k gating, a
FIXED per-expert capacity C, and one-hot dispatch/combine einsums — no
dynamic shapes anywhere, so XLA compiles it like any other op. The
stacked expert weights [E, ...] are sharded over the 'ep' mesh axis
(annotated by the layer); GSPMD turns the dispatch einsum into the
all-to-all that routes tokens to their expert's devices.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec

from ..core.registry import register_op


def _moe_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, x.dtype
    aux = block.var(op.output("AuxLoss")[0])
    aux.shape, aux.dtype = (), "float32"


def _moe_tokens(xt, gate_w, top_k, cap_f, act, expert_fn, stat_mean):
    """Shared MoE math over a flat token block xt [n, D].

    `expert_fn(expert_in [E, C, D]) -> expert_out [E, C, D]` runs the
    expert FFNs — locally for the dense path, via all-to-all dispatch for
    the expert-parallel path. `stat_mean(sum_vec, n)` turns local sums
    into global means for the aux loss (psum over the token-sharding axes
    when inside shard_map)."""
    n, _ = xt.shape
    e = gate_w.shape[-1]
    c = max(int(math.ceil(top_k * n / e * cap_f)), 1)

    logits = (xt @ gate_w.astype(xt.dtype)).astype(jnp.float32)   # [n, E]
    probs = jax.nn.softmax(logits, axis=-1)

    combine = jnp.zeros((n, e, c), jnp.float32)
    # iterative top-k assignment (k is 1 or 2: unrolled python loop)
    masked = probs
    counts = jnp.zeros((e,), jnp.int32)
    for _ in range(top_k):
        choice = jnp.argmax(masked, axis=-1)                # [n]
        gate = jnp.take_along_axis(masked, choice[:, None], 1)[:, 0]
        onehot = jax.nn.one_hot(choice, e, dtype=jnp.int32)  # [n, E]
        # position of each token within its chosen expert (cumsum order)
        pos = (jnp.cumsum(onehot, axis=0) - 1) + counts[None, :]  # [n, E]
        pos_tok = jnp.sum(pos * onehot, axis=1)             # [n]
        keep = pos_tok < c
        slot = jax.nn.one_hot(pos_tok, c, dtype=jnp.float32)     # [n, C]
        contrib = (gate * keep)[:, None, None] \
            * onehot.astype(jnp.float32)[:, :, None] * slot[:, None, :]
        combine = combine + contrib
        counts = counts + jnp.sum(onehot, axis=0)
        masked = masked * (1.0 - onehot.astype(jnp.float32))

    if top_k > 1:
        # GShard-style: top-k gates renormalized over the kept set (their
        # RELATIVE weights stay differentiable w.r.t. the router)
        denom = jnp.maximum(jnp.sum(combine, axis=(1, 2), keepdims=True),
                            1e-9)
        combine = combine / denom
    # top_k == 1 keeps the RAW gate probability (Switch Transformer:
    # out = p_i * expert_i(x)) — normalizing would make the weight
    # identically 1 and cut the router off from the task gradient
    dispatch = (combine > 0).astype(xt.dtype)               # [n, E, C]

    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xt)     # [E, C, D]
    expert_out = expert_fn(expert_in)                       # [E, C, D]
    out = jnp.einsum("nec,ecd->nd", combine.astype(xt.dtype), expert_out)

    # dropped tokens (no kept slot) pass through unchanged
    routed = jnp.sum(combine, axis=(1, 2)) > 0              # [n]
    out = jnp.where(routed[:, None], out, xt)

    # load-balancing aux loss: E * sum_e (fraction routed_e * mean prob_e)
    top1 = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32)
    f_e = stat_mean(jnp.sum(top1, axis=0), n)
    p_e = stat_mean(jnp.sum(probs, axis=0), n)
    aux = e * jnp.sum(f_e * p_e)
    return out, aux


def _expert_ffn(expert_in, w1, b1, w2, b2, act):
    h = jnp.einsum("ecd,edh->ech", expert_in,
                   w1.astype(expert_in.dtype)) \
        + b1[:, None, :].astype(expert_in.dtype)
    h = jnp.maximum(h, 0) if act == "relu" else jax.nn.gelu(h)
    return jnp.einsum("ech,ehd->ecd", h, w2.astype(expert_in.dtype)) \
        + b2[:, None, :].astype(expert_in.dtype)


@register_op("moe_ffn", infer_shape=_moe_infer)
def moe_ffn(ctx, ins, attrs):
    """X [..., D]; GateW [D, E]; W1 [E, D, H]; B1 [E, H]; W2 [E, H, D];
    B2 [E, D] -> Out [..., D], AuxLoss [] (load-balancing, Switch
    Transformer eq. 4: E * sum_e f_e * p_e).

    top_k=1 (switch) or 2; capacity_factor bounds per-expert tokens at
    C = ceil(top_k * N / E * capacity_factor); overflow tokens pass
    through unchanged for their dropped slot (residual-friendly).

    On a mesh with an `ep` axis (experts divisible by it, tokens
    divisible by the token-sharding axes) the op enters shard_map:
    tokens shard over (dp, ep), expert weights over ep, and the
    dispatch/combine run as the canonical all-to-all PAIR over ICI —
    [E, C_loc, D] -> [E/ep, ep*C_loc, D] and back — rather than
    trusting GSPMD to reverse-engineer the routing from one-hot einsums
    (measured on the 8-device virtual mesh: the einsum formulation
    all-gathers; tests/test_collectives_emitted.py pins the a2a pair).
    Per-shard capacity (C computed from the LOCAL token count) is the
    GShard/Switch formulation; with ample capacity_factor it matches the
    dense path bit-for-bit (tested)."""
    x = ins["X"][0]
    gate_w = ins["GateW"][0]
    w1, b1 = ins["W1"][0], ins["B1"][0]
    w2, b2 = ins["W2"][0], ins["B2"][0]
    top_k = int(attrs.get("top_k", 1))
    cap_f = float(attrs.get("capacity_factor", 1.25))
    act = attrs.get("act", "relu")

    lead = x.shape[:-1]
    d = x.shape[-1]
    xt = x.reshape(-1, d)                                   # [N, D]
    n = xt.shape[0]
    e = gate_w.shape[-1]

    from ..parallel.mesh import DP, EP
    mesh = getattr(ctx, "mesh", None) if ctx is not None else None
    ep = mesh.shape.get(EP, 1) if mesh is not None else 1
    tok_axes = tuple(a for a in (DP, EP)
                     if mesh is not None and mesh.shape.get(a, 1) > 1)
    tok_shards = int(np.prod([mesh.shape[a] for a in tok_axes])) \
        if tok_axes else 1
    use_ep = (ep > 1 and e % ep == 0 and n % max(tok_shards, 1) == 0
              and n >= tok_shards)

    if not use_ep:
        out, aux = _moe_tokens(
            xt, gate_w, top_k, cap_f, act,
            expert_fn=lambda ein: _expert_ffn(ein, w1, b1, w2, b2, act),
            stat_mean=lambda s, cnt: s / cnt)
        return {"Out": [out.reshape(lead + (d,))], "AuxLoss": [aux]}

    def local(xt_l, gate_w_l, w1_l, b1_l, w2_l, b2_l):
        def expert_fn(expert_in):
            # dispatch: each source shard's per-expert slices route to the
            # expert's owner — the canonical a2a pair over the ep axis
            routed = jax.lax.all_to_all(expert_in, EP, split_axis=0,
                                        concat_axis=1, tiled=True)
            eout = _expert_ffn(routed, w1_l, b1_l, w2_l, b2_l, act)
            return jax.lax.all_to_all(eout, EP, split_axis=1,
                                      concat_axis=0, tiled=True)

        def stat_mean(s, cnt):
            return jax.lax.psum(s, tok_axes) / (cnt * tok_shards)

        return _moe_tokens(xt_l, gate_w_l, top_k, cap_f, act, expert_fn,
                           stat_mean)

    tok_spec = PartitionSpec(tok_axes if len(tok_axes) > 1
                             else tok_axes[0], None)
    from ..core.compat import shard_map
    fn = shard_map(
        local, mesh=mesh,
        in_specs=(tok_spec, PartitionSpec(),
                  PartitionSpec(EP, None, None), PartitionSpec(EP, None),
                  PartitionSpec(EP, None, None), PartitionSpec(EP, None)),
        out_specs=(tok_spec, PartitionSpec()), check_vma=False)
    out, aux = fn(xt, gate_w, w1, b1, w2, b2)
    return {"Out": [out.reshape(lead + (d,))], "AuxLoss": [aux]}
