"""NN ops: activations, softmax, conv/pool/norm, dropout, losses.

≙ reference paddle/fluid/operators/{activation_op.cc, softmax_op, conv_op.cc,
conv_cudnn_op.cu.cc, pool_op, batch_norm_op, layer_norm_op, dropout_op,
cross_entropy_op, softmax_with_cross_entropy_op.cu, ...}. The cuDNN-special
kernels (conv/pool/BN) map to XLA's native convolution/reduce-window HLOs,
which XLA tiles onto the MXU — no library dispatch attr (`use_cudnn`) is
needed; it is accepted and ignored for API parity.
"""

from __future__ import annotations

import functools
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op, same_shape

# ---------------------------------------------------------------------------
# Activations (activation_op.cc registers ~20 via functor templates; here a
# table of jnp lambdas serves the same role)
# ---------------------------------------------------------------------------

_ACTIVATIONS = {
    "relu": lambda x, a: jnp.maximum(x, 0),
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "exp": lambda x, a: jnp.exp(x),
    "log": lambda x, a: jnp.log(x),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "rsqrt": lambda x, a: jax.lax.rsqrt(x),
    "abs": lambda x, a: jnp.abs(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "floor": lambda x, a: jnp.floor(x),
    "round": lambda x, a: jnp.round(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "square": lambda x, a: jnp.square(x),
    "softplus": lambda x, a: jax.nn.softplus(x),
    "softsign": lambda x, a: x / (1 + jnp.abs(x)),
    "sin": lambda x, a: jnp.sin(x),
    "cos": lambda x, a: jnp.cos(x),
    "relu6": lambda x, a: jnp.clip(x, 0, a.get("threshold", 6.0)),
    "leaky_relu": lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 0.02) * x),
    "elu": lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(x) - 1)),
    "brelu": lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)),
    "soft_relu": lambda x, a: jnp.log1p(jnp.exp(jnp.clip(
        x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))),
    "hard_sigmoid": lambda x, a: jnp.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0, 1),
    "thresholded_relu": lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0),
    "hard_shrink": lambda x, a: jnp.where(jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
    "softshrink": lambda x, a: jnp.sign(x) * jnp.maximum(
        jnp.abs(x) - a.get("lambda", 0.5), 0.0),
    "swish": lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x),
    "gelu": lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", False)),
    "pow": lambda x, a: jnp.power(x, a.get("factor", 1.0)),
    "stanh": lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(a.get("scale_a", 0.67) * x),
}


def _make_activation(name, fn):
    def compute(ctx, ins, attrs):
        return {"Out": [fn(ins["X"][0], attrs)]}
    register_op(name, infer_shape=same_shape())(compute)


for _n, _f in _ACTIVATIONS.items():
    _make_activation(_n, _f)


@register_op("prelu", infer_shape=same_shape())
def prelu(ctx, ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        alpha = alpha.reshape((1,) + x.shape[1:])
    return {"Out": [jnp.where(x >= 0, x, alpha * x)]}


@register_op("softmax", infer_shape=same_shape())
def softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


@register_op("log_softmax", infer_shape=same_shape())
def log_softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


def _maxout_infer(op, block):
    x = block.var(op.input("X")[0])
    g = op.attrs["groups"]
    out = block.var(op.output("Out")[0])
    out.shape = (x.shape[0], x.shape[1] // g) + tuple(x.shape[2:])
    out.dtype = x.dtype


@register_op("maxout", infer_shape=_maxout_infer)
def maxout(ctx, ins, attrs):
    x = ins["X"][0]
    g = attrs["groups"]
    n, c = x.shape[0], x.shape[1]
    return {"Out": [jnp.max(x.reshape((n, c // g, g) + x.shape[2:]), axis=2)]}


@register_op("dropout", infer_shape=same_shape())
def dropout(ctx, ins, attrs):
    """dropout_op.cc (upscale-in-train OFF in this reference era: outputs are
    scaled by (1-p) at test time? No — reference uses 'downgrade_in_infer':
    train: mask only; infer: scale by (1-p))."""
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    if attrs.get("is_test", False):
        return {"Out": [x * (1.0 - p)], "Mask": [jnp.ones_like(x)]}
    key = ctx.next_rng_key()
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    return {"Out": [x * mask], "Mask": [mask]}


# ---------------------------------------------------------------------------
# Convolution / pooling  (NCHW layout, matching the reference's default)
# ---------------------------------------------------------------------------

def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


def _conv_out_dim(size, k, pad, stride, dil=1):
    return (size + 2 * pad - (dil * (k - 1) + 1)) // stride + 1


def _conv2d_infer(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    out = block.var(op.output("Output")[0])
    s, p, d = (_pair(op.attrs.get(k, v)) for k, v in
               (("strides", 1), ("paddings", 0), ("dilations", 1)))
    n, _, h, wd = x.shape
    oc, _, kh, kw = w.shape
    out.shape = (n, oc, _conv_out_dim(h, kh, p[0], s[0], d[0]),
                 _conv_out_dim(wd, kw, p[1], s[1], d[1]))
    out.dtype = x.dtype


def _harmonize_w(x, w):
    from .math_ops import harmonize
    return harmonize(x, w)


def _dense_expand_grouped(w, groups):
    """[C_out, Cg, kh, kw] grouped filter -> [C_out, C_in, kh, kw] dense
    with zeros off the block diagonal, via a constant one-hot placement
    einsum (AD routes dW straight back to the grouped filter and the
    zeros contribute nothing)."""
    c_out, cg = int(w.shape[0]), int(w.shape[1])
    c_in = cg * groups
    out_per_group = c_out // groups
    place = np.zeros((c_out, cg, c_in), np.float32)
    for o in range(c_out):
        base = (o // out_per_group) * cg
        place[o, np.arange(cg), base + np.arange(cg)] = 1
    return jnp.einsum("ocyx,oci->oiyx", w,
                      jnp.asarray(place, w.dtype))


def _gconv_prefers_dense(x, w, groups, stride=(1, 1), padding=None,
                         dilation=(1, 1)) -> bool:
    """Formulation choice for grouped convs: XLA's native grouped lowering
    vs a dense conv over block-diagonal-expanded weights (the dense detour
    pays Cg->C_in flops inflation but keeps the MXU's lanes full where
    tiny groups would idle them).

    Decided by MEASUREMENT, not a rule (VERDICT r4 next #4): the executor
    pre-tunes every grouped conv shape before first compile
    (utils/gconv_autotune.py — per-shape fwd+bwd shootout memoized on
    disk, keyed by device kind); here at trace time the cache can only be
    read. An untuned shape (CPU tests, PT_GCONV_TUNE=0) takes the native
    path. PT_GCONV_DENSE=always|never remains the override."""
    cg = int(w.shape[1])
    # malformed configs (c_out not divisible by groups, mismatched c_in)
    # must keep the native path so XLA raises its loud shape error
    # instead of a silently wrong block placement
    if int(w.shape[0]) % groups or int(x.shape[1]) != cg * groups:
        return False
    mode = os.environ.get("PT_GCONV_DENSE", "auto")
    if mode in ("0", "never"):
        return False
    if mode in ("1", "always"):
        return True
    from ..utils import gconv_autotune as _gt
    key = _gt.shape_key(int(x.shape[0]), int(x.shape[1]),
                        int(x.shape[2]), int(x.shape[3]),
                        int(w.shape[0]), int(groups),
                        (int(stride[0]), int(stride[1])),
                        str(x.dtype), int(w.shape[2]),
                        padding=padding, dilation=dilation)
    hit = _gt.lookup(key)
    return bool(hit) if hit is not None else False


def _gconv_dense_layout(x, w, groups, stride=(1, 1), padding=None,
                        dilation=(1, 1)) -> str:
    """Weight layout for the DENSE grouped-conv formulation: 'oihw'
    (operand as stored) or 'hwio' (pre-transposed before the conv — the
    layout hint changes which tiling XLA's layout assignment hands the
    MXU; measured as a second autotuned dimension of the same gconv
    shootout). PT_GCONV_LAYOUT=oihw|hwio pins it; untuned shapes keep
    the stored layout."""
    mode = os.environ.get("PT_GCONV_LAYOUT", "auto")
    if mode in ("oihw", "hwio"):
        return mode
    from ..utils import gconv_autotune as _gt
    key = _gt.shape_key(int(x.shape[0]), int(x.shape[1]),
                        int(x.shape[2]), int(x.shape[3]),
                        int(w.shape[0]), int(groups),
                        (int(stride[0]), int(stride[1])),
                        str(x.dtype), int(w.shape[2]),
                        padding=padding, dilation=dilation)
    return _gt.lookup_layout(key) or "oihw"


def _conv2d(x, w, attrs, feature_group_count=None):
    w = _harmonize_w(x, w)
    s = _pair(attrs.get("strides", 1))
    p = _pair(attrs.get("paddings", 0))
    d = _pair(attrs.get("dilations", 1))
    groups = feature_group_count or attrs.get("groups", 1) or 1
    dn = ("NCHW", "OIHW", "NCHW")
    if groups > 1 and groups < x.shape[1] \
            and _gconv_prefers_dense(x, w, groups, stride=s, padding=p,
                                     dilation=d):
        layout = _gconv_dense_layout(x, w, groups, stride=s, padding=p,
                                     dilation=d)
        w = _dense_expand_grouped(w, groups)
        if layout == "hwio":
            w = jnp.transpose(w, (2, 3, 1, 0))
            dn = ("NCHW", "HWIO", "NCHW")
        groups = 1
    # NOTE: no preferred_element_type upcast — the MXU accumulates bf16
    # operands in fp32 internally, and jax 0.9's conv transpose rule cannot
    # transpose a dtype-upcasting conv.
    return jax.lax.conv_general_dilated(
        x, w, window_strides=s, padding=[(p[0], p[0]), (p[1], p[1])],
        rhs_dilation=d, dimension_numbers=dn,
        feature_group_count=groups)


@register_op("conv2d", infer_shape=_conv2d_infer)
def conv2d(ctx, ins, attrs):
    """conv_op.cc / conv_cudnn_op.cu.cc → XLA conv_general_dilated (MXU)."""
    return {"Output": [_conv2d(ins["Input"][0], ins["Filter"][0], attrs)]}


@register_op("depthwise_conv2d", infer_shape=_conv2d_infer)
def depthwise_conv2d(ctx, ins, attrs):
    """operators/math/depthwise_conv.cu → grouped XLA conv."""
    x, w = ins["Input"][0], ins["Filter"][0]
    return {"Output": [_conv2d(x, w, attrs, feature_group_count=x.shape[1])]}


def _conv2d_transpose_infer(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    out = block.var(op.output("Output")[0])
    s, p, d = (_pair(op.attrs.get(k, v)) for k, v in
               (("strides", 1), ("paddings", 0), ("dilations", 1)))
    n, _, h, wd = x.shape
    _, oc, kh, kw = w.shape
    oh = (h - 1) * s[0] - 2 * p[0] + d[0] * (kh - 1) + 1
    ow = (wd - 1) * s[1] - 2 * p[1] + d[1] * (kw - 1) + 1
    out.shape = (n, oc * (op.attrs.get("groups", 1) or 1), oh, ow)
    out.dtype = x.dtype


@register_op("conv2d_transpose", infer_shape=_conv2d_transpose_infer)
def conv2d_transpose(ctx, ins, attrs):
    """conv_transpose_op.cc → gradient-style dilated conv (IOHW filter).
    Grouped transpose runs per-group channel blocks (the flipped-kernel
    trick cannot express groups via feature_group_count)."""
    x, w = ins["Input"][0], ins["Filter"][0]
    w = _harmonize_w(x, w)
    s = _pair(attrs.get("strides", 1))
    p = _pair(attrs.get("paddings", 0))
    d = _pair(attrs.get("dilations", 1))
    kh, kw = w.shape[2], w.shape[3]
    pad_h = d[0] * (kh - 1) - p[0]
    pad_w = d[1] * (kw - 1) - p[1]
    g = attrs.get("groups", 1) or 1

    def one(xg, wg):
        return jax.lax.conv_general_dilated(
            xg, jnp.flip(wg, (2, 3)), window_strides=(1, 1),
            padding=[(pad_h, pad_h), (pad_w, pad_w)], lhs_dilation=s,
            rhs_dilation=d, dimension_numbers=("NCHW", "IOHW", "NCHW"))

    if g == 1:
        return {"Output": [one(x, w)]}
    cin = x.shape[1] // g
    outs = [one(x[:, i * cin:(i + 1) * cin], w[i * cin:(i + 1) * cin])
            for i in range(g)]
    return {"Output": [jnp.concatenate(outs, axis=1)]}


def _pool2d_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    if op.attrs.get("global_pooling", False):
        out.shape = tuple(x.shape[:2]) + (1, 1)
    else:
        k = _pair(op.attrs["ksize"])
        s = _pair(op.attrs.get("strides", 1))
        p = _pair(op.attrs.get("paddings", 0))
        n, c, h, w = x.shape
        if op.attrs.get("ceil_mode", False):
            oh = -(-(h + 2 * p[0] - k[0]) // s[0]) + 1
            ow = -(-(w + 2 * p[1] - k[1]) // s[1]) + 1
        else:
            oh = (h + 2 * p[0] - k[0]) // s[0] + 1
            ow = (w + 2 * p[1] - k[1]) // s[1] + 1
        out.shape = (n, c, oh, ow)
    out.dtype = x.dtype


@register_op("pool2d", infer_shape=_pool2d_infer)
def pool2d(ctx, ins, attrs):
    """pool_op.cc → XLA reduce_window (max) / avg via sum+count."""
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        if ptype == "max":
            return {"Out": [jnp.max(x, axis=(2, 3), keepdims=True)]}
        return {"Out": [jnp.mean(x, axis=(2, 3), keepdims=True)]}
    k = _pair(attrs["ksize"])
    s = _pair(attrs.get("strides", 1))
    p = _pair(attrs.get("paddings", 0))
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1]))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides, pads)
    else:
        ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, pads)
        if attrs.get("exclusive", True):
            ones = jnp.ones(x.shape[2:], x.dtype)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, k, s,
                                        ((p[0], p[0]), (p[1], p[1])))
            out = ssum / cnt
        else:
            out = ssum / (k[0] * k[1])
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------

def _bn_infer(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.output("Y")[0])
    y.shape, y.dtype = x.shape, x.dtype
    c = x.shape[1] if len(x.shape) > 1 else x.shape[0]
    for slot in ("MeanOut", "VarianceOut", "SavedMean", "SavedVariance"):
        if op.output(slot):
            v = block.var(op.output(slot)[0])
            v.shape, v.dtype = (c,), "float32"


def _bn_apply(x, mean, inv, scale, bias):
    """The normalize-scale-shift pass, kept byte-identical between forward
    and the backward's recompute (the ReLU mask must see the same y)."""
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - mean.reshape(bshape).astype(x.dtype)) * \
        (inv * scale).reshape(bshape).astype(x.dtype) + \
        bias.reshape(bshape).astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _bn_train(x, scale, bias, mean_in, var_in, eps, momentum, relu):
    """Training-mode batch norm with a memory-lean hand-written VJP.

    JAX's default AD of the naive formulation keeps the FLOAT32 cast of
    the whole activation (and the normalized x-hat) alive from forward to
    backward — for ResNet-50 bs128 that is gigabytes of extra HBM traffic
    per step (the round-3 control measured 44 GB moved vs a ~15 GB
    analytic floor). This VJP saves only the bf16 conv output plus two
    per-channel vectors and recomputes x-hat (elementwise, fuses into the
    backward reduces). `relu` additionally folds the activation into the
    same op (≙ the reference batch_norm op's fuse_with_relu attr,
    batch_norm_op.cc); the mask is recomputed from the residuals, never
    stored."""
    out, _ = _bn_train_fwd(x, scale, bias, mean_in, var_in, eps, momentum,
                           relu)
    return out


def _bn_train_stats(x, eps):
    axes = tuple(i for i in range(x.ndim) if i != 1)
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    return mean, var, inv


def _bn_train_fwd(x, scale, bias, mean_in, var_in, eps, momentum, relu):
    mean, var, inv = _bn_train_stats(x, eps)
    new_mean = momentum * mean_in + (1 - momentum) * mean
    new_var = momentum * var_in + (1 - momentum) * var
    y = _bn_apply(x, mean, inv, scale, bias)
    if relu:
        y = jnp.maximum(y, 0)
    out = (y, new_mean, new_var, mean, var)
    return out, (x, scale, bias, mean, inv)


def _bn_train_bwd(eps, momentum, relu, res, cts):
    x, scale, bias, mean, inv = res
    gy, g_new_mean, g_new_var, g_saved_mean, g_saved_var = cts
    axes = tuple(i for i in range(x.ndim) if i != 1)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    m = 1
    for i in axes:
        m *= x.shape[i]
    if relu:
        y = _bn_apply(x, mean, inv, scale, bias)
        gy = jnp.where(y > 0, gy, jnp.zeros_like(gy))
    gyf = gy.astype(jnp.float32)
    xf = x.astype(jnp.float32)
    xhat = (xf - mean.reshape(bshape)) * inv.reshape(bshape)
    dbeta = jnp.sum(gyf, axis=axes)
    dgamma = jnp.sum(gyf * xhat, axis=axes)
    sf = scale.astype(jnp.float32)
    dx = (sf * inv).reshape(bshape) * (
        gyf - (dbeta / m).reshape(bshape) - xhat * (dgamma / m).reshape(bshape))
    # direct cotangents on the emitted batch statistics (zero in normal
    # training — MeanOut/SavedMean feed state, not the loss — but custom_vjp
    # must be exact for any caller): d mean/dx = 1/m, d var/dx = 2(x-mu)/m
    g_mean_tot = (1 - momentum) * g_new_mean + g_saved_mean
    g_var_tot = (1 - momentum) * g_new_var + g_saved_var
    dx = dx + (g_mean_tot / m).reshape(bshape) \
        + (xf - mean.reshape(bshape)) * (2.0 * g_var_tot / m).reshape(bshape)
    return (dx.astype(x.dtype), dgamma.astype(scale.dtype),
            dbeta.astype(bias.dtype), momentum * g_new_mean,
            momentum * g_new_var)


_bn_train.defvjp(_bn_train_fwd, _bn_train_bwd)


@register_op("batch_norm", infer_shape=_bn_infer)
def batch_norm(ctx, ins, attrs):
    """batch_norm_op.cc/.cu. NCHW; running stats are persistable state vars
    threaded functionally (MeanOut/VarianceOut rebind the same names, exactly
    like the reference's in-place variable reuse). Training mode routes
    through the memory-lean custom-VJP kernel (see _bn_train; disable with
    PT_BN_PLAIN_VJP=1 for A/B measurement); fuse_with_relu folds the
    activation in (≙ the reference attr of the same name)."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean_in, var_in = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False)
    relu = bool(attrs.get("fuse_with_relu", False))
    axes = tuple(i for i in range(x.ndim) if i != 1)
    bshape = (1, -1) + (1,) * (x.ndim - 2)

    if is_test or attrs.get("use_global_stats", False):
        inv = jax.lax.rsqrt(var_in + eps)
        y = _bn_apply(x, mean_in, inv, scale, bias)
        if relu:
            y = jnp.maximum(y, 0)
        return {"Y": [y], "MeanOut": [mean_in], "VarianceOut": [var_in],
                "SavedMean": [mean_in], "SavedVariance": [var_in]}
    if os.environ.get("PT_BN_PLAIN_VJP"):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
        new_mean = momentum * mean_in + (1 - momentum) * mean
        new_var = momentum * var_in + (1 - momentum) * var
        inv = jax.lax.rsqrt(var + eps)
        y = _bn_apply(x, mean, inv, scale, bias)
        if relu:
            y = jnp.maximum(y, 0)
        return {"Y": [y], "MeanOut": [new_mean], "VarianceOut": [new_var],
                "SavedMean": [mean], "SavedVariance": [var]}
    y, new_mean, new_var, mean, var = _bn_train(
        x, scale, bias, mean_in, var_in, eps, momentum, relu)
    return {"Y": [y], "MeanOut": [new_mean], "VarianceOut": [new_var],
            "SavedMean": [mean], "SavedVariance": [var]}


def _ln_infer(op, block):
    x = block.var(op.input("X")[0])
    y = block.var(op.output("Y")[0])
    y.shape, y.dtype = x.shape, x.dtype
    ba = op.attrs.get("begin_norm_axis", 1)
    rows = int(np.prod(x.shape[:ba])) if x.shape else 1
    for slot in ("Mean", "Variance"):
        if op.output(slot):
            v = block.var(op.output(slot)[0])
            v.shape, v.dtype = (rows,), "float32"


@register_op("layer_norm", infer_shape=_ln_infer)
def layer_norm(ctx, ins, attrs):
    """layer_norm_op.cc: normalize over dims >= begin_norm_axis."""
    x = ins["X"][0]
    ba = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(ba, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + eps)).astype(x.dtype)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape((1,) * ba + x.shape[ba:])
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape((1,) * ba + x.shape[ba:])
    return {"Y": [y], "Mean": [mean.reshape(-1)], "Variance": [var.reshape(-1)]}


@register_op("l2_normalize", infer_shape=same_shape())
def l2_normalize(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    out = x / jnp.maximum(norm, eps)
    return {"Out": [out], "Norm": [norm]}


@register_op("lrn", infer_shape=same_shape())
def lrn(ctx, ins, attrs):
    """lrn_op.cc: local response normalization across channels (AlexNet)."""
    x = ins["X"][0]
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    win = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * win
    return {"Out": [x / jnp.power(mid, beta)], "MidOut": [mid]}


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

def _xent_infer(op, block):
    in_slot = "X" if op.type == "cross_entropy" else "Logits"
    x = block.var(op.input(in_slot)[0])
    out = block.var(op.output("Y" if op.type == "cross_entropy" else "Loss")[0])
    out.shape = tuple(x.shape[:-1]) + (1,)
    out.dtype = x.dtype


@register_op("cross_entropy", infer_shape=_xent_infer)
def cross_entropy(ctx, ins, attrs):
    """cross_entropy_op.cc: takes probabilities (post-softmax). Hard labels
    (int index, soft_label=False) or soft distributions."""
    x, label = ins["X"][0], ins["Label"][0]
    eps = 1e-8
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1, keepdims=True)
    else:
        lbl = label.reshape(label.shape[:-1]) if label.shape[-1:] == (1,) else label
        p = jnp.take_along_axis(x, lbl[..., None].astype(jnp.int32), axis=-1)
        loss = -jnp.log(jnp.maximum(p, eps))
    return {"Y": [loss]}


@jax.custom_vjp
def _softmax_xent_hard(logits, lbl):
    """Numerically-stable hard-label softmax cross-entropy with a
    memory-lean hand-written VJP.

    Default AD of log_softmax keeps an f32 copy of the FULL logits (and
    builds dlogits through a scatter-add into another full f32 array) —
    at 32k tokens x 32k vocab that is 2 x 3.9 GB of HLO temps, the
    allocations that OOM'd the long_context_32k config on a 16 GB chip.
    This VJP saves only the bf16 logits (alive anyway as the projection
    output) + the [*, 1] logsumexp, and computes
    dlogits = (softmax - onehot) * g with the onehot expressed as an
    iota==label compare (fuses; no scatter, no f32 temp)."""
    loss, _ = _softmax_xent_hard_fwd(logits, lbl)
    return loss


def _softmax_xent_hard_fwd(logits, lbl):
    lf = logits.astype(jnp.float32)
    m = jnp.max(lf, axis=-1, keepdims=True)
    lse = m + jnp.log(jnp.sum(jnp.exp(lf - m), axis=-1, keepdims=True))
    picked = jnp.take_along_axis(lf, lbl[..., None].astype(jnp.int32),
                                 axis=-1)
    return lse - picked, (logits, lbl, lse)


def _softmax_xent_hard_bwd(res, g):
    logits, lbl, lse = res
    lf = logits.astype(jnp.float32)
    p = jnp.exp(lf - lse)
    onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                       logits.ndim - 1)
              == lbl[..., None].astype(jnp.int32))
    dl = (p - onehot.astype(jnp.float32)) * g
    return (dl.astype(logits.dtype),
            np.zeros(lbl.shape, jax.dtypes.float0))


_softmax_xent_hard.defvjp(_softmax_xent_hard_fwd, _softmax_xent_hard_bwd)


@register_op("softmax_with_cross_entropy", infer_shape=_xent_infer)
def softmax_with_cross_entropy(ctx, ins, attrs):
    """softmax_with_cross_entropy_op.cu: numerically-stable fused version.
    Hard labels route through the memory-lean custom VJP (see
    _softmax_xent_hard; PT_XENT_PLAIN=1 restores default AD for A/B)."""
    logits, label = ins["Logits"][0], ins["Label"][0]
    if attrs.get("soft_label", False):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
        return {"Loss": [loss], "Softmax": [jnp.exp(logp)]}
    lbl = label.reshape(label.shape[:-1]) if label.shape[-1:] == (1,) \
        else label
    if os.environ.get("PT_XENT_PLAIN"):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        loss = -jnp.take_along_axis(logp, lbl[..., None].astype(jnp.int32),
                                    axis=-1)
        return {"Loss": [loss], "Softmax": [jnp.exp(logp)]}
    loss = _softmax_xent_hard(logits, lbl)
    # the Softmax side-output is DCE'd when unused; stop_gradient keeps it
    # off the AD path so consuming it costs fwd memory only
    soft = jax.lax.stop_gradient(
        jax.nn.softmax(logits.astype(jnp.float32), axis=-1))
    return {"Loss": [loss], "Softmax": [soft]}


@register_op("sigmoid_cross_entropy_with_logits", infer_shape=same_shape())
def sigmoid_cross_entropy_with_logits(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [loss]}


@register_op("square_error_cost", infer_shape=same_shape())
def square_error_cost(ctx, ins, attrs):
    """squared_l2_distance flavor used by fit_a_line: (X - Y)^2."""
    return {"Out": [jnp.square(ins["X"][0] - ins["Y"][0])]}


@register_op("smooth_l1_loss")
def smooth_l1_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    sigma2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight"):
        diff = diff * ins["InsideWeight"][0]
    abs_diff = jnp.abs(diff)
    val = jnp.where(abs_diff < 1.0 / sigma2, 0.5 * sigma2 * jnp.square(diff),
                    abs_diff - 0.5 / sigma2)
    if ins.get("OutsideWeight"):
        val = val * ins["OutsideWeight"][0]
    out = jnp.sum(val.reshape(val.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [out], "Diff": [diff]}


@register_op("huber_loss")
def huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * jnp.square(r), delta * (ar - 0.5 * delta))
    return {"Out": [out], "Residual": [r]}


@register_op("hinge_loss", infer_shape=same_shape("Logits"))
def hinge_loss(ctx, ins, attrs):
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0)]}


@register_op("log_loss", infer_shape=same_shape("Predicted", "Loss"))
def log_loss(ctx, ins, attrs):
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": [loss]}


@register_op("rank_loss")
def rank_loss(ctx, ins, attrs):
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": [jnp.log1p(jnp.exp(d)) - label * d]}


@register_op("squared_l2_norm")
def squared_l2_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.square(ins["X"][0])).reshape((1,))]}


@register_op("squared_l2_distance")
def squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    out = 0.5 * jnp.sum(jnp.square(sub).reshape(sub.shape[0], -1), axis=1, keepdims=True)
    return {"Out": [out], "sub_result": [sub]}


@register_op("mse_loss", infer_shape=same_shape("X", "Out"))
def mse_loss(ctx, ins, attrs):
    return {"Out": [jnp.square(ins["X"][0] - ins["Label"][0])]}


@register_op("label_smooth", infer_shape=same_shape())
def label_smooth(ctx, ins, attrs):
    """label_smooth_op.cc: (1-eps)*label + eps/K."""
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.1)
    k = x.shape[-1]
    if ins.get("PriorDist"):
        return {"Out": [(1 - eps) * x + eps * ins["PriorDist"][0]]}
    return {"Out": [(1 - eps) * x + eps / k]}


@register_op("auc")
def auc(ctx, ins, attrs):
    """auc_op.cc: trapezoidal AUC over a uniform threshold grid (per batch)."""
    pred = ins["Predict"][0]
    label = ins["Label"][0].reshape(-1)
    n_th = attrs.get("num_thresholds", 200)
    pos_score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] >= 2 else pred.reshape(-1)
    th = jnp.linspace(0.0, 1.0, n_th)
    is_pos = (label > 0)[None, :]
    above = pos_score[None, :] >= th[:, None]
    tp = jnp.sum(above & is_pos, axis=1).astype(jnp.float32)
    fp = jnp.sum(above & ~is_pos, axis=1).astype(jnp.float32)
    P = jnp.maximum(jnp.sum(is_pos), 1).astype(jnp.float32)
    N = jnp.maximum(jnp.sum(~is_pos), 1).astype(jnp.float32)
    tpr = tp / P
    fpr = fp / N
    auc_val = -jnp.trapezoid(tpr, fpr)
    return {"AUC": [auc_val.reshape((1,))]}
