"""Optimizer update ops.

≙ reference paddle/fluid/operators/{sgd_op, momentum_op, adam_op, adagrad_op,
adamax_op, adadelta_op, rmsprop_op, ftrl_op, decayed_adagrad_op,
proximal_gd_op, proximal_adagrad_op}.h/.cc/.cu. Each op consumes Param +
Grad + LearningRate (+ accumulators) and emits the updated tensors; the
lowering rebinds the persistable names so the new values become next step's
state — the functional reading of the reference's in-place param update.

sgd/momentum/adam/adagrad additionally implement the SelectedRows sparse
path (≙ their .h kernels specialized on SelectedRows grads): a
RowSparseGrad (core/selected_rows.py) updates only the touched rows —
"lazy" semantics for stateful optimizers, exactly like the reference,
where untouched rows' moments do not decay. Other optimizers densify
sparse grads via the registry fallback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op
from ..core.selected_rows import RowSparseGrad


def _p(ins, slot):
    return ins[slot][0]


def _f32(x):
    """Accumulator math under PT_OPT_STATE_DTYPE (optimizer.py): moments
    may be STORED bf16 but must UPDATE in f32 — a bf16 `b1*m + (1-b1)*g`
    would quantize the running statistic itself, not just its storage.
    New moment values are cast back to the stored dtype by the caller so
    the carried state keeps one dtype across steps (a drifting state
    dtype re-keys the jit cache and breaks run_loop's scan-carry
    structure). For f32 moments every cast is an identity — the
    pre-policy path stays bit-exact."""
    return x.astype(jnp.float32) if x.dtype != jnp.float32 else x


@register_op("sgd", supports_sparse=True)
def sgd(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    if isinstance(g, RowSparseGrad):
        # scatter-add update; padding slots point at the OOB sentinel row
        # and are dropped (sgd_op.h SelectedRows branch)
        return {"ParamOut": [p.at[g.rows].add(
            (-lr * g.values).astype(p.dtype), mode="drop")]}
    return {"ParamOut": [p - lr * g]}


@register_op("momentum", supports_sparse=True)
def momentum(ctx, ins, attrs):
    p, g, v, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Velocity"), _p(ins, "LearningRate")
    mu = attrs["mu"]
    if isinstance(g, RowSparseGrad):
        rows, vals = g.rows, g.values.astype(p.dtype)
        v_rows = _f32(v.at[rows].get(mode="clip"))
        v_new = mu * v_rows + vals
        if attrs.get("use_nesterov", False):
            delta = (vals + mu * v_new) * lr
        else:
            delta = lr * v_new
        return {"ParamOut": [p.at[rows].add(-delta.astype(p.dtype),
                                            mode="drop")],
                "VelocityOut": [v.at[rows].set(v_new.astype(v.dtype),
                                               mode="drop")]}
    v_new = mu * _f32(v) + g
    if attrs.get("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new.astype(v.dtype)]}


@register_op("adam", supports_sparse=True)
def adam(ctx, ins, attrs):
    """adam_op.h: m/v moments + scalar beta-power accumulators. Sparse =
    the reference's lazy mode: only touched rows' moments update."""
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    m, v = _p(ins, "Moment1"), _p(ins, "Moment2")
    b1p, b2p = _p(ins, "Beta1Pow"), _p(ins, "Beta2Pow")
    b1, b2, eps = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999), attrs.get("epsilon", 1e-8)
    if isinstance(g, RowSparseGrad):
        rows, vals = g.rows, g.values.astype(p.dtype)
        m_rows = _f32(m.at[rows].get(mode="clip"))
        v_rows = _f32(v.at[rows].get(mode="clip"))
        m_new = b1 * m_rows + (1 - b1) * vals
        v_new = b2 * v_rows + (1 - b2) * jnp.square(vals)
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        delta = lr_t * m_new / (jnp.sqrt(v_new) + eps)
        return {"ParamOut": [p.at[rows].add(-delta.astype(p.dtype),
                                            mode="drop")],
                "Moment1Out": [m.at[rows].set(m_new.astype(m.dtype),
                                              mode="drop")],
                "Moment2Out": [v.at[rows].set(v_new.astype(v.dtype),
                                              mode="drop")],
                "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}
    m_new = b1 * _f32(m) + (1 - b1) * g
    v_new = b2 * _f32(v) + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return {"ParamOut": [p_new], "Moment1Out": [m_new.astype(m.dtype)],
            "Moment2Out": [v_new.astype(v.dtype)],
            "Beta1PowOut": [b1p * b1], "Beta2PowOut": [b2p * b2]}


@register_op("adagrad", supports_sparse=True)
def adagrad(ctx, ins, attrs):
    p, g, mom, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Moment"), _p(ins, "LearningRate")
    eps = attrs.get("epsilon", 1e-6)
    if isinstance(g, RowSparseGrad):
        rows, vals = g.rows, g.values.astype(p.dtype)
        mom_rows = mom.at[rows].get(mode="clip")
        mom_new = mom_rows + jnp.square(vals)
        delta = lr * vals / (jnp.sqrt(mom_new) + eps)
        return {"ParamOut": [p.at[rows].add(-delta, mode="drop")],
                "MomentOut": [mom.at[rows].set(mom_new, mode="drop")]}
    mom_new = mom + jnp.square(g)
    return {"ParamOut": [p - lr * g / (jnp.sqrt(mom_new) + eps)],
            "MomentOut": [mom_new]}


@register_op("adamax")
def adamax(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    m, inf = _p(ins, "Moment"), _p(ins, "InfNorm")
    b1p = _p(ins, "Beta1Pow")
    b1, b2, eps = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999), attrs.get("epsilon", 1e-8)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g))
    p_new = p - (lr / (1 - b1p)) * (m_new / (inf_new + eps))
    return {"ParamOut": [p_new], "MomentOut": [m_new], "InfNormOut": [inf_new]}


@register_op("adadelta")
def adadelta(ctx, ins, attrs):
    p, g = _p(ins, "Param"), _p(ins, "Grad")
    avg_sq_g, avg_sq_u = _p(ins, "AvgSquaredGrad"), _p(ins, "AvgSquaredUpdate")
    rho, eps = attrs.get("rho", 0.95), attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    upd = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(upd)
    return {"ParamOut": [p + upd], "AvgSquaredGradOut": [g2], "AvgSquaredUpdateOut": [u2]}


@register_op("rmsprop")
def rmsprop(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    ms, mom = _p(ins, "MeanSquare"), _p(ins, "Moment")
    rho, eps, mu = attrs.get("decay", 0.95), attrs.get("epsilon", 1e-6), attrs.get("momentum", 0.0)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    if attrs.get("centered", False):
        mg = _p(ins, "MeanGrad")
        mg_new = rho * mg + (1 - rho) * g
        mom_new = mu * mom + lr * g / jnp.sqrt(ms_new - jnp.square(mg_new) + eps)
        return {"ParamOut": [p - mom_new], "MeanSquareOut": [ms_new],
                "MomentOut": [mom_new], "MeanGradOut": [mg_new]}
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    return {"ParamOut": [p - mom_new], "MeanSquareOut": [ms_new], "MomentOut": [mom_new]}


@register_op("decayed_adagrad")
def decayed_adagrad(ctx, ins, attrs):
    p, g, mom, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Moment"), _p(ins, "LearningRate")
    decay, eps = attrs.get("decay", 0.95), attrs.get("epsilon", 1e-6)
    mom_new = decay * mom + (1 - decay) * jnp.square(g)
    return {"ParamOut": [p - lr * g / (jnp.sqrt(mom_new) + eps)], "MomentOut": [mom_new]}


@register_op("ftrl")
def ftrl(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    sq, lin = _p(ins, "SquaredAccumulator"), _p(ins, "LinearAccumulator")
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    new_sq = sq + jnp.square(g)
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    return {"ParamOut": [pre / denom], "SquaredAccumOut": [new_sq],
            "LinearAccumOut": [new_lin]}


@register_op("proximal_gd")
def proximal_gd(ctx, ins, attrs):
    p, g, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "LearningRate")
    l1, l2 = attrs.get("l1", 0.0), attrs.get("l2", 0.0)
    prox = p - lr * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0) / (1 + lr * l2)
    return {"ParamOut": [p_new]}


@register_op("proximal_adagrad")
def proximal_adagrad(ctx, ins, attrs):
    p, g, mom, lr = _p(ins, "Param"), _p(ins, "Grad"), _p(ins, "Moment"), _p(ins, "LearningRate")
    l1, l2, eps = attrs.get("l1", 0.0), attrs.get("l2", 0.0), 1e-10
    mom_new = mom + jnp.square(g)
    lr_t = lr / (jnp.sqrt(mom_new) + eps)
    prox = p - lr_t * g
    p_new = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0) / (1 + lr_t * l2)
    return {"ParamOut": [p_new], "MomentOut": [mom_new]}


@register_op("average_accumulates")
def average_accumulates(ctx, ins, attrs):
    """average_accumulates_op.cc — the state machine behind ModelAverage.

    Accumulates param sums in three windows; restore logic lives in
    optimizer.ModelAverage (python side), as in the reference.
    """
    p = _p(ins, "param")
    sum1, sum2, sum3 = _p(ins, "in_sum_1"), _p(ins, "in_sum_2"), _p(ins, "in_sum_3")
    num_acc, old_num, num_upd = (_p(ins, "in_num_accumulates"),
                                 _p(ins, "in_old_num_accumulates"),
                                 _p(ins, "in_num_updates"))
    avg_window = attrs.get("average_window", 0.0)
    max_avg = attrs.get("max_average_window", 10000)
    min_avg = attrs.get("min_average_window", 10000)
    num_upd_new = num_upd + 1
    num_acc_new = num_acc + 1
    sum1_new = sum1 + p
    window = jnp.maximum(jnp.minimum(avg_window * num_upd_new.astype(jnp.float32),
                                     float(max_avg)), float(min_avg))
    roll = num_acc_new.astype(jnp.float32) >= window
    sum2_new = jnp.where(roll, sum2 + sum1_new, sum2)
    sum1_new = jnp.where(roll, jnp.zeros_like(sum1), sum1_new)
    old_num_new = jnp.where(roll, num_acc_new, old_num)
    num_acc_new = jnp.where(roll, jnp.zeros_like(num_acc_new), num_acc_new)
    big = old_num_new.astype(jnp.float32) + num_acc_new.astype(jnp.float32) >= float(max_avg)
    sum3_new = jnp.where(big, sum1_new + sum2_new, sum3)
    sum1_cl = jnp.where(big, jnp.zeros_like(sum1_new), sum1_new)
    sum2_cl = jnp.where(big, jnp.zeros_like(sum2_new), sum2_new)
    return {"out_sum_1": [sum1_cl], "out_sum_2": [sum2_cl], "out_sum_3": [sum3_new],
            "out_num_accumulates": [num_acc_new],
            "out_old_num_accumulates": [old_num_new],
            "out_num_updates": [num_upd_new]}
