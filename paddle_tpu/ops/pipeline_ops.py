"""The `pipeline` op: sub-block GPipe lowering.

Mirrors the dynamic_rnn pattern (ops/rnn_ops.py): the sub-block defines
ONE stage's computation over inner placeholder vars (the per-stage
parameter slice + the stage input); the op traces it as the gpipe
stage_fn. With a 'pp' mesh axis the schedule runs shard_map+ppermute
(parallel/pipeline.py); without one it falls back to the numerically
identical sequential scan, so CPU tests and single-chip runs work
unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("pipeline")
def pipeline_op(ctx, ins, attrs):
    from ..core import lowering
    from ..parallel.pipeline import gpipe, sequential_stages

    program = ctx.program
    sub = program.block(attrs["sub_block"])
    x_inner = attrs["x_var"]
    param_inner = list(attrs["param_vars"])    # inner slice names
    out_inner = attrs["out_var"]
    m = int(attrs["n_microbatches"])

    stacked = list(ins["Params"])              # [S, ...] per param
    x = ins["X"][0]                            # [B, ...]
    if not stacked:
        raise ValueError(
            "pipeline: the stage declared no stage_param()s — per-stage "
            "parameters must come from pipe.stage_param (ordinary layers "
            "create unstacked globals the schedule cannot slice)")
    s = stacked[0].shape[0]
    want = int(attrs.get("num_stages", s))
    if s != want:
        raise ValueError(f"pipeline: stacked params have {s} stages, "
                         f"layer declared {want}")
    b = x.shape[0]
    if b % m:
        raise ValueError(f"pipeline: batch {b} not divisible by "
                         f"n_microbatches {m}")
    xs = x.reshape((m, b // m) + tuple(x.shape[1:]))
    outer_env = dict(ctx.env)

    def stage_fn(p_slices, xmb):
        env = dict(outer_env)
        env[x_inner] = xmb
        env.update(zip(param_inner, p_slices))
        lowering.run_op_range(sub.ops, 0, len(sub.ops), env, ctx, sub)
        return env[out_inner]

    mesh = ctx.mesh
    params = tuple(stacked)
    if mesh is not None and "pp" in mesh.axis_names \
            and int(mesh.shape["pp"]) > 1:
        pp = int(mesh.shape["pp"])
        if pp != s:
            raise ValueError(f"pipeline: {s} stages but pp axis size {pp}")
        out = gpipe(lambda p, xmb: stage_fn(tuple(p), xmb), params, xs,
                    mesh=mesh)
    else:
        out = sequential_stages(lambda p, xmb: stage_fn(tuple(p), xmb),
                                params, xs)
    return {"Out": [out.reshape((b,) + tuple(out.shape[2:]))]}
