"""The `pipeline` op: sub-block GPipe lowering.

Mirrors the dynamic_rnn pattern (ops/rnn_ops.py): the sub-block defines
ONE stage's computation over inner placeholder vars (the per-stage
parameter slice + the stage input); the op traces it as the gpipe
stage_fn. With a 'pp' mesh axis the schedule runs shard_map+ppermute
(parallel/pipeline.py); without one it falls back to the numerically
identical sequential scan, so CPU tests and single-chip runs work
unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("pipeline")
def pipeline_op(ctx, ins, attrs):
    from ..core import lowering
    from ..parallel.pipeline import gpipe, one_f1b, sequential_stages

    program = ctx.program
    sub = program.block(attrs["sub_block"])
    x_inner = attrs["x_var"]
    param_inner = list(attrs["param_vars"])    # inner slice names
    out_inner = attrs["out_var"]
    m = int(attrs["n_microbatches"])

    stacked = list(ins["Params"])              # [S*ls, ...] per param
    x = ins["X"][0]                            # [B, ...]
    if not stacked:
        raise ValueError(
            "pipeline: the stage declared no stage_param()s — per-stage "
            "parameters must come from pipe.stage_param (ordinary layers "
            "create unstacked globals the schedule cannot slice)")
    total = stacked[0].shape[0]
    ls = int(attrs.get("layers_per_stage", 1))  # >1: auto-pp packs layers
    want = int(attrs.get("num_stages", total // ls))
    if total != want * ls:
        raise ValueError(f"pipeline: stacked params have {total} layers, "
                         f"expected {want} stages x {ls} layers/stage")
    s = want
    # leaves become [S, ls, ...]: gpipe/sequential slice over stages, the
    # stage body scans its ls layer slices
    stacked = [a.reshape((s, ls) + tuple(a.shape[1:])) for a in stacked]
    b = x.shape[0]
    if b % m:
        raise ValueError(f"pipeline: batch {b} not divisible by "
                         f"n_microbatches {m}")
    outer_env = dict(ctx.env)

    def one_layer(xin, p_layer):
        env = dict(outer_env)
        env[x_inner] = xin
        env.update(zip(param_inner, p_layer))
        lowering.run_op_range(sub.ops, 0, len(sub.ops), env, ctx, sub)
        return env[out_inner]

    def stage_fn(p_slices, xmb):
        # p_slices: tuple of [ls, ...] leaves (this stage's layer params)
        if ls == 1:
            return one_layer(xmb, tuple(p[0] for p in p_slices))

        def body(carry, p_layer):
            return one_layer(carry, tuple(p_layer)), None

        # unroll: ls is small and static; a rolled layer scan costs ~11%
        # on the chip (measured, bench transpiler_sanity — XLA cannot
        # fuse across a scan boundary), unrolling folds the stacked-param
        # slices back to the inline-layer program
        out, _ = jax.lax.scan(body, xmb, tuple(p_slices), unroll=True)
        return out

    from ..parallel.mesh import PP
    mesh = ctx.mesh
    params = tuple(stacked)
    from ..analysis.schedule import SCHEDULES
    schedule = str(attrs.get("schedule", "gpipe"))
    if schedule not in SCHEDULES:
        raise ValueError(f"pipeline: unknown schedule {schedule!r} "
                         f"(know {' | '.join(SCHEDULES)})")
    if mesh is not None and PP in mesh.axis_names \
            and int(mesh.shape[PP]) > 1:
        pp = int(mesh.shape[PP])
        if pp != s:
            raise ValueError(f"pipeline: {s} stages but pp axis size {pp}")
        xs = x.reshape((m, b // m) + tuple(x.shape[1:]))
        run = one_f1b if schedule == "1f1b" else gpipe
        out = run(lambda p, xmb: stage_fn(tuple(p), xmb), params, xs,
                  mesh=mesh)
        out = out.reshape((b,) + tuple(out.shape[2:]))
    else:
        # no pp axis: run the stages sequentially on the FULL batch — the
        # microbatch split only exists to fill the pipeline, and keeping
        # the original rank keeps rank-sensitive stage ops (layer_norm
        # begin_norm_axis, reshapes) identical to the unpartitioned program
        out = sequential_stages(lambda p, xmb: stage_fn(tuple(p), xmb),
                                params, x)
    return {"Out": [out]}
