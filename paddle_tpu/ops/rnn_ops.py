"""Recurrent ops: fused LSTM/GRU cells and the dynamic_rnn sub-block scanner.

≙ reference recurrent machinery: fused kernels lstm_op/gru_op
(operators/math/{lstm,gru}_compute.cu, paddle/cuda/src/hl_cuda_lstm.cu) and
the sub-block interpreters recurrent_op.cc:222 / DynamicRNN
(layers/control_flow.py:1313). TPU-native: everything is lax.scan over
time-major arrays with length masking — XLA unrolls nothing, the scan body
is one fused step, gradients come from scan's native VJP (the reference
needed StepScopes + hand-written grad sub-blocks, recurrent_op.cc:53).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op
from ..core.types import device_dtype
from .sequence_ops import time_mask

_ACT = {
    "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh, "relu": lambda x: jnp.maximum(x, 0),
    "identity": lambda x: x, None: jnp.tanh,
}


def _pallas_lstm_ok(ctx, attrs, use_peep, w_proj, b, h, t):
    """Route to the whole-sequence Pallas kernel (kernels/fused_lstm.py, ≙
    the reference's hl_cuda_lstm.cu persistent-weight tier) when the
    configuration matches its contract and we are on one real TPU device.
    PT_FUSED_LSTM=never reverts to the lax.scan formulation."""
    import os
    if os.environ.get("PT_FUSED_LSTM", "auto") in ("0", "never"):
        return False
    if use_peep or w_proj is not None:
        return False
    if (attrs.get("gate_activation", "sigmoid") != "sigmoid"
            or attrs.get("cell_activation", "tanh") != "tanh"
            or attrs.get("candidate_activation", "tanh") != "tanh"):
        return False
    if ctx is None or getattr(ctx, "mesh", None) is not None:
        return False
    if h % 128 or b % 8 or t < 4:
        return False
    try:
        import jax
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


def _lstm_scan(ins, attrs, w_proj=None, pact=None, ctx=None):
    """Shared fused-LSTM scan (lstm_op.cc / lstmp_op.h): one lax.scan whose
    carry is (recurrent_state, cell). For plain LSTM the recurrent state is
    the hidden h [B,H]; for LSTMP it is the projection r = pact(h @ w_proj)
    [B,P] (Sak et al. 2014). Gate layout i,c,f,o per the reference kernel
    (operators/math/detail/lstm_kernel.h); rows past each sequence's length
    hold their last valid state (stacked outputs are zero-masked)."""
    x = ins["Input"][0]
    w = ins["Weight"][0].astype(x.dtype)   # [H,4H] | [P,4H]
    seq_len = ins["SeqLen"][0]
    B, T, H4 = x.shape
    H = H4 // 4
    R = H if w_proj is None else w_proj.shape[1]   # recurrent-state width
    use_peep = attrs.get("use_peepholes", False)
    bias = ins["Bias"][0].astype(x.dtype) if ins.get("Bias") else None
    if bias is not None:
        b_gate = bias.reshape(-1)[:4 * H]
        b_peep = bias.reshape(-1)[4 * H:] if use_peep else None
    else:
        b_gate, b_peep = None, None
    gact = _ACT[attrs.get("gate_activation", "sigmoid")]
    cact = _ACT[attrs.get("cell_activation", "tanh")]
    hact = _ACT[attrs.get("candidate_activation", "tanh")]
    reverse = attrs.get("is_reverse", False)

    xs = jnp.moveaxis(x, 1, 0)  # [T,B,4H]
    mask = jnp.moveaxis(time_mask(seq_len, T, x.dtype), 1, 0)  # [T,B]
    if reverse:
        xs = jnp.flip(xs, 0)
        mask = jnp.flip(mask, 0)

    if ins.get("H0"):
        r0 = ins["H0"][0].astype(x.dtype)          # [B,H] (ref convention)
        if w_proj is not None:
            # lstmp_op.h:174-183: project the initial hidden state
            r0 = pact(r0 @ w_proj)
    else:
        r0 = jnp.zeros((B, R), x.dtype)
    c0 = ins["C0"][0].astype(x.dtype) if ins.get("C0") else jnp.zeros((B, H), x.dtype)

    def step(carry, inp):
        r, c = carry
        xt, m = inp
        gates = xt + r @ w
        if b_gate is not None:
            gates = gates + b_gate
        gi, gc, gf, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            wic, wfc, woc = jnp.split(b_peep, 3)
            gi = gi + wic * c
            gf = gf + wfc * c
        i = gact(gi)
        f = gact(gf)
        cand = cact(gc)
        c_new = f * c + i * cand
        if use_peep:
            go = go + woc * c_new
        o = gact(go)
        r_new = o * hact(c_new)
        if w_proj is not None:
            r_new = pact(r_new @ w_proj)
        m1 = m[:, None]
        r_new = m1 * r_new + (1 - m1) * r
        c_new = m1 * c_new + (1 - m1) * c
        return (r_new, c_new), (r_new * m1, c_new * m1)

    if _pallas_lstm_ok(ctx, attrs, use_peep, w_proj, B, H, T):
        from ..kernels.fused_lstm import lstm_sequence
        bz = b_gate if b_gate is not None else jnp.zeros((4 * H,), x.dtype)
        rs_c, cs_c = lstm_sequence(xs, w, bz, mask, r0, c0)
        # the op's outputs are the MASKED values; carries come from the
        # kernel (its backward needs them), the mask ride is one fused
        # XLA elementwise
        m3 = mask[:, :, None]
        rs, cs = rs_c * m3.astype(rs_c.dtype), cs_c * m3.astype(cs_c.dtype)
    else:
        (_, _), (rs, cs) = jax.lax.scan(step, (r0, c0), (xs, mask))
    if reverse:
        rs, cs = jnp.flip(rs, 0), jnp.flip(cs, 0)
    return jnp.moveaxis(rs, 0, 1), jnp.moveaxis(cs, 0, 1)


@register_op("dynamic_lstm")
def dynamic_lstm(ctx, ins, attrs):
    """lstm_op.cc. Input [B,T,4H] (pre-projected x*W_x), Weight [H,4H]
    recurrent, Bias [1,4H] (+[1,3H] peephole tail when use_peepholes).
    Outputs Hidden/Cell [B,T,H]."""
    hs, cs = _lstm_scan(ins, attrs, ctx=ctx)
    return {"Hidden": [hs], "Cell": [cs]}


@register_op("lstmp")
def lstmp(ctx, ins, attrs):
    """lstmp_op.cc/.h: LSTM with a recurrent projection layer (LSTMP, Sak
    et al. 2014). Input [B,T,4H] pre-projected; recurrent Weight [P,4H]
    acts on the PROJECTED state r; ProjWeight [H,P] maps cell-output h to
    r = proj_act(h @ ProjWeight). H0 follows the reference convention of a
    HIDDEN state [B,H], projected before the first step (lstmp_op.h:174).
    Outputs Projection [B,T,P] and Cell [B,T,H]."""
    x = ins["Input"][0]
    w_proj = ins["ProjWeight"][0].astype(x.dtype)   # [H, P]
    pact = _ACT[attrs.get("proj_activation", "tanh")]
    rs, cs = _lstm_scan(ins, attrs, w_proj=w_proj, pact=pact)
    return {"Projection": [rs], "Cell": [cs]}


@register_op("dynamic_gru")
def dynamic_gru(ctx, ins, attrs):
    """gru_op.cc. Input [B,T,3H] pre-projected, Weight [H,3H]: layout
    [update u | reset r | candidate c] following gru_compute. Output [B,T,H]."""
    x = ins["Input"][0]
    w = ins["Weight"][0].astype(x.dtype)
    seq_len = ins["SeqLen"][0]
    B, T, H3 = x.shape
    H = H3 // 3
    bias = ins["Bias"][0].astype(x.dtype).reshape(-1) if ins.get("Bias") else None
    gact = _ACT[attrs.get("gate_activation", "sigmoid")]
    cact = _ACT[attrs.get("activation", "tanh")]
    reverse = attrs.get("is_reverse", False)
    w_ur = w[:, :2 * H]
    w_c = w[:, 2 * H:]

    xs = jnp.moveaxis(x, 1, 0)
    mask = jnp.moveaxis(time_mask(seq_len, T, x.dtype), 1, 0)
    if reverse:
        xs = jnp.flip(xs, 0)
        mask = jnp.flip(mask, 0)
    h0 = ins["H0"][0].astype(x.dtype) if ins.get("H0") else jnp.zeros((B, H), x.dtype)

    def step(h, inp):
        xt, m = inp
        xur = xt[:, :2 * H]
        xc = xt[:, 2 * H:]
        gur = xur + h @ w_ur
        if bias is not None:
            gur = gur + bias[:2 * H]
        u, r = jnp.split(gact(gur), 2, axis=-1)
        gc = xc + (r * h) @ w_c
        if bias is not None:
            gc = gc + bias[2 * H:]
        cand = cact(gc)
        # gru_kernel.h:62: out = prev - u*prev + u*cand = (1-u)*prev + u*cand
        h_new = u * cand + (1.0 - u) * h
        m1 = m[:, None]
        h_new = m1 * h_new + (1 - m1) * h
        return h_new, h_new * m1

    _, hs = jax.lax.scan(step, h0, (xs, mask))
    if reverse:
        hs = jnp.flip(hs, 0)
    return {"Hidden": [jnp.moveaxis(hs, 0, 1)]}


@register_op("dynamic_rnn")
def dynamic_rnn(ctx, ins, attrs):
    """The DynamicRNN/recurrent_op sub-block scanner (recurrent_op.cc:222).

    Runs the ops of `sub_block` once per timestep under lax.scan. Step
    inputs are time-sliced from padded [B,T,...] arrays; memories carry with
    length masking; outer vars (parameters) are captured read-only from the
    enclosing environment — the functional equivalent of StepScopes' parent
    lookup (recurrent_op.cc:53).
    """
    from ..core import lowering

    program = ctx.program
    sub = program.block(attrs["sub_block"])
    step_inner = list(attrs["step_input_vars"])     # inner per-step names
    mem_inner = list(attrs["memory_vars"])          # inner memory names
    mem_updates = dict(attrs["memory_updates"])     # inner -> updated name
    mem_init_values = list(attrs["memory_init_values"])
    mem_shapes = list(attrs["memory_shapes"])
    out_inner = list(attrs["output_vars"])

    xs_list = ins["X"]
    seq_len = ins["SeqLen"][0]
    init_mems_in = list(ins.get("InitMems", []))
    has_init = list(attrs.get("memory_has_init", [False] * len(mem_inner)))
    B, T = xs_list[0].shape[0], xs_list[0].shape[1]
    dtype = xs_list[0].dtype if jnp.issubdtype(xs_list[0].dtype, jnp.floating) \
        else jnp.float32
    mem_dtypes = list(attrs.get("memory_dtypes", []))

    init = []
    init_iter = iter(init_mems_in)
    for i, name in enumerate(mem_inner):
        if has_init[i]:
            init.append(next(init_iter))
        else:
            shape = (B,) + tuple(s for s in mem_shapes[i] if s != -1)
            mdt = mem_dtypes[i] if i < len(mem_dtypes) and mem_dtypes[i] else dtype
            # device dtypes are 32-bit (same canonicalization as the executor
            # feed path); jnp.full with "int64" would truncate with a warning
            mdt = device_dtype(str(mdt)) if isinstance(mdt, str) else mdt
            init.append(jnp.full(shape, mem_init_values[i], mdt))

    xs_tm = [jnp.moveaxis(x, 1, 0) for x in xs_list]
    mask_tm = jnp.moveaxis(time_mask(seq_len, T, jnp.float32), 1, 0)  # [T,B]
    outer_env = dict(ctx.env)

    def body(carry, scanned):
        mems = carry
        xts, m = scanned[:-1], scanned[-1]
        env = dict(outer_env)
        for name, xt in zip(step_inner, xts):
            env[name] = xt
        for name, mem in zip(mem_inner, mems):
            env[name] = mem
        lowering.run_op_range(sub.ops, 0, len(sub.ops), env, ctx, sub)
        new_mems = []
        for name, old in zip(mem_inner, mems):
            upd = env[mem_updates.get(name, name)]
            mb = m.reshape((B,) + (1,) * (upd.ndim - 1)) > 0
            new_mems.append(jnp.where(mb, upd, old))
        outs = []
        for name in out_inner:
            v = env[name]
            mb = m.reshape((B,) + (1,) * (v.ndim - 1)) > 0
            outs.append(jnp.where(mb, v, jnp.zeros((), v.dtype)))
        return tuple(new_mems), tuple(outs)

    final_mems, stacked = jax.lax.scan(body, tuple(init),
                                       tuple(xs_tm) + (mask_tm,))
    outs = [jnp.moveaxis(o, 0, 1) for o in stacked]
    return {"Out": outs, "FinalMems": list(final_mems)}
