"""Ragged-sequence ops over the padded+lengths representation.

≙ reference LoD sequence machinery (SURVEY.md §5 "long context"): LoDTensor
offsets (lod_tensor.h:58) + sequence_{pool,softmax,expand,conv,...} ops and
the sequence2batch scheduler (operators/math/sequence2batch.h). TPU-native
representation: a sequence batch is a dense padded array [B, T, ...] plus an
int32 lengths vector [B] (the `@SEQ_LEN` companion var) — static shapes for
XLA, masking instead of compaction. The "no padding waste" property of LoD
batching is recovered by length-bucketed feeding (data/feeder.py), which
bounds pad waste while keeping one compiled executable per bucket.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op, same_shape


def time_mask(seq_len, T, dtype=jnp.bool_):
    """[B] lengths -> [B, T] mask."""
    return (jnp.arange(T)[None, :] < seq_len[:, None]).astype(dtype)


def _bshape(mask, x):
    """[B,T] mask broadcast to x's rank [B,T,...]."""
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


@register_op("sequence_pool")
def sequence_pool(ctx, ins, attrs):
    """sequence_pool_op.cc: pooltype ∈ {sum, average, sqrt, max, last, first}.
    X: [B, T, ...], SeqLen: [B]; Out: [B, ...]."""
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0]
    ptype = attrs.get("pooltype", "average").lower()
    T = x.shape[1]
    mask = _bshape(time_mask(seq_len, T, x.dtype), x)
    if ptype == "sum":
        out = jnp.sum(x * mask, axis=1)
    elif ptype == "average":
        denom = jnp.maximum(seq_len, 1).astype(x.dtype)
        out = jnp.sum(x * mask, axis=1) / denom.reshape((-1,) + (1,) * (x.ndim - 2))
    elif ptype == "sqrt":
        denom = jnp.sqrt(jnp.maximum(seq_len, 1).astype(x.dtype))
        out = jnp.sum(x * mask, axis=1) / denom.reshape((-1,) + (1,) * (x.ndim - 2))
    elif ptype == "max":
        neg = jnp.asarray(-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                          else jnp.iinfo(x.dtype).min, x.dtype)
        out = jnp.max(jnp.where(mask.astype(bool), x, neg), axis=1)
    elif ptype == "last":
        idx = jnp.maximum(seq_len - 1, 0).astype(jnp.int32)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1).squeeze(1)
    elif ptype == "first":
        out = x[:, 0]
    else:
        raise ValueError(f"unknown pooltype {ptype}")
    return {"Out": [out]}


@register_op("sequence_softmax")
def sequence_softmax(ctx, ins, attrs):
    """sequence_softmax_op.cc: softmax within each sequence (over T)."""
    x = ins["X"][0]
    seq_len = ins["SeqLen"][0]
    squeeze = x.ndim >= 3 and x.shape[-1] == 1
    v = x.reshape(x.shape[:2]) if squeeze and x.ndim == 3 else x
    mask = time_mask(seq_len, v.shape[1])
    mask = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
    logits = jnp.where(mask, v.astype(jnp.float32), -1e30)
    out = jax.nn.softmax(logits, axis=1).astype(x.dtype)
    out = out * mask.astype(x.dtype)
    if squeeze and x.ndim == 3:
        out = out[..., None]
    return {"Out": [out]}


@register_op("sequence_expand")
def sequence_expand(ctx, ins, attrs):
    """sequence_expand_op.cc: broadcast per-sequence rows X [B, D] along Y's
    time axis -> [B, T, D] (the dense-padded reading; used to carry encoder
    state into each decoder step)."""
    x, y = ins["X"][0], ins["Y"][0]
    T = y.shape[1]
    return {"Out": [jnp.broadcast_to(x[:, None], (x.shape[0], T) + x.shape[1:])]}


@register_op("sequence_conv")
def sequence_conv(ctx, ins, attrs):
    """sequence_conv_op.cc: sliding-window projection over time.
    X: [B,T,D], Filter: [ctx_len*D, M] -> Out [B,T,M], masked."""
    x = ins["X"][0]
    w = ins["Filter"][0]
    seq_len = ins["SeqLen"][0]
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -((ctx_len - 1) // 2))
    B, T, D = x.shape
    mask = _bshape(time_mask(seq_len, T, x.dtype), x)
    xm = x * mask
    cols = []
    for i in range(ctx_len):
        off = ctx_start + i
        shifted = jnp.roll(xm, -off, axis=1)
        if off < 0:
            shifted = shifted.at[:, :(-off)].set(0.0) if hasattr(shifted, "at") else shifted
        elif off > 0:
            shifted = shifted.at[:, T - off:].set(0.0)
        cols.append(shifted)
    stacked = jnp.concatenate(cols, axis=-1)  # [B,T,ctx_len*D]
    out = jnp.einsum("btd,dm->btm", stacked, w.astype(stacked.dtype))
    return {"Out": [out * _bshape(time_mask(seq_len, T, out.dtype), out)]}


@register_op("sequence_reshape")
def sequence_reshape(ctx, ins, attrs):
    x = ins["X"][0]
    new_dim = attrs["new_dim"]
    B, T, D = x.shape
    return {"Out": [x.reshape(B, T * D // new_dim, new_dim)]}


@register_op("sequence_concat")
def sequence_concat(ctx, ins, attrs):
    """Concat along feature dim (axis=-1 flavor used in practice)."""
    return {"Out": [jnp.concatenate(ins["X"], axis=-1)]}


@register_op("sequence_slice")
def sequence_slice(ctx, ins, attrs):
    x = ins["X"][0]
    offset = ins["Offset"][0].reshape(-1)
    length = ins["Length"][0].reshape(-1)
    B, T = x.shape[0], x.shape[1]
    idx = offset[:, None] + jnp.arange(T)[None, :]
    idx = jnp.minimum(idx, T - 1)
    out = jnp.take_along_axis(x, idx.reshape((B, T) + (1,) * (x.ndim - 2)), axis=1)
    mask = (jnp.arange(T)[None, :] < length[:, None]).astype(x.dtype)
    return {"Out": [out * mask.reshape(mask.shape + (1,) * (x.ndim - 2))],
            "SeqLenOut": [length.astype(jnp.int32)]}


@register_op("sequence_enumerate")
def sequence_enumerate(ctx, ins, attrs):
    x = ins["X"][0]
    win = attrs["win_size"]
    pad = attrs.get("pad_value", 0)
    B, T = x.shape[0], x.shape[1]
    v = x.reshape(B, T)
    outs = []
    for i in range(win):
        shifted = jnp.concatenate(
            [v[:, i:], jnp.full((B, i), pad, v.dtype)], axis=1)
        outs.append(shifted)
    return {"Out": [jnp.stack(outs, axis=-1)]}


@register_op("sequence_erase")
def sequence_erase(ctx, ins, attrs):
    """Mask out tokens: padded representation keeps positions, zeroing erased
    tokens and adjusting lengths is done host-side; here tokens are replaced
    by 0 (cannot compact under static shapes)."""
    x = ins["X"][0]
    tokens = jnp.asarray(attrs.get("tokens", []), x.dtype)
    erase = jnp.isin(x, tokens)
    return {"Out": [jnp.where(erase, jnp.zeros_like(x), x)]}


@register_op("im2sequence")
def im2sequence(ctx, ins, attrs):
    """im2sequence_op.cc: image patches -> sequence [B, H'*W', C*kh*kw]."""
    x = ins["X"][0]
    kh, kw = attrs.get("kernels", [1, 1])
    sh, sw = attrs.get("strides", [1, 1])
    pads = attrs.get("paddings", [0, 0, 0, 0])
    x = jnp.pad(x, ((0, 0), (0, 0), (pads[0], pads[2]), (pads[1], pads[3])))
    B, C, H, W = x.shape
    oh = (H - kh) // sh + 1
    ow = (W - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [B, C*kh*kw, oh, ow]
    out = patches.reshape(B, C * kh * kw, oh * ow).transpose(0, 2, 1)
    return {"Out": [out]}


@register_op("sequence_pad")
def sequence_pad(ctx, ins, attrs):
    """Identity in the padded world (kept for API parity)."""
    return {"Out": [ins["X"][0]], "Length": [ins["SeqLen"][0]]}


@register_op("sequence_unpad")
def sequence_unpad(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register_op("edit_distance")
def edit_distance(ctx, ins, attrs):
    """edit_distance_op.cc: Levenshtein distance between hyp/ref id rows via
    a scan over the DP table (per batch row)."""
    hyp = ins["Hyps"][0].reshape(ins["Hyps"][0].shape[0], -1).astype(jnp.int32)
    ref = ins["Refs"][0].reshape(ins["Refs"][0].shape[0], -1).astype(jnp.int32)
    hyp_len = ins["HypsLen"][0].reshape(-1) if ins.get("HypsLen") else \
        jnp.full((hyp.shape[0],), hyp.shape[1], jnp.int32)
    ref_len = ins["RefsLen"][0].reshape(-1) if ins.get("RefsLen") else \
        jnp.full((ref.shape[0],), ref.shape[1], jnp.int32)
    B, M = hyp.shape
    N = ref.shape[1]

    def row_fn(carry, j):
        prev_row = carry  # [B, M+1]
        jm = j - 1
        ref_j = jnp.take_along_axis(ref, jm[None, None].repeat(B, 0), axis=1)[:, 0]

        def col_step(row_carry, i):
            row = row_carry
            im = i - 1
            hyp_i = hyp[:, im]
            sub_cost = (hyp_i != ref_j).astype(jnp.int32)
            val = jnp.minimum(
                jnp.minimum(row[:, im] + 1, prev_row[:, i] + 1),
                prev_row[:, im] + sub_cost)
            row = row.at[:, i].set(val)
            return row, None

        init_row = jnp.zeros((B, M + 1), jnp.int32).at[:, 0].set(j)
        row, _ = jax.lax.scan(col_step, init_row, jnp.arange(1, M + 1))
        return row, row

    row0 = jnp.tile(jnp.arange(M + 1, dtype=jnp.int32)[None, :], (B, 1))
    _, rows = jax.lax.scan(row_fn, row0, jnp.arange(1, N + 1))
    # rows: [N, B, M+1]; distance at [ref_len-1, b, hyp_len]
    full = jnp.concatenate([row0[None], rows], axis=0)  # [N+1, B, M+1]
    d = full[ref_len, jnp.arange(B), hyp_len].astype(jnp.float32)
    if attrs.get("normalized", True):
        d = d / jnp.maximum(ref_len.astype(jnp.float32), 1.0)
    return {"Out": [d.reshape(-1, 1)],
            "SequenceNum": [jnp.asarray([B], jnp.int32)]}
