"""Tensor creation/manipulation ops.

≙ reference paddle/fluid/operators/{reshape_op, transpose_op, concat_op,
split_op, slice_op, gather_op, scatter_op, pad_op, expand_op, one_hot_op,
cast_op, fill_constant_op, uniform_random_op, gaussian_random_op, assign_op,
lookup_table_op, shape_op, ...}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op, same_shape
from ..core.types import device_dtype, np_dtype


def _dev_dtype(dtype: str):
    return np_dtype(device_dtype(dtype))


# -- creation ---------------------------------------------------------------

def _fill_infer(op, block):
    out = block.var(op.output("Out")[0])
    out.shape = tuple(op.attrs["shape"])
    out.dtype = op.attrs.get("dtype", "float32")


@register_op("fill_constant", infer_shape=_fill_infer)
def fill_constant(ctx, ins, attrs):
    return {"Out": [jnp.full(tuple(attrs["shape"]), attrs.get("value", 0.0),
                             _dev_dtype(attrs.get("dtype", "float32")))]}


def _fill_bsl_infer(op, block):
    x = block.var(op.input("Input")[0])
    out = block.var(op.output("Out")[0])
    shape = list(op.attrs["shape"])
    in_idx = op.attrs.get("input_dim_idx", 0)
    out_idx = op.attrs.get("output_dim_idx", 0)
    if x.shape:
        shape[out_idx] = x.shape[in_idx]
    out.shape = tuple(shape)
    out.dtype = op.attrs.get("dtype", "float32")


@register_op("fill_constant_batch_size_like", infer_shape=_fill_bsl_infer)
def fill_constant_batch_size_like(ctx, ins, attrs):
    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    return {"Out": [jnp.full(tuple(shape), attrs.get("value", 0.0),
                             _dev_dtype(attrs.get("dtype", "float32")))]}


@register_op("uniform_random_batch_size_like", infer_shape=_fill_bsl_infer)
def uniform_random_batch_size_like(ctx, ins, attrs):
    """uniform_random_batch_size_like_op.cc: runtime batch dim from
    Input (build-time -1 resolves here, like fill_constant_batch_size_like)."""
    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed", 0)
           else ctx.next_rng_key())
    return {"Out": [jax.random.uniform(
        key, tuple(shape), _dev_dtype(attrs.get("dtype", "float32")),
        attrs.get("min", -1.0), attrs.get("max", 1.0))]}


@register_op("gaussian_random_batch_size_like", infer_shape=_fill_bsl_infer)
def gaussian_random_batch_size_like(ctx, ins, attrs):
    """gaussian_random_batch_size_like_op.cc."""
    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = x.shape[attrs.get("input_dim_idx", 0)]
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed", 0)
           else ctx.next_rng_key())
    dt = _dev_dtype(attrs.get("dtype", "float32"))
    out = jax.random.normal(key, tuple(shape), dt)
    return {"Out": [out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)]}


@register_op("fill_zeros_like", infer_shape=same_shape())
def fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register_op("uniform_random", infer_shape=_fill_infer)
def uniform_random(ctx, ins, attrs):
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed", 0)
           else ctx.next_rng_key())
    return {"Out": [jax.random.uniform(
        key, tuple(attrs["shape"]), _dev_dtype(attrs.get("dtype", "float32")),
        attrs.get("min", -1.0), attrs.get("max", 1.0))]}


@register_op("gaussian_random", infer_shape=_fill_infer)
def gaussian_random(ctx, ins, attrs):
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed", 0)
           else ctx.next_rng_key())
    dt = _dev_dtype(attrs.get("dtype", "float32"))
    out = jax.random.normal(key, tuple(attrs["shape"]), dt)
    return {"Out": [out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)]}


@register_op("truncated_gaussian_random", infer_shape=_fill_infer)
def truncated_gaussian_random(ctx, ins, attrs):
    key = (jax.random.PRNGKey(attrs["seed"]) if attrs.get("seed", 0)
           else ctx.next_rng_key())
    dt = _dev_dtype(attrs.get("dtype", "float32"))
    out = jax.random.truncated_normal(key, -2.0, 2.0, tuple(attrs["shape"]), dt)
    return {"Out": [out * attrs.get("std", 1.0) + attrs.get("mean", 0.0)]}


@register_op("assign", infer_shape=same_shape())
def assign(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register_op("assign_value", infer_shape=_fill_infer)
def assign_value(ctx, ins, attrs):
    vals = np.array(attrs["values"], dtype=_dev_dtype(attrs.get("dtype", "float32")))
    return {"Out": [jnp.asarray(vals).reshape(tuple(attrs["shape"]))]}


@register_op("shape")
def shape_op(ctx, ins, attrs):
    return {"Out": [jnp.asarray(jnp.shape(ins["Input"][0]), jnp.int32)]}


# -- dtype / layout ---------------------------------------------------------

def _cast_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape = x.shape
    out.dtype = op.attrs["out_dtype"]


@register_op("cast", infer_shape=_cast_infer)
def cast(ctx, ins, attrs):
    return {"Out": [ins["X"][0].astype(_dev_dtype(attrs["out_dtype"]))]}


# -- shape manipulation -----------------------------------------------------

def _reshape_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    shape = list(op.attrs["shape"])
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    known = int(np.prod([s for s in shape if s != -1]))
    total = int(np.prod(x.shape)) if x.shape and all(d >= 0 for d in x.shape) else None
    if -1 in shape and total is not None:
        shape[shape.index(-1)] = total // known
    out.shape = tuple(shape)
    out.dtype = x.dtype


@register_op("reshape", infer_shape=_reshape_infer)
def reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    return {"Out": [jnp.reshape(x, tuple(shape))]}


def _transpose_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    perm = op.attrs["axis"]
    out.shape = tuple(x.shape[p] for p in perm) if x.shape else ()
    out.dtype = x.dtype


@register_op("transpose", infer_shape=_transpose_infer)
def transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


def _concat_infer(op, block):
    xs = [block.var(n) for n in op.input("X")]
    out = block.var(op.output("Out")[0])
    axis = op.attrs.get("axis", 0)
    shape = list(xs[0].shape)
    if shape:
        shape[axis] = sum(v.shape[axis] for v in xs)
    out.shape = tuple(shape)
    out.dtype = xs[0].dtype


@register_op("concat", infer_shape=_concat_infer)
def concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


def _split_infer(op, block):
    x = block.var(op.input("X")[0])
    axis = op.attrs.get("axis", 0)
    sections = op.attrs.get("sections") or []
    num = op.attrs.get("num", 0)
    outs = [block.var(n) for n in op.output("Out")]
    if not sections and num:
        sections = [x.shape[axis] // num] * num
    for v, s in zip(outs, sections):
        shape = list(x.shape)
        shape[axis] = s
        v.shape, v.dtype = tuple(shape), x.dtype


@register_op("split", infer_shape=_split_infer)
def split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    sections = attrs.get("sections") or []
    if sections:
        idx = np.cumsum(sections[:-1]).tolist()
        return {"Out": list(jnp.split(x, idx, axis=axis))}
    return {"Out": list(jnp.split(x, attrs["num"], axis=axis))}


def _stack_infer(op, block):
    xs = [block.var(n) for n in op.input("X")]
    out = block.var(op.output("Y")[0])
    axis = op.attrs.get("axis", 0)
    shape = list(xs[0].shape)
    shape.insert(axis if axis >= 0 else axis + len(shape) + 1, len(xs))
    out.shape, out.dtype = tuple(shape), xs[0].dtype


@register_op("stack", infer_shape=_stack_infer)
def stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    return {"Y": [jnp.squeeze(s, axis) for s in jnp.split(x, n, axis)]}


def _squeeze_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    axes = op.attrs.get("axes", [])
    if axes:
        out.shape = tuple(s for i, s in enumerate(x.shape)
                          if i not in [a % len(x.shape) for a in axes])
    else:
        out.shape = tuple(s for s in x.shape if s != 1)
    out.dtype = x.dtype


@register_op("squeeze", infer_shape=_squeeze_infer)
def squeeze(ctx, ins, attrs):
    x = ins["X"][0]
    axes = attrs.get("axes", [])
    if not axes:
        return {"Out": [jnp.squeeze(x)]}
    return {"Out": [jnp.squeeze(x, axis=tuple(a % x.ndim for a in axes))]}


def _unsqueeze_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    shape = list(x.shape)
    for a in sorted(op.attrs["axes"]):
        shape.insert(a, 1)
    out.shape, out.dtype = tuple(shape), x.dtype


@register_op("unsqueeze", infer_shape=_unsqueeze_infer)
def unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": [x]}


def _flatten_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    ax = op.attrs.get("axis", 1)
    lead = int(np.prod(x.shape[:ax])) if x.shape else 1
    out.shape = (lead, int(np.prod(x.shape[ax:])))
    out.dtype = x.dtype


@register_op("flatten", infer_shape=_flatten_infer)
def flatten(ctx, ins, attrs):
    x = ins["X"][0]
    ax = attrs.get("axis", 1)
    return {"Out": [jnp.reshape(x, (int(np.prod(x.shape[:ax]) or 1), -1))]}


def _expand_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    times = op.attrs["expand_times"]
    if x.shape and len(x.shape) == len(times):
        out.shape = tuple(d * t if d != -1 else -1
                          for d, t in zip(x.shape, times))
    out.dtype = x.dtype


@register_op("expand", infer_shape=_expand_infer)
def expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register_op("reverse", infer_shape=same_shape())
def reverse(ctx, ins, attrs):
    return {"Out": [jnp.flip(ins["X"][0], axis=tuple(attrs["axis"]))]}


def _pad_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    p = op.attrs["paddings"]
    out.shape = tuple(s + p[2 * i] + p[2 * i + 1] for i, s in enumerate(x.shape))
    out.dtype = x.dtype


@register_op("pad", infer_shape=_pad_infer)
def pad(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))]}


@register_op("crop")
def crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    return {"Out": [jax.lax.dynamic_slice(x, offsets, shape)]}


def _slice_infer(op, block):
    x = block.var(op.input("Input")[0])
    out = block.var(op.output("Out")[0])
    shape = list(x.shape)
    for ax, st, en in zip(op.attrs["axes"], op.attrs["starts"], op.attrs["ends"]):
        size = x.shape[ax]
        st2 = max(st + size, 0) if st < 0 else min(st, size)
        en2 = max(en + size, 0) if en < 0 else min(en, size)
        shape[ax] = max(en2 - st2, 0)
    out.shape, out.dtype = tuple(shape), x.dtype


@register_op("slice", infer_shape=_slice_infer)
def slice_op(ctx, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        idx[ax] = slice(st, en)
    return {"Out": [x[tuple(idx)]]}


# -- gather/scatter/indexing ------------------------------------------------

def _gather_infer(op, block):
    x = block.var(op.input("X")[0])
    idx = block.var(op.input("Index")[0])
    out = block.var(op.output("Out")[0])
    out.shape = tuple(idx.shape[:1]) + tuple(x.shape[1:])
    out.dtype = x.dtype


@register_op("gather", infer_shape=_gather_infer)
def gather(ctx, ins, attrs):
    idx = ins["Index"][0].astype(jnp.int32).reshape(-1)
    return {"Out": [jnp.take(ins["X"][0], idx, axis=0)]}


@register_op("scatter", infer_shape=same_shape())
def scatter(ctx, ins, attrs):
    x, idx, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    idx = idx.astype(jnp.int32).reshape(-1)
    if attrs.get("overwrite", True):
        return {"Out": [x.at[idx].set(upd)]}
    return {"Out": [x.at[idx].add(upd)]}


def _onehot_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    shape = list(x.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    out.shape = tuple(shape) + (op.attrs["depth"],)
    out.dtype = "float32"


@register_op("one_hot", infer_shape=_onehot_infer)
def one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    if x.shape and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    return {"Out": [jax.nn.one_hot(x.astype(jnp.int32), attrs["depth"])]}


def _lookup_infer(op, block):
    ids = block.var(op.input("Ids")[0])
    w = block.var(op.input("W")[0])
    out = block.var(op.output("Out")[0])
    shape = list(ids.shape)
    if shape and shape[-1] == 1:
        shape = shape[:-1]
    out.shape = tuple(shape) + (w.shape[1],)
    out.dtype = w.dtype


@register_op("lookup_table", infer_shape=_lookup_infer)
def lookup_table(ctx, ins, attrs):
    """lookup_table_op.cc: embedding gather. padding_idx rows read as zero.

    is_sparse=True grads: the autodiff (core/lowering.py) differentiates
    through a zero surrogate added to the gathered rows instead of through
    the table, yielding a RowSparseGrad (≙ SelectedRows grad,
    lookup_table_op.cc's sparse path) whose size is O(n_ids), not O(vocab).
    is_distributed=True is handled at layer level: the table is annotated
    vocab-sharded over the mesh so GSPMD partitions the gather
    (≙ distributed lookup table, distribute_transpiler.py:120-180)."""
    from ..core.selected_rows import squeeze_trailing_ids
    ids, w = ins["Ids"][0], ins["W"][0]
    ids = squeeze_trailing_ids(ids)

    block0 = getattr(ctx, "block_idx", 0) == 0
    probe = getattr(ctx, "sparse_probe", None)
    if probe is not None and attrs.get("is_sparse") and block0:
        probe[ctx.op_index] = ids
    sur = getattr(ctx, "sparse_surrogates", None)
    if (sur is not None and block0 and ctx.op_index in sur
            and attrs.get("is_sparse")):
        out = jnp.take(jax.lax.stop_gradient(w), ids, axis=0) \
            + sur[ctx.op_index]
    else:
        out = jnp.take(w, ids, axis=0)
    pidx = attrs.get("padding_idx", -1)
    if pidx is not None and pidx >= 0:
        out = jnp.where((ids == pidx)[..., None], 0.0, out)
    return {"Out": [out]}


@register_op("multiplex")
def multiplex(ctx, ins, attrs):
    ids = ins["Ids"][0].astype(jnp.int32).reshape(-1)
    stacked = jnp.stack(ins["X"], axis=0)
    rows = jnp.arange(stacked.shape[1])
    return {"Out": [stacked[ids, rows]]}


@register_op("where_op", infer_shape=same_shape())
def where_op(ctx, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register_op("arange", infer_shape=None)
def arange(ctx, ins, attrs):
    return {"Out": [jnp.arange(attrs["start"], attrs["end"], attrs.get("step", 1),
                               dtype=_dev_dtype(attrs.get("dtype", "int32")))]}


@register_op("linspace")
def linspace(ctx, ins, attrs):
    return {"Out": [jnp.linspace(attrs["start"], attrs["stop"], attrs["num"],
                                 dtype=_dev_dtype(attrs.get("dtype", "float32")))]}


@register_op("bilinear_interp")
def bilinear_interp(ctx, ins, attrs):
    """bilinear_interp_op.cc: NCHW resize via jax.image (`method` attr
    also admits "nearest" for layers.image_resize(resample="NEAREST"))."""
    x = ins["X"][0]
    oh = attrs.get("out_h")
    ow = attrs.get("out_w")
    out = jax.image.resize(x, (x.shape[0], x.shape[1], oh, ow),
                           method=attrs.get("method", "bilinear"))
    return {"Out": [out]}


@register_op("random_crop")
def random_crop(ctx, ins, attrs):
    x = ins["X"][0]
    shape = attrs["shape"]
    key = ctx.next_rng_key()
    ndim = x.ndim
    crop_dims = len(shape)
    starts = []
    for i, target in enumerate(shape):
        dim = ndim - crop_dims + i
        limit = x.shape[dim] - target
        k = jax.random.fold_in(key, i)
        starts.append(jax.random.randint(k, (), 0, max(limit, 0) + 1))
    full_starts = [jnp.zeros((), jnp.int32)] * (ndim - crop_dims) + starts
    sizes = list(x.shape[:ndim - crop_dims]) + list(shape)
    return {"Out": [jax.lax.dynamic_slice(x, full_starts, sizes)]}


def _pad_constant_like_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    out.shape, out.dtype = x.shape, block.var(op.input("Y")[0]).dtype


@register_op("pad_constant_like", infer_shape=_pad_constant_like_infer)
def pad_constant_like(ctx, ins, attrs):
    """pad_constant_like_op.cc: pad Y up to X's (larger) shape with
    pad_value; a shape-driven variant of pad used by seq2seq decoders."""
    x, y = ins["X"][0], ins["Y"][0]
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": [jnp.pad(y, pads,
                            constant_values=attrs.get("pad_value", 0.0))]}


@register_op("split_ids")
def split_ids(ctx, ins, attrs):
    """split_ids_op.cc: route each id to shard id%N (the distributed
    lookup-table dispatcher, distribute_transpiler.py:120-180). The
    reference emits N variable-length LoD outputs; the dense redesign
    keeps each output the full id shape with non-owned slots masked to
    -1 — shard k's lookup gathers only rows it owns, matching the
    vocab-sharded embedding design (docs/distributed_embedding.md)."""
    ids = ins["Ids"][0]
    n = int(attrs["num_shards"])
    outs = [jnp.where(ids % n == k, ids, -1) for k in range(n)]
    return {"Out": outs}


@register_op("merge_ids")
def merge_ids(ctx, ins, attrs):
    """merge_ids_op: inverse of split_ids — merge per-shard embedding rows
    back into the original id order. Ids is the original [N] id tensor;
    Rows is the per-shard stack [num_shards, N, D] where shard k filled
    only the slots it owns (others zero); output [N, D] sums the slots."""
    if len(ins["Rows"]) > 1:
        rows = jnp.stack(ins["Rows"], axis=0)      # N separate [N,D] shards
    elif ins["Rows"][0].ndim == 3:
        rows = ins["Rows"][0]                      # already-stacked [S, N, D]
    else:
        return {"Out": [ins["Rows"][0]]}           # single shard owns all ids
    return {"Out": [rows.sum(axis=0)]}
