"""3-D conv/pool family + unpool + remaining small ops.

≙ reference paddle/fluid/operators/{conv3d via conv_op.cc, conv3d_transpose,
pool3d + max_pool3d_with_index via pool_op/pool_with_index, unpool_op,
bilinear_tensor_product_op, conv_shift_op, cos_sim_op, l1_norm_op, norm_op,
margin_rank_loss_op, minus_op, modified_huber_loss_op, fill_op, print_op,
gru_unit_op, lstm_unit_op}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op, same_shape


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _conv3d_infer(op, block):
    x = block.var(op.input("Input")[0])
    w = block.var(op.input("Filter")[0])
    out = block.var(op.output("Output")[0])
    st = _triple(op.attrs.get("strides", 1))
    pd = _triple(op.attrs.get("paddings", 0))
    dl = _triple(op.attrs.get("dilations", 1))
    dims = tuple((x.shape[2 + i] + 2 * pd[i]
                  - (dl[i] * (w.shape[2 + i] - 1) + 1)) // st[i] + 1
                 for i in range(3))
    out.shape = (x.shape[0], w.shape[0]) + dims
    out.dtype = x.dtype


@register_op("conv3d", infer_shape=_conv3d_infer)
def conv3d(ctx, ins, attrs):
    """NCDHW conv (conv_op.cc 3-D path) → XLA conv_general_dilated."""
    from .math_ops import harmonize
    x, w = ins["Input"][0], ins["Filter"][0]
    w = harmonize(x, w)
    s = _triple(attrs.get("strides", 1))
    p = _triple(attrs.get("paddings", 0))
    d = _triple(attrs.get("dilations", 1))
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=s, padding=[(pi, pi) for pi in p],
        rhs_dilation=d, dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        feature_group_count=attrs.get("groups", 1) or 1)
    return {"Output": [out]}


@register_op("conv3d_transpose")
def conv3d_transpose(ctx, ins, attrs):
    from .math_ops import harmonize
    x, w = ins["Input"][0], ins["Filter"][0]
    w = harmonize(x, w)
    s = _triple(attrs.get("strides", 1))
    p = _triple(attrs.get("paddings", 0))
    d = _triple(attrs.get("dilations", 1))
    k = w.shape[2:]
    pad = [(d[i] * (k[i] - 1) - p[i],) * 2 for i in range(3)]
    g = attrs.get("groups", 1) or 1

    def one(xg, wg):
        return jax.lax.conv_general_dilated(
            xg, jnp.flip(wg, (2, 3, 4)), window_strides=(1, 1, 1),
            padding=pad, lhs_dilation=s, rhs_dilation=d,
            dimension_numbers=("NCDHW", "IODHW", "NCDHW"))

    if g == 1:
        return {"Output": [one(x, w)]}
    # grouped transpose: per-group channel blocks (the flipped-kernel
    # trick cannot express groups via feature_group_count)
    cin = x.shape[1] // g
    outs = [one(x[:, i * cin:(i + 1) * cin], w[i * cin:(i + 1) * cin])
            for i in range(g)]
    return {"Output": [jnp.concatenate(outs, axis=1)]}


def _pool3d_infer(op, block):
    x = block.var(op.input("X")[0])
    out = block.var(op.output("Out")[0])
    if op.attrs.get("global_pooling", False):
        out.shape = tuple(x.shape[:2]) + (1, 1, 1)
    else:
        k = _triple(op.attrs["ksize"])
        st = _triple(op.attrs.get("strides", 1))
        pd = _triple(op.attrs.get("paddings", 0))
        dims = tuple((x.shape[2 + i] + 2 * pd[i] - k[i]) // st[i] + 1
                     for i in range(3))
        out.shape = tuple(x.shape[:2]) + dims
    out.dtype = x.dtype


@register_op("pool3d", infer_shape=_pool3d_infer)
def pool3d(ctx, ins, attrs):
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": [red(x, axis=(2, 3, 4), keepdims=True)]}
    k = _triple(attrs["ksize"])
    s = _triple(attrs.get("strides", 1))
    p = _triple(attrs.get("paddings", 0))
    dims = (1, 1) + k
    strides = (1, 1) + s
    pads = ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides,
                                    pads)
    else:
        ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides,
                                     pads)
        if attrs.get("exclusive", True):
            ones = jnp.ones(x.shape[2:], x.dtype)  # spatial-only, once
            cnt = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, k, s,
                tuple((pi, pi) for pi in p))
            out = ssum / cnt
        else:
            out = ssum / float(k[0] * k[1] * k[2])
    return {"Out": [out]}


@register_op("max_pool3d_with_index")
def max_pool3d_with_index(ctx, ins, attrs):
    x = ins["X"][0]
    k = _triple(attrs["ksize"])
    s = _triple(attrs.get("strides", k))
    p = _triple(attrs.get("paddings", 0))
    B, C, D, H, W = x.shape
    od = (D + 2 * p[0] - k[0]) // s[0] + 1
    oh = (H + 2 * p[1] - k[1]) // s[1] + 1
    ow = (W + 2 * p[2] - k[2]) // s[2] + 1
    pad = jnp.pad(x, ((0, 0), (0, 0)) + tuple((pi, pi) for pi in p),
                  constant_values=-jnp.inf)
    iz = jnp.arange(od)[:, None] * s[0] + jnp.arange(k[0])[None, :]
    iy = jnp.arange(oh)[:, None] * s[1] + jnp.arange(k[1])[None, :]
    ix = jnp.arange(ow)[:, None] * s[2] + jnp.arange(k[2])[None, :]
    win = pad[:, :, iz[:, None, None, :, None, None],
              iy[None, :, None, None, :, None],
              ix[None, None, :, None, None, :]]
    flat = win.reshape(B, C, od, oh, ow, -1)
    arg = jnp.argmax(flat, axis=-1)
    out = jnp.max(flat, axis=-1)
    kz = arg // (k[1] * k[2])
    ky = (arg // k[2]) % k[1]
    kx = arg % k[2]
    gz = jnp.arange(od)[None, None, :, None, None] * s[0] + kz - p[0]
    gy = jnp.arange(oh)[None, None, None, :, None] * s[1] + ky - p[1]
    gx = jnp.arange(ow)[None, None, None, None, :] * s[2] + kx - p[2]
    idx = (gz * H + gy) * W + gx
    return {"Out": [out], "Mask": [idx.astype(jnp.int32)]}


@register_op("unpool")
def unpool(ctx, ins, attrs):
    """unpool_op.cc: scatter pooled values back to the argmax positions
    recorded by max_pool2d_with_index (flat H*W indices)."""
    x, mask = ins["X"][0], ins["Indices"][0]
    B, C, oh, ow = x.shape
    uh, uw = attrs["unpooled_height"], attrs["unpooled_width"]
    flat_idx = mask.reshape(B, C, -1).astype(jnp.int32)
    vals = x.reshape(B, C, -1)
    out = jnp.zeros((B, C, uh * uw), x.dtype)

    def one(o, i, v):
        # ASSIGN like unpool_op.cc (duplicate indices from overlapping
        # pooling windows must not sum)
        return o.at[i].set(v, mode="drop")

    out = jax.vmap(jax.vmap(one))(out, flat_idx, vals)
    return {"Out": [out.reshape(B, C, uh, uw)]}


# ---------------------------------------------------------------------------
# small math / loss stragglers
# ---------------------------------------------------------------------------

@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx, ins, attrs):
    """out[:, k] = x W_k y^T (+ bias) — bilinear_tensor_product_op.cc."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register_op("conv_shift", infer_shape=same_shape())
def conv_shift(ctx, ins, attrs):
    """conv_shift_op.cc: circular correlation (NTM attention shift).
    X [B, N], Y [B, M] (M odd, M <= N): out[i] = sum_j y[j] * x[(i + j -
    M//2) mod N]."""
    x, y = ins["X"][0], ins["Y"][0]
    n, m = x.shape[1], y.shape[1]
    half = m // 2
    idx = (jnp.arange(n)[:, None] + jnp.arange(m)[None, :] - half) % n
    return {"Out": [jnp.einsum("bnm,bm->bn", x[:, idx], y)]}


@register_op("cos_sim")
def cos_sim(ctx, ins, attrs):
    """cos_sim_op.cc; Y may be [1, D] (broadcast) or [B, D]."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(jnp.square(x), -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), -1, keepdims=True))
    out = jnp.sum(x * y, -1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register_op("l1_norm")
def l1_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0])).reshape((1,))]}


@register_op("norm")
def norm(ctx, ins, attrs):
    """norm_op.cc: l2-normalize along `axis`."""
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / n], "Norm": [n]}


@register_op("margin_rank_loss")
def margin_rank_loss(ctx, ins, attrs):
    """margin_rank_loss_op.cc: max(0, -label*(x1-x2)+margin)."""
    label, x1, x2 = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(x1.dtype)]}


@register_op("minus", infer_shape=same_shape())
def minus(ctx, ins, attrs):
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register_op("modified_huber_loss")
def modified_huber_loss(ctx, ins, attrs):
    """modified_huber_loss_op.cc: labels in {0,1} -> y in {-1,1};
    quadratic inside the margin, linear beyond."""
    x, label = ins["X"][0], ins["Y"][0]
    y = 2.0 * label - 1.0
    z = x * y
    out = jnp.where(z < -1.0, -4.0 * z,
                    jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))
    return {"Out": [out], "IntermediateVal": [z]}


from .tensor_ops import _fill_infer


@register_op("fill", infer_shape=_fill_infer)
def fill(ctx, ins, attrs):
    """fill_op.cc: constant tensor from attr data."""
    from .tensor_ops import _dev_dtype
    shape = tuple(attrs["shape"])
    data = jnp.asarray(attrs["value"],
                       _dev_dtype(attrs.get("dtype", "float32")))
    return {"Out": [jnp.broadcast_to(data.reshape(-1)[: int(np.prod(shape))]
                                     .reshape(shape), shape)
                    if jnp.size(data) > 1 else jnp.full(shape, data)]}


@register_op("print", infer_shape=same_shape("In", "Out"))
def print_op(ctx, ins, attrs):
    """print_op.cc → jax.debug.print (runs on every execution, even under
    jit; ≙ the reference printing at op-execution time)."""
    x = ins["In"][0]
    msg = attrs.get("message", "")
    safe = msg.replace("{", "{{").replace("}", "}}")  # free-text message
    jax.debug.print(safe + "{x}", x=x)
    return {"Out": [x]}


# ---------------------------------------------------------------------------
# RNN unit cells (single-step; the scan wrappers live in rnn_ops.py)
# ---------------------------------------------------------------------------

@register_op("gru_unit")
def gru_unit(ctx, ins, attrs):
    """gru_unit_op.cc: one GRU step. Input [B, 3D] (pre-projected x),
    HiddenPrev [B, D], Weight [D, 3D] layout (update|reset|cand)."""
    x, h_prev, w = ins["Input"][0], ins["HiddenPrev"][0], ins["Weight"][0]
    d = h_prev.shape[-1]
    bias = ins["Bias"][0] if ins.get("Bias") else 0.0
    acts = {"sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
            "relu": jax.nn.relu, "identity": lambda v: v}
    gact = acts[attrs.get("gate_activation", "sigmoid")]
    cact = acts[attrs.get("activation", "tanh")]
    xs = x + bias
    xu, xr, xc = xs[:, :d], xs[:, d:2 * d], xs[:, 2 * d:]
    wu, wr, wc = w[:, :d], w[:, d:2 * d], w[:, 2 * d:]
    u = gact(xu + h_prev @ wu)
    r = gact(xr + h_prev @ wr)
    c = cact(xc + (r * h_prev) @ wc)
    # gru_unit_op.h:116: h = u * (c - h_prev) + h_prev = u*c + (1-u)*h_prev
    h = u * c + (1.0 - u) * h_prev
    return {"Hidden": [h], "Gate": [jnp.concatenate([u, r, c], -1)],
            "ResetHiddenPrev": [r * h_prev]}


@register_op("lstm_unit")
def lstm_unit(ctx, ins, attrs):
    """lstm_unit_op.h:63-66: one LSTM step from pre-computed gate pre-
    activations X [B, 4D] in the reference's i|f|o|g layout, C_prev
    [B, D]."""
    x, c_prev = ins["X"][0], ins["C_prev"][0]
    d = c_prev.shape[-1]
    forget_bias = attrs.get("forget_bias", 0.0)
    i = jax.nn.sigmoid(x[:, :d])
    f = jax.nn.sigmoid(x[:, d:2 * d] + forget_bias)
    o = jax.nn.sigmoid(x[:, 2 * d:3 * d])
    g = jnp.tanh(x[:, 3 * d:])
    c = f * c_prev + i * g
    return {"C": [c], "H": [o * jnp.tanh(c)]}
