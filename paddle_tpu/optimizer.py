"""Optimizer library: minimize = append_backward + accumulators + update ops.

≙ reference python/paddle/fluid/optimizer.py:36-970 (Optimizer base:36,
SGD:257, Momentum:283, Adagrad:327, Adam:368, Adamax:473, DecayedAdagrad:557,
Adadelta:601, RMSProp:683, Ftrl, ModelAverage:818). The structure is
preserved exactly: `minimize` appends backward, regularization, clipping,
then one update op per parameter; accumulators are persistable vars
initialized via the startup program. All of it compiles into the single
per-step XLA executable.
"""

from __future__ import annotations

import os
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from .core.program import (VarDesc, default_main_program,
                           default_startup_program, unique_name, program_guard)
from .backward import append_backward
from .layer_helper import LayerHelper
from .initializer import ConstantInitializer
from .regularizer import append_regularization_ops
from .clip import append_gradient_clip_ops, error_clip_callback


def _opt_state_dtype() -> Optional[str]:
    """PT_OPT_STATE_DTYPE: precision policy for the param-shaped moment
    accumulators (Adam m/v, Momentum velocity). 'bfloat16' halves the
    optimizer-state HBM residency — for Adam the single largest state
    term after the params themselves — at a precision cost the bf16
    moment literature accepts (the moments are statistics, not masters;
    the update math still runs f32 in the op kernels and the params stay
    f32). The memory estimator (analysis/memory.py) prices accumulators
    at their RECORDED dtype, so the saving is visible to the
    PT_MEM_BUDGET_GB gate and the placement planner before anything
    compiles. Unset/float32 = off. Scalar beta-power accumulators always
    stay f32 (they steer the bias correction; narrowing them would decay
    the correction itself)."""
    raw = os.environ.get("PT_OPT_STATE_DTYPE", "").strip().lower()
    if raw in ("", "0", "off", "float32", "f32", "fp32"):
        return None
    if raw in ("bfloat16", "bf16"):
        return "bfloat16"
    raise ValueError(f"malformed PT_OPT_STATE_DTYPE={raw!r}: expected "
                     "bfloat16 (or unset/float32)")


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None):
        if not isinstance(learning_rate, (float, VarDesc)):
            raise TypeError("learning_rate must be float or Variable")
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_map: Dict[int, VarDesc] = {}
        self._accumulators: Dict[str, Dict[str, VarDesc]] = defaultdict(dict)
        self.helper: Optional[LayerHelper] = None

    # -- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self):
        prog = default_main_program()
        if id(prog) in self._learning_rate_map:
            return
        if isinstance(self._learning_rate, VarDesc):
            self._learning_rate_map[id(prog)] = self._learning_rate
            return
        helper = LayerHelper("learning_rate")
        lr = helper.create_global_variable(
            name=unique_name("learning_rate"), dtype="float32", shape=(1,),
            persistable=True)
        lr.stop_gradient = True
        helper.set_variable_initializer(
            lr, ConstantInitializer(float(self._learning_rate)))
        self._learning_rate_map[id(prog)] = lr

    def _global_learning_rate(self) -> VarDesc:
        return self._learning_rate_map[id(default_main_program())]

    def _create_param_lr(self, param_and_grad):
        """Per-param LR multiplier (ParamAttr.learning_rate, optimizer.py)."""
        param = param_and_grad[0]
        base = self._global_learning_rate()
        mult = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        if mult == 1.0:
            return base
        helper = LayerHelper("param_lr")
        out = helper.create_tmp_variable("float32")
        out.stop_gradient = True
        helper.append_op("scale", {"X": base}, {"Out": out}, {"scale": float(mult)})
        return out

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name: str, param: VarDesc, dtype=None,
                         fill_value: float = 0.0, shape=None) -> VarDesc:
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = self.helper or LayerHelper("optimizer")
        var = helper.create_global_variable(
            name=unique_name(f"{param.name}_{name}"),
            dtype=dtype or param.dtype,
            shape=tuple(shape) if shape is not None else param.shape,
            persistable=True)
        var.stop_gradient = True
        helper.set_variable_initializer(var, ConstantInitializer(fill_value))
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks for subclasses ----------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    # -- driver -------------------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        block = default_main_program().global_block
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if g is not None])
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if param_and_grad[0].trainable:
                optimize_ops.append(
                    self._append_optimize_op(block, param_and_grad))
        self._finish_update(block)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = append_backward(loss, parameter_list, no_grad_set,
                                       [error_clip_callback])
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        # training guardrails (resilience/guard.py): with PT_GUARD armed,
        # append the in-graph step-health op so the executor can run the
        # update as new_state = where(healthy, updated, old). The norm it
        # measures is the RAW @GRAD set from the autodiff boundary —
        # pre-clip, so clip_by_global_norm cannot mask an explosion.
        from .resilience.guard import maybe_instrument
        maybe_instrument(default_main_program())
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            "sgd",
            {"Param": param_and_grad[0], "Grad": param_and_grad[1],
             "LearningRate": self._create_param_lr(param_and_grad)},
            {"ParamOut": param_and_grad[0]})


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        moment_dt = _opt_state_dtype()
        for p in parameters:
            self._add_accumulator(
                self._velocity_acc_str, p,
                dtype=moment_dt if str(p.dtype) == "float32" else None)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator(self._velocity_acc_str, param_and_grad[0])
        return block.append_op(
            "momentum",
            {"Param": param_and_grad[0], "Grad": param_and_grad[1],
             "Velocity": velocity,
             "LearningRate": self._create_param_lr(param_and_grad)},
            {"ParamOut": param_and_grad[0], "VelocityOut": velocity},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov})


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            "adagrad",
            {"Param": param_and_grad[0], "Grad": param_and_grad[1],
             "Moment": moment,
             "LearningRate": self._create_param_lr(param_and_grad)},
            {"ParamOut": param_and_grad[0], "MomentOut": moment},
            {"epsilon": self._epsilon})


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        # PT_OPT_STATE_DTYPE: the param-shaped moments take the policy
        # dtype (bf16 halves Adam's optimizer-state HBM); the scalar
        # beta-power accumulators stay f32 — see _opt_state_dtype
        moment_dt = _opt_state_dtype()
        for p in parameters:
            dt = moment_dt if str(p.dtype) == "float32" else None
            self._add_accumulator(self._moment1_acc_str, p, dtype=dt)
            self._add_accumulator(self._moment2_acc_str, p, dtype=dt)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        m1 = self._get_accumulator(self._moment1_acc_str, p)
        m2 = self._get_accumulator(self._moment2_acc_str, p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        return block.append_op(
            "adam",
            {"Param": p, "Grad": param_and_grad[1],
             "LearningRate": self._create_param_lr(param_and_grad),
             "Moment1": m1, "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p},
            {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
             "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p = param_and_grad[0]
        moment = self._get_accumulator("moment", p)
        inf_norm = self._get_accumulator("inf_norm", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        op = block.append_op(
            "adamax",
            {"Param": p, "Grad": param_and_grad[1],
             "LearningRate": self._create_param_lr(param_and_grad),
             "Moment": moment, "InfNorm": inf_norm, "Beta1Pow": b1p},
            {"ParamOut": p, "MomentOut": moment, "InfNormOut": inf_norm},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon})
        # beta1_pow update (reference appends a scale op in _finish_update)
        block.append_op("scale", {"X": b1p}, {"Out": b1p},
                        {"scale": self._beta1})
        return op


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator("moment", param_and_grad[0])
        return block.append_op(
            "decayed_adagrad",
            {"Param": param_and_grad[0], "Grad": param_and_grad[1],
             "Moment": moment,
             "LearningRate": self._create_param_lr(param_and_grad)},
            {"ParamOut": param_and_grad[0], "MomentOut": moment},
            {"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("_avg_squared_grad", p)
            self._add_accumulator("_avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        g = self._get_accumulator("_avg_squared_grad", param_and_grad[0])
        u = self._get_accumulator("_avg_squared_update", param_and_grad[0])
        return block.append_op(
            "adadelta",
            {"Param": param_and_grad[0], "Grad": param_and_grad[1],
             "AvgSquaredGrad": g, "AvgSquaredUpdate": u},
            {"ParamOut": param_and_grad[0], "AvgSquaredGradOut": g,
             "AvgSquaredUpdateOut": u},
            {"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho, self._epsilon, self._momentum = rho, epsilon, momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator("momentum", param_and_grad[0])
        mean_square_acc = self._get_accumulator("mean_square", param_and_grad[0])
        return block.append_op(
            "rmsprop",
            {"Param": param_and_grad[0], "Grad": param_and_grad[1],
             "Moment": momentum_acc, "MeanSquare": mean_square_acc,
             "LearningRate": self._create_param_lr(param_and_grad)},
            {"ParamOut": param_and_grad[0], "MomentOut": momentum_acc,
             "MeanSquareOut": mean_square_acc},
            {"epsilon": self._epsilon, "decay": self._rho,
             "momentum": self._momentum})


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        sq = self._get_accumulator("squared", param_and_grad[0])
        lin = self._get_accumulator("linear", param_and_grad[0])
        return block.append_op(
            "ftrl",
            {"Param": param_and_grad[0], "Grad": param_and_grad[1],
             "SquaredAccumulator": sq, "LinearAccumulator": lin,
             "LearningRate": self._create_param_lr(param_and_grad)},
            {"ParamOut": param_and_grad[0], "SquaredAccumOut": sq,
             "LinearAccumOut": lin},
            {"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class ModelAverage(Optimizer):
    """≙ optimizer.py:818 — maintains sliding-window parameter averages via
    average_accumulates ops; apply()/restore() swap averaged params in/out."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super().__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads: List[Tuple[VarDesc, VarDesc]] = []
        main = default_main_program()
        for param in main.global_block.all_parameters():
            if param.trainable:
                grad_name = param.name + "@GRAD"
                if grad_name in main.global_block.vars:
                    self.params_grads.append(
                        (param, main.global_block.vars[grad_name]))
        self.helper = LayerHelper("model_average")
        for param, grad in self.params_grads:
            self._append_average_accumulate_op(param)

    def _append_average_accumulate_op(self, param):
        self.helper = self.helper or LayerHelper("model_average")
        sum_1 = self._add_accumulator("sum_1", param)
        sum_2 = self._add_accumulator("sum_2", param)
        sum_3 = self._add_accumulator("sum_3", param)
        num_accumulates = self._add_accumulator("num_accumulates", param,
                                                dtype="int64", shape=[1])
        old_num_accumulates = self._add_accumulator("old_num_accumulates",
                                                    param, dtype="int64",
                                                    shape=[1])
        num_updates = self._add_accumulator("num_updates", param,
                                            dtype="int64", shape=[1])
        block = default_main_program().global_block
        block.append_op(
            "average_accumulates",
            {"param": param, "in_sum_1": sum_1, "in_sum_2": sum_2,
             "in_sum_3": sum_3, "in_num_accumulates": num_accumulates,
             "in_old_num_accumulates": old_num_accumulates,
             "in_num_updates": num_updates},
            {"out_sum_1": sum_1, "out_sum_2": sum_2, "out_sum_3": sum_3,
             "out_num_accumulates": num_accumulates,
             "out_old_num_accumulates": old_num_accumulates,
             "out_num_updates": num_updates},
            {"average_window": self.average_window,
             "min_average_window": self.min_average_window,
             "max_average_window": self.max_average_window})

    def apply(self, executor, scope=None):
        """Swap params to their window averages (host-side, functional)."""
        import numpy as np
        from .core.scope import global_scope
        scope = scope or global_scope()
        self._backup = {}
        for param, _ in self.params_grads:
            s1 = np.asarray(scope.find_var(self._get_accumulator("sum_1", param).name))
            s2 = np.asarray(scope.find_var(self._get_accumulator("sum_2", param).name))
            s3 = np.asarray(scope.find_var(self._get_accumulator("sum_3", param).name))
            na = int(np.asarray(scope.find_var(
                self._get_accumulator("num_accumulates", param).name)).ravel()[0])
            ona = int(np.asarray(scope.find_var(
                self._get_accumulator("old_num_accumulates", param).name)).ravel()[0])
            total = max(na + ona, 1)
            self._backup[param.name] = np.asarray(scope.find_var(param.name))
            scope.set_var(param.name, (s1 + s2 + s3) / float(total))

    def restore(self, executor, scope=None):
        from .core.scope import global_scope
        scope = scope or global_scope()
        for name, val in getattr(self, "_backup", {}).items():
            scope.set_var(name, val)
        self._backup = {}


# public aliases matching fluid
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
