"""Parallelism: device meshes, SPMD executors, sharding passes, collectives.

≙ reference ParallelExecutor + framework/details/ + transpiler/ + the three
communication backends of SURVEY.md §2.3, all re-realized as XLA collectives
over a jax.sharding.Mesh.
"""

from .mesh import (make_mesh, default_mesh, set_default_mesh, spec_for, named,
                   mesh_from_plan, Topology, DP, TP, PP, SP, EP)
from .parallel_executor import (ParallelExecutor, BuildStrategy,
                                ExecutionStrategy, ReduceStrategy)
