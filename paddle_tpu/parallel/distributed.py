"""Multi-host bootstrap: the DCN coordination layer.

≙ reference multi-node rendezvous: gen_nccl_id_op.cc:30-90 (trainer-0 mints
an ncclUniqueId and RPC-broadcasts it) + NCCLContextMap rank math
(platform/nccl_helper.h:81-120) + the env-var job contract
(PADDLE_TRAINER_ID/PADDLE_TRAINERS/PADDLE_PSERVER_IPS, trainer.py:226,
benchmark/fluid/fluid_benchmark.py:62). TPU-native: one call to
jax.distributed.initialize(coordinator, num_processes, process_id) — the
coordinator address IS the rendezvous, XLA owns the collectives, and the
global device mesh spans all hosts' chips over ICI/DCN.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Explicit multi-host init. Safe to call once per process."""
    global _initialized
    if _initialized:
        return
    if num_processes is None or num_processes <= 1:
        _initialized = True
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def initialize_from_env():
    """Read the reference's env contract and initialize.

    PADDLE_TRAINERS          ≙ num_processes
    PADDLE_TRAINER_ID        ≙ process_id
    PADDLE_COORDINATOR       — coordinator host:port (new; plays the role of
                               the pserver-0 endpoint used for gen_nccl_id)
    Falls back to PADDLE_PSERVER_IPS[0]:PADDLE_PSERVER_PORT for the
    coordinator so reference launch scripts keep working.
    """
    trainers = int(os.getenv("PADDLE_TRAINERS", "1"))
    if trainers <= 1:
        return
    trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    coord = os.getenv("PADDLE_COORDINATOR")
    if coord is None:
        ips = os.getenv("PADDLE_PSERVER_IPS", "")
        port = os.getenv("PADDLE_PSERVER_PORT", "6174")
        if ips:
            coord = f"{ips.split(',')[0]}:{port}"
    initialize(coord, trainers, trainer_id)


def axis_spans_hosts(axis_sizes, axis: str, chips_per_host: int) -> bool:
    """Does mesh axis `axis` connect devices on DIFFERENT hosts?

    make_mesh lays devices out row-major over the ordered axis dict, so
    an axis's communication groups stride by the product of the sizes of
    the axes AFTER it; the group spans `stride * size` consecutive
    device ids. Hosts own contiguous id ranges (jax.distributed device
    enumeration), so the group stays on one host iff that span fits in
    chips_per_host. This is the planner's ICI-vs-DCI pricing predicate
    (analysis/planner.py) and the multi-host reading of the mesh axis
    convention in mesh.py.
    """
    names = list(axis_sizes)
    if axis not in names:
        return False
    sizes = [int(axis_sizes[a]) for a in names]
    i = names.index(axis)
    if sizes[i] <= 1:
        return False
    stride = 1
    for s in sizes[i + 1:]:
        stride *= s
    # a group along the axis occupies one contiguous id block of width
    # stride * size (ids decompose hi*span + mid*stride + lo; the group
    # fixes hi and lo). The mesh occupies device ids [0, total); a
    # sub-mesh that fits on the first host never crosses. Beyond that,
    # every block stays on one host iff the blocks tile the host ranges
    # evenly — span <= chips_per_host alone is NOT enough when it does
    # not divide (a span-2 block can straddle two 3-chip hosts)
    cph = max(1, int(chips_per_host))
    total = 1
    for s in sizes:
        total *= s
    if total <= cph:
        return False
    span = stride * sizes[i]
    return span > cph or cph % span != 0


def host_axis_split(axis_sizes, chips_per_host: int):
    """Partition ordered mesh axes into (dcn_axes, ici_axes): the axes
    whose collectives cross the host boundary vs the ones that stay on
    intra-host ICI. The planner prices collectives with this split; a
    launch script can use it to sanity-check that only the cheap-to-sync
    axes (dp grad-sync once a step) land on DCN."""
    dcn = [a for a in axis_sizes
           if axis_spans_hosts(axis_sizes, a, chips_per_host)]
    ici = [a for a in axis_sizes
           if int(axis_sizes[a]) > 1 and a not in dcn]
    return dcn, ici


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()
