"""Multi-host bootstrap: the DCN coordination layer.

≙ reference multi-node rendezvous: gen_nccl_id_op.cc:30-90 (trainer-0 mints
an ncclUniqueId and RPC-broadcasts it) + NCCLContextMap rank math
(platform/nccl_helper.h:81-120) + the env-var job contract
(PADDLE_TRAINER_ID/PADDLE_TRAINERS/PADDLE_PSERVER_IPS, trainer.py:226,
benchmark/fluid/fluid_benchmark.py:62). TPU-native: one call to
jax.distributed.initialize(coordinator, num_processes, process_id) — the
coordinator address IS the rendezvous, XLA owns the collectives, and the
global device mesh spans all hosts' chips over ICI/DCN.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None):
    """Explicit multi-host init. Safe to call once per process."""
    global _initialized
    if _initialized:
        return
    if num_processes is None or num_processes <= 1:
        _initialized = True
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def initialize_from_env():
    """Read the reference's env contract and initialize.

    PADDLE_TRAINERS          ≙ num_processes
    PADDLE_TRAINER_ID        ≙ process_id
    PADDLE_COORDINATOR       — coordinator host:port (new; plays the role of
                               the pserver-0 endpoint used for gen_nccl_id)
    Falls back to PADDLE_PSERVER_IPS[0]:PADDLE_PSERVER_PORT for the
    coordinator so reference launch scripts keep working.
    """
    trainers = int(os.getenv("PADDLE_TRAINERS", "1"))
    if trainers <= 1:
        return
    trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    coord = os.getenv("PADDLE_COORDINATOR")
    if coord is None:
        ips = os.getenv("PADDLE_PSERVER_IPS", "")
        port = os.getenv("PADDLE_PSERVER_PORT", "6174")
        if ips:
            coord = f"{ips.split(',')[0]}:{port}"
    initialize(coord, trainers, trainer_id)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()
