"""Device mesh construction.

TPU-native replacement for the reference's device topology machinery
(platform/nccl_helper.h NCCLContextMap, gen_nccl_id_op rendezvous): a
jax.sharding.Mesh over local or multi-host devices. Multi-host bootstrap
(the gen_nccl_id equivalent) is jax.distributed.initialize — see
parallel/distributed.py.

Axis convention (used across the framework):
  dp — data parallel (batch)        sp — sequence/context parallel
  tp — tensor/model parallel        ep — expert parallel
  pp — pipeline stages
Any subset may be present; size-1 axes are free.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_default_mesh: Optional[Mesh] = None

DP, TP, PP, SP, EP = "dp", "tp", "pp", "sp", "ep"


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh. axes maps axis name -> size; one size may be -1 to
    absorb the remaining devices (like a reshape)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {DP: n}
    names = list(axes)
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} need {total} "
                         f"devices, have {n}")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_default_mesh(mesh: Mesh):
    global _default_mesh
    _default_mesh = mesh


def spec_for(var_sharding: Optional[Tuple], mesh: Mesh) -> PartitionSpec:
    """VarDesc.sharding tuple -> PartitionSpec, dropping axes the mesh lacks."""
    if not var_sharding:
        return PartitionSpec()
    dims = []
    for d in var_sharding:
        if d is None:
            dims.append(None)
        elif isinstance(d, (list, tuple)):
            kept = tuple(a for a in d if a in mesh.axis_names)
            dims.append(kept if kept else None)
        else:
            dims.append(d if d in mesh.axis_names else None)
    while dims and dims[-1] is None:
        dims.pop()
    return PartitionSpec(*dims)


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))
