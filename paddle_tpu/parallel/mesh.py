"""Device mesh construction.

TPU-native replacement for the reference's device topology machinery
(platform/nccl_helper.h NCCLContextMap, gen_nccl_id_op rendezvous): a
jax.sharding.Mesh over local or multi-host devices. Multi-host bootstrap
(the gen_nccl_id equivalent) is jax.distributed.initialize — see
parallel/distributed.py.

Axis convention (used across the framework):
  dp — data parallel (batch)        sp — sequence/context parallel
  tp — tensor/model parallel        ep — expert parallel
  pp — pipeline stages
Any subset may be present; size-1 axes are free.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_default_mesh: Optional[Mesh] = None

DP, TP, PP, SP, EP = "dp", "tp", "pp", "sp", "ep"


@dataclass(frozen=True)
class Topology:
    """Device-topology description for the placement planner
    (analysis/planner.py): what hardware a plan is searched FOR, not
    what this process can see — a laptop plans for a 2-host v5e pod.

    chip      PEAK_TABLE key (analysis/cost.py): per-chip peak flops,
              HBM bandwidth, ICI bandwidth, and HBM capacity.
    n_devices total chips across all hosts.
    hosts     host count; chips_per_host = n_devices // hosts. Mesh axes
              are laid out row-major (make_mesh), so the OUTERMOST axes
              are the ones that cross the host boundary.
    dci_gbps  per-chip inter-host (DCN) bandwidth — the tier a collective
              pays when any of its axes spans hosts; ICI otherwise.
    ici_gbps  intra-host tier override; None = the chip's PEAK_TABLE
              link bandwidth. Override it when planning for a fabric
              whose effective collective bandwidth differs from the
              chip's spec sheet — e.g. the 8-virtual-device CPU mesh the
              dryrun suite measures on, where a "collective" is memcpy +
              thread synchronization, nowhere near 10 GB/s effective.
    hbm_gb    per-chip HBM budget override; None = the chip's PEAK_TABLE
              capacity.
    """

    chip: str = "tpu v5e"
    n_devices: int = 8
    hosts: int = 1
    dci_gbps: float = 25.0
    ici_gbps: Optional[float] = None
    hbm_gb: Optional[float] = None

    def __post_init__(self):
        if self.n_devices < 1 or self.hosts < 1:
            raise ValueError(f"topology needs >=1 device and host, got "
                             f"{self.n_devices} devices / {self.hosts} hosts")
        if self.n_devices % self.hosts:
            raise ValueError(f"{self.n_devices} devices do not spread "
                             f"evenly over {self.hosts} hosts")

    @property
    def chips_per_host(self) -> int:
        return self.n_devices // self.hosts

    def chip_spec(self):
        # unlike cost.resolve_chip's never-crash platform detection, the
        # topology's chip is an explicit user-declared TARGET: a typo'd
        # name must raise, not silently price the pod with wrong peaks
        from ..analysis.cost import PEAK_TABLE
        kind = self.chip.lower()
        for cand in (kind, "tpu " + kind):  # bare generations: "v5e"
            for spec in PEAK_TABLE:
                if spec.name in cand:
                    return spec
        raise ValueError(
            f"topology chip {self.chip!r} does not name a PEAK_TABLE "
            f"chip ({sorted(s.name for s in PEAK_TABLE)})")

    def hbm_bytes(self) -> float:
        gb = self.hbm_gb if self.hbm_gb is not None \
            else self.chip_spec().hbm_gb
        return float(gb) * 1e9

    def ici_bandwidth_gbps(self) -> float:
        if self.ici_gbps is not None:
            return float(self.ici_gbps)
        return float(self.chip_spec().ici_gbps)

    def to_dict(self) -> dict:
        # hbm_gb recorded UNROUNDED: validate_plan re-derives the budget
        # from this field, and a rounded-down budget would reject plans
        # the search's own (exact) gate admitted
        return {"chip": self.chip, "n_devices": int(self.n_devices),
                "hosts": int(self.hosts), "dci_gbps": float(self.dci_gbps),
                "ici_gbps": self.ici_bandwidth_gbps(),
                "hbm_gb": self.hbm_bytes() / 1e9}

    @staticmethod
    def from_dict(d: dict) -> "Topology":
        """Rebuild from to_dict() output (plan artifacts record this)."""
        return Topology(chip=str(d.get("chip", "cpu")),
                        n_devices=int(d.get("n_devices", 8)),
                        hosts=int(d.get("hosts", 1)),
                        dci_gbps=float(d.get("dci_gbps", 25.0)),
                        ici_gbps=(None if d.get("ici_gbps") is None
                                  else float(d["ici_gbps"])),
                        hbm_gb=(None if d.get("hbm_gb") is None
                                else float(d["hbm_gb"])))

    @staticmethod
    def parse(spec: str) -> "Topology":
        """Parse 'chip:chips_per_host[xhosts][@dci=][@ici=][@hbm=]' —
        e.g. 'v5e:8' (one host), 'v5p:4x2@dci=50' (8 chips over 2
        hosts), 'cpu:8@ici=1@hbm=16' (the PT_PLAN_TOPOLOGY format;
        bandwidths in GB/s, hbm in GB)."""
        head, *opts = spec.strip().split("@")
        chip, _, devs = head.partition(":")
        if not devs:
            raise ValueError(f"topology {spec!r}: expected chip:devices")
        per_host, _, hosts = devs.partition("x")
        hosts = int(hosts) if hosts else 1
        kw: Dict[str, float] = {}
        names = {"dci": "dci_gbps", "ici": "ici_gbps", "hbm": "hbm_gb"}
        for opt in opts:
            k, _, v = opt.partition("=")
            if k not in names or not v:
                raise ValueError(f"topology {spec!r}: unknown option "
                                 f"{opt!r} (dci=GBPS / ici=GBPS / hbm=GB)")
            kw[names[k]] = float(v)
        return Topology(chip=chip.strip(),
                        n_devices=int(per_host) * hosts, hosts=hosts, **kw)


def make_mesh(axes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh. axes maps axis name -> size; one size may be -1 to
    absorb the remaining devices (like a reshape)."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if not axes:
        axes = {DP: n}
    names = list(axes)
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total != n:
        raise ValueError(f"mesh axes {dict(zip(names, sizes))} need {total} "
                         f"devices, have {n}")
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh() -> Mesh:
    global _default_mesh
    if _default_mesh is None:
        _default_mesh = make_mesh()
    return _default_mesh


def set_default_mesh(mesh: Mesh):
    global _default_mesh
    _default_mesh = mesh


def spec_for(var_sharding: Optional[Tuple], mesh: Mesh) -> PartitionSpec:
    """VarDesc.sharding tuple -> PartitionSpec, dropping axes the mesh lacks."""
    if not var_sharding:
        return PartitionSpec()
    dims = []
    for d in var_sharding:
        if d is None:
            dims.append(None)
        elif isinstance(d, (list, tuple)):
            kept = tuple(a for a in d if a in mesh.axis_names)
            dims.append(kept if kept else None)
        else:
            dims.append(d if d in mesh.axis_names else None)
    while dims and dims[-1] is None:
        dims.pop()
    return PartitionSpec(*dims)


def named(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def mesh_from_plan(plan, devices: Optional[Sequence] = None) -> Mesh:
    """Build the Mesh a PlacementPlan (analysis/planner.py) was scored
    for, preserving the plan's axis ORDER (outermost first — the order
    the planner's host-boundary pricing assumed). Uses the first
    n_devices local devices unless `devices` is given."""
    axes = {str(a): int(s) for a, s in dict(plan["mesh"]).items()}
    n = int(np.prod(list(axes.values())))
    if devices is None:
        devices = jax.devices()[:n]
    return make_mesh(axes, devices=devices)
