"""ParallelExecutor: SPMD data-parallel (and mesh-parallel) training.

≙ reference ParallelExecutor (paddle/fluid/framework/parallel_executor.cc:54,
python/paddle/fluid/parallel_executor.py:29) + the SSA-graph machinery in
framework/details/. The reference replicates the program per GPU, inserts
NCCL allreduce op-handles per gradient, and drives the DAG with a host
thread pool. Here the SAME lowered step function is jit-compiled over a
jax.sharding.Mesh: feeds are batch-sharded (≙ SplitLoDTensor feed split,
parallel_executor.cc:216), parameters replicated (or sharded per
BuildStrategy), and XLA GSPMD inserts the gradient all-reduces that
AllReduceOpHandle (details/all_reduce_op_handle.cc:42) hand-codes — riding
ICI instead of NCCL rings.

BuildStrategy parity (details/build_strategy.h:24-33):
  * ReduceStrategy.AllReduce — params+optimizer state replicated, grad psum.
  * ReduceStrategy.Reduce    — optimizer state sharded over dp (the modern
    ZeRO-1 reading of the reference's reduce+broadcast round-robin placement,
    multi_devices_graph_builder.cc:234-259).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..core.program import (Program, VarDesc, default_main_program,
                            iter_optimizer_state_inputs)
from ..core.scope import Scope, global_scope
from ..core.executor import Executor, TimedExecutorMixin, _Compiled
from ..core.async_fetch import LazyFetch
from ..core import lowering
from .mesh import default_mesh, spec_for, DP


class ReduceStrategy:
    AllReduce = 0
    Reduce = 1


class BuildStrategy:
    """≙ details/build_strategy.h. gradient_scale_ and debug fields kept."""

    ReduceStrategy = ReduceStrategy

    def __init__(self):
        self.reduce_strategy = ReduceStrategy.AllReduce
        self.debug_graphviz_path = ""


class ExecutionStrategy:
    """≙ details/execution_strategy.h — scheduling knobs. XLA owns
    scheduling, so these are accepted and recorded only."""

    def __init__(self):
        self.num_threads = 0
        self.allow_op_delay = False
        self.num_iteration_per_drop_scope = 100


class ParallelExecutor(TimedExecutorMixin):
    def __init__(self, use_cuda: bool = False, loss_name: Optional[str] = None,
                 main_program: Optional[Program] = None,
                 share_vars_from: Optional["ParallelExecutor"] = None,
                 exec_strategy: Optional[ExecutionStrategy] = None,
                 build_strategy: Optional[BuildStrategy] = None,
                 num_trainers: int = 1, trainer_id: int = 0,
                 scope: Optional[Scope] = None, mesh: Optional[Mesh] = None,
                 plan=None):
        """plan: a PlacementPlan (analysis/planner.py) — artifact object,
        plan/artifact dict, or a saved-artifact path. Applies the plan's
        per-var specs + sp rewrite to `main_program` in place, builds the
        mesh from the plan's axes when `mesh` is not given, and switches
        to ReduceStrategy.Reduce when the plan says ZeRO — so the
        planner-chosen placement executes with zero per-model code."""
        self._program = main_program if main_program is not None else default_main_program()
        self._scope = scope or global_scope()
        self._build_strategy = build_strategy or BuildStrategy()
        if plan is not None:
            from ..analysis.planner import apply_plan, resolve_plan
            from .mesh import mesh_from_plan
            plan = resolve_plan(plan)
            apply_plan(self._program, plan)
            if mesh is None:
                mesh = mesh_from_plan(plan)
            if plan.get("zero"):
                # copy before flipping: a caller-supplied BuildStrategy
                # must not leak Reduce into executors built without a plan
                import copy
                self._build_strategy = copy.copy(self._build_strategy)
                self._build_strategy.reduce_strategy = ReduceStrategy.Reduce
        self._mesh = mesh or default_mesh()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._loss_name = loss_name
        self._cache: Dict[tuple, _Compiled] = {}
        self._run_counter = 0
        self._init_timing()
        if share_vars_from is not None:
            self._scope = share_vars_from._scope

    # -- sharding decisions -------------------------------------------------
    def _divisible(self, spec: PartitionSpec, value) -> PartitionSpec:
        """Drop spec axes a dim cannot be evenly split over (GSPMD rejects
        explicit non-divisible shardings); e.g. a vocab of 50 over 8 devices
        falls back to replication rather than erroring. ≙ the reference's
        block-size rounding in slice_variable (distribute_transpiler.py:74),
        which also degrades placement instead of failing."""
        shape = jnp.shape(value)
        dims = []
        for i, axes in enumerate(tuple(spec)):
            if axes is None or i >= len(shape):
                dims.append(axes)
                continue
            ax_tuple = axes if isinstance(axes, tuple) else (axes,)
            size = int(np.prod([self._mesh.shape[a] for a in ax_tuple]))
            dims.append(axes if size and shape[i] % size == 0 else None)
        while dims and dims[-1] is None:
            dims.pop()
        return PartitionSpec(*dims)

    def _optimizer_state_names(self) -> dict:
        """Map accumulator var name -> its parameter name (velocity,
        moments, …). ≙ identifying the per-param state the reference's
        kReduce mode places on the grad's reduce device
        (multi_devices_graph_builder.cc:234-259). Cached per program
        CONTENT (fingerprint), so mutating the program between runs —
        which the compile cache supports — refreshes the set."""
        fp = self._program.fingerprint()
        if getattr(self, "_acc_cache_for", None) != fp:
            self._acc_cache = {acc: p for p, acc in
                               iter_optimizer_state_inputs(
                                   self._program.global_block)}
            self._acc_cache_for = fp
        return self._acc_cache

    def _state_spec(self, var: VarDesc, value) -> PartitionSpec:
        if var is not None and var.sharding:
            return self._divisible(spec_for(var.sharding, self._mesh), value)
        if var is not None and not var.is_parameter:
            # an accumulator with no sharding of its own follows its
            # parameter (same shape ⇒ same layout): a sharded param (moe
            # 'ep' experts, tp row/col shards) with replicated moments
            # would force GSPMD to all-gather every grad at the optimizer
            # update — measured on the moe leg: 8 expert-weight-shaped
            # all-gathers per step before this rule, 0 after
            p_name = self._optimizer_state_names().get(var.name)
            if p_name is not None:
                try:
                    p = self._program.global_block.var(p_name)
                except KeyError:
                    p = None
                if (p is not None and p.sharding
                        and tuple(p.shape) == tuple(var.shape)):
                    return self._divisible(spec_for(p.sharding, self._mesh),
                                           value)
        if (self._build_strategy.reduce_strategy == ReduceStrategy.Reduce
                and var is not None and not var.is_parameter
                and var.name in self._optimizer_state_names()):
            # ZeRO-1: shard the accumulator on its first dp-divisible axis.
            # GSPMD then computes the optimizer update dp-sharded (grads
            # arrive reduce-scattered) and all-gathers the updated param —
            # exactly the reduce-then-broadcast dataflow of the reference's
            # kReduce mode, derived instead of hand-built.
            shape = jnp.shape(value)
            dp_size = self._mesh.shape.get(DP, 1)
            if dp_size > 1:
                for i, s in enumerate(shape):
                    if s % dp_size == 0 and s >= dp_size:
                        return PartitionSpec(*([None] * i + [DP]))
        return PartitionSpec()

    def _feed_spec(self, var: Optional[VarDesc], value,
                   step_axis: bool = False) -> PartitionSpec:
        """step_axis: the array carries a leading [n_steps] window axis
        (run_loop per_step_feeds) — replicated; the batch axis moves to
        dim 1 and the var's own spec shifts right by one."""
        if var is not None and var.sharding:
            spec = spec_for(var.sharding, self._mesh)
            if step_axis:
                spec = PartitionSpec(None, *tuple(spec))
            # _divisible guard like _state_spec: an epoch-tail fragment
            # batch (3 rows on dp=2) must degrade to replication on the
            # offending axis, not crash jit in_shardings
            return self._divisible(spec, value)
        shape = jnp.shape(value)
        bdim = 1 if step_axis else 0
        dp_size = self._mesh.shape.get(DP, 1)
        if (len(shape) > bdim and dp_size > 1
                and shape[bdim] % dp_size == 0):
            # batch split ≙ SplitLoDTensor
            return PartitionSpec(*([None] * bdim), DP)
        return PartitionSpec()

    # -- compile ------------------------------------------------------------
    def _get_compiled(self, fetch_list: Sequence, feed: dict,
                      loop: Optional[tuple] = None, guard: bool = False):
        """Build (or fetch from cache) the jitted sharded step for this
        (program, feed-shapes, fetches) signature. Returns
        (compiled, state, feed_arrays, was_cached). `loop` = (n_steps,
        per_step_feeds, unroll) compiles a device-side lax.scan over the
        SAME sharded step — the multi-device fast path (run_loop).

        guard=True: guarded update + the step-health fetch, same contract
        as Executor (resilience/guard.py). The health scalar and the
        fault-code feed are replicated; the guarded select runs INSIDE
        the partitioned step, so it stays valid under whatever update
        sharding GSPMD picks (ZeRO-1 sharded accumulators included)."""
        program = self._program
        block = program.global_block
        t_prep = time.perf_counter()
        exe_helper = Executor()
        per_step = bool(loop and loop[1])
        fetch_names = [exe_helper._fetch_name(f) for f in fetch_list]
        feed_arrays = exe_helper._prep_feed(program, feed, per_step=per_step)
        if guard:
            from ..resilience import guard as guard_mod
            guard_mod.assert_instrumented(program)
            fetch_names = fetch_names + [guard_mod.HEALTH_VAR]
            feed_arrays[guard_mod.FAULT_FEED] = guard_mod.fault_feed(
                loop[0] if per_step else None)
            guard_key = ("guard", guard_mod.max_gnorm())
        else:
            guard_key = ()
        state = exe_helper._state_for(program, self._scope)
        self._timings.add("host_prep", time.perf_counter() - t_prep)

        feed_sig = tuple(sorted((k, v.shape, str(v.dtype))
                                for k, v in feed_arrays.items()))
        state_sig = tuple(sorted((k, jnp.shape(v), str(jnp.result_type(v)))
                                 for k, v in state.items()))
        key = (program.fingerprint(), feed_sig, tuple(fetch_names), state_sig,
               id(self._mesh), self._build_strategy.reduce_strategy, loop,
               guard_key)

        compiled = self._cache.get(key)
        was_cached = compiled is not None
        if compiled is None:
            from ..analysis import verify_enabled, verify_program
            if verify_enabled():
                # the mesh is known here, so the shard divisibility checks
                # AND the collective audit run concrete (the single-chip
                # Executor can only check axis names against the alphabet)
                verify_program(program, feeds=list(feed_arrays),
                               fetches=fetch_names,
                               mesh=self._mesh).raise_if_errors()
            # memory-budget pre-compile gate (analysis/memory.py). The
            # mesh is known, so the estimate prices the PER-DEVICE batch
            # (feeds' batch-dim shard factor divides it); params and
            # optimizer state stay whole-program — replicated under pure
            # dp, an upper bound under tp/ZeRO — conservative-safe.
            from ..analysis.memory import enforce_budget
            from ..core.executor import _autotune_batch_hint
            bh = _autotune_batch_hint(program, feed_arrays,
                                      1 if per_step else 0)
            enforce_budget(program, batch=bh, mesh=self._mesh)
            # drift monitor (obs/drift.py): whole-program roofline
            # prediction recorded at compile time, same contract as the
            # single-chip Executor — measured sharded steps fold into
            # the same pt_model_* entry
            if fetch_names:
                from ..obs import drift as obs_drift
                obs_drift.observe_prediction(program, batch=bh,
                                             timer=self._timings)
            if loop is None:
                step, state_out = lowering.build_step_fn(
                    program, list(feed_arrays), fetch_names, sorted(state),
                    mesh=self._mesh, guard=guard)
            else:
                n_steps, per_step_feeds, unroll = loop
                step, state_out = lowering.build_loop_fn(
                    program, list(feed_arrays), fetch_names, sorted(state),
                    n_steps=n_steps, mesh=self._mesh,
                    per_step_feeds=per_step_feeds, unroll=unroll,
                    guard=guard)

            def var_of(name):
                try:
                    return block.var(name)
                except KeyError:
                    return None

            mesh = self._mesh

            def feed_sharding(n, v):
                spec = self._feed_spec(var_of(n), v, step_axis=per_step)
                return NamedSharding(mesh, spec)

            state_shardings = {
                n: NamedSharding(mesh, self._state_spec(var_of(n), v))
                for n, v in state.items()}
            feed_shardings = {n: feed_sharding(n, v)
                              for n, v in feed_arrays.items()}
            rng_sharding = NamedSharding(mesh, PartitionSpec())
            out_state_shardings = {
                n: state_shardings.get(n, NamedSharding(mesh, self._state_spec(var_of(n), state.get(n))))
                for n in state_out}
            fetch_shardings = tuple(NamedSharding(mesh, PartitionSpec())
                                    for _ in fetch_names)
            fn = jax.jit(step,
                         in_shardings=(state_shardings, feed_shardings,
                                       rng_sharding),
                         out_shardings=(fetch_shardings, out_state_shardings),
                         donate_argnums=(0,))
            compiled = _Compiled(fn, sorted(state), state_out, fetch_names)
            self._cache[key] = compiled
        return compiled, state, feed_arrays, was_cached

    def compiled_hlo(self, fetch_list: Sequence,
                     feed: Optional[dict] = None) -> str:
        """Post-GSPMD optimized HLO of the sharded step, for inspection.

        On a rig with no multi-chip hardware this is the load-bearing
        evidence of WHAT the parallelism axes actually emit — tests count
        collective instructions (all-reduce / reduce-scatter /
        collective-permute / all-to-all) instead of assuming GSPMD chose
        the intended program (tests/test_collectives_emitted.py)."""
        compiled, state, feed_arrays, _ = self._get_compiled(fetch_list,
                                                             feed or {})
        rng = jax.random.PRNGKey(0)
        with self._mesh:
            return compiled.fn.lower(state, feed_arrays,
                                     rng).compile().as_text()

    # -- run ----------------------------------------------------------------
    def run_loop(self, fetch_list: Sequence, feed: Optional[dict] = None,
                 n_steps: int = 1, per_step_feeds: bool = False,
                 unroll: int = 2, return_numpy: bool = True,
                 lazy: bool = False, guard: bool = False):
        """Run `n_steps` SHARDED training steps in one device dispatch:
        lax.scan over the same GSPMD-partitioned step `run` executes.

        This is the multi-device reading of the reference's hot loop —
        ParallelExecutor::Run drives the whole multi-GPU step graph per
        call (parallel_executor.cc:193, threaded_ssa_graph_executor.cc) —
        composed with the device-side loop that is this runtime's fast
        path (host dispatch costs 150-250 ms on the benched fabric;
        docs/design_decisions.md). Feeds follow Executor.run_loop
        semantics: same dict every step, or a leading [n_steps] axis with
        per_step_feeds=True (the batch axis then dp-shards at dim 1).
        Fetches come back stacked [n_steps, ...]."""
        feed = feed or {}
        compiled, state, feed_arrays, was_cached = self._get_compiled(
            fetch_list, feed, loop=(n_steps, per_step_feeds, unroll),
            guard=guard)
        return self._execute(compiled, state, feed_arrays, return_numpy,
                             was_cached, lazy=lazy, n_steps=n_steps)

    def run(self, fetch_list: Sequence, feed: Optional[dict] = None,
            feed_dict: Optional[dict] = None, return_numpy: bool = True,
            lazy: bool = False, guard: bool = False):
        """lazy=True: LazyFetch handles, same contract as Executor.run —
        the sharded step is enqueued and the host moves on. guard=True:
        guarded update + step-health fetch (resilience/guard.py)."""
        feed = feed if feed is not None else (feed_dict or {})
        compiled, state, feed_arrays, was_cached = self._get_compiled(
            fetch_list, feed, guard=guard)
        return self._execute(compiled, state, feed_arrays, return_numpy,
                             was_cached, lazy=lazy)

    def _execute(self, compiled, state, feed_arrays, return_numpy,
                 was_cached=True, lazy=False, n_steps=1):
        program = self._program
        seed = program.random_seed if program.random_seed is not None else 0
        self._run_counter += 1
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), self._run_counter)
        # measured-step recorder (obs/drift.py): settle-to-settle gaps,
        # cached runs only — see Executor._run_impl for the rationale
        settle = None
        if was_cached and compiled.fetch_names:
            from ..obs import drift as obs_drift
            settle = obs_drift.step_recorder(program.fingerprint(),
                                             n_steps)
        t0 = time.perf_counter()
        with self._mesh:
            fetches, new_state = compiled.fn(state, feed_arrays, rng)
        self._charge_dispatch(time.perf_counter() - t0, was_cached)
        for name, val in new_state.items():
            self._scope.set_var(name, val)
        if lazy:
            from ..obs import trace as obs_trace
            span_ctx = obs_trace.current_attrs()
            return [LazyFetch(f, self._timings,
                              provenance=dict(span_ctx, fetch=n),
                              on_settle=settle)
                    for n, f in zip(compiled.fetch_names, fetches)]
        if return_numpy:
            with self._timings.span("device"):
                jax.block_until_ready(fetches)
            if settle is not None:
                settle()
            with self._timings.span("fetch"):
                # host-sync: ok — the sync return contract (return_numpy)
                return [np.asarray(f) for f in fetches]
        return list(fetches)

    @property
    def device_count(self) -> int:
        return int(np.prod(list(self._mesh.shape.values())))

    def bcast_params(self):
        """≙ ParallelExecutor::BCastParamsToGPUs (parallel_executor.cc:134).
        Under GSPMD replication is a sharding property, so this is a no-op
        kept for API parity."""
        return None
