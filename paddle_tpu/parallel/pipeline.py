"""Pipeline parallelism: GPipe and 1F1B schedules over the 'pp' axis.

ADDITIVE capability (SURVEY §2.4 last row: the reference has no pipeline
parallelism; this is north-star work designed TPU-first). Homogeneous
stages hold their parameter slice on their own devices (stacked leaves
[S, ...] sharded over 'pp'); microbatches flow stage-to-stage over ICI
via jax.lax.ppermute inside ONE lax.scan of S+M-1 ticks — the classic
bubble fraction (S-1)/(S+M-1). The whole schedule is differentiable
(scan + ppermute VJPs), so training just works through it.

Two schedules, one oracle: `gpipe` runs all M microbatches through one
fill-drain pipe (every microbatch's activations resident before the
backward); `one_f1b` bounds the in-flight window at the pipeline depth
S — the 1F1B stash bound the planner's memory model prices
(analysis/schedule.stash_microbatches: min(S, M) vs GPipe's M).
Microbatches are independent in the forward, so both schedules are
numerically identical to `sequential_stages`, and parity tests run all
three against each other.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DP, PP

__all__ = ["gpipe", "one_f1b", "sequential_stages"]


def sequential_stages(stage_fn: Callable, params, x):
    """Reference semantics: apply the S stacked stages in order (used when
    no 'pp' mesh axis is available — identical math, no parallelism)."""
    s = jax.tree.leaves(params)[0].shape[0]

    def body(carry, p_slice):
        return stage_fn(p_slice, carry), None

    # unroll: S is small and static; the rolled stage scan costs ~11% on
    # the chip (bench transpiler_sanity) because XLA cannot fuse across
    # the scan boundary
    out, _ = jax.lax.scan(body, x, params, length=s, unroll=True)
    return out


def gpipe(stage_fn: Callable, params, xs, *, mesh: Mesh, axis: str = PP):
    """Run GPipe over `mesh`'s `axis`.

    stage_fn(param_slice, x[mb, ...]) -> y[mb, ...] (same shape: stages
    are homogeneous). params: pytree with leading stage dim S == mesh
    axis size on every leaf. xs: [M, mb, ...] microbatched inputs
    (replicated). Returns [M, mb, ...] outputs, numerically identical to
    applying the S stages sequentially.
    """
    s = int(mesh.shape[axis])
    m = int(xs.shape[0])
    perm = [(i, (i + 1) % s) for i in range(s)]
    # split the per-microbatch batch dim over 'dp' when present so data-
    # parallel replicas pipeline their own slice instead of redundantly
    # recomputing the full batch
    dp = int(mesh.shape.get(DP, 1))
    x_spec = P(None, DP) if dp > 1 and xs.shape[1] % dp == 0 else P()

    def body(local_params, xs_full):
        p = jax.tree.map(lambda a: a[0], local_params)  # this stage's slice
        idx = jax.lax.axis_index(axis)

        def tick(carry, t):
            recv, outbuf = carry
            # stage 0 consumes microbatch t (zeros once the feed drains);
            # later stages consume what the previous stage ppermuted over
            x0 = jnp.where(t < m, xs_full[jnp.minimum(t, m - 1)],
                           jnp.zeros_like(xs_full[0]))
            x_in = jnp.where(idx == 0, x0, recv)
            y = stage_fn(p, x_in)
            widx = jnp.clip(t - (s - 1), 0, m - 1)
            write = (idx == s - 1) & (t >= s - 1)
            outbuf = jnp.where(write, outbuf.at[widx].set(y), outbuf)
            recv_next = jax.lax.ppermute(y, axis, perm)
            return (recv_next, outbuf), None

        init = (jnp.zeros_like(xs_full[0]), jnp.zeros_like(xs_full))
        (_, outbuf), _ = jax.lax.scan(tick, init, jnp.arange(s + m - 1))
        # results live on the last stage; replicate via masked psum
        return jax.lax.psum(
            jnp.where(idx == s - 1, outbuf, jnp.zeros_like(outbuf)), axis)

    from ..core.compat import shard_map
    fn = shard_map(body, mesh=mesh, in_specs=(P(axis), x_spec),
                   out_specs=x_spec, check_vma=False)
    return fn(params, xs)


def one_f1b(stage_fn: Callable, params, xs, *, mesh: Mesh,
            axis: str = PP):
    """The 1F1B-windowed schedule: microbatches enter the pipe in waves
    of at most S in flight — the 1F1B window (stash bound min(S, M), vs
    GPipe's M). Within a wave the fill-drain tick scan is reused
    verbatim; forward microbatches are independent, so the output is
    numerically identical to `gpipe`/`sequential_stages` (parity-tested)
    — the schedule only changes ORDER.

    Residency caveat (ROADMAP open item): the wave structure bounds
    IN-FLIGHT microbatches, but jax's whole-program reverse-mode AD
    still saves every wave's residuals until the backward runs — so on
    THIS runtime the min(S, M) activation stash is the 1F1B schedule's
    semantic bound (what the planner's memory model prices for the
    deployment target), not yet a measured residency guarantee; a
    staged custom-VJP backward is the realization path.

    Same contract as gpipe: params [S, ...]-stacked over `axis`,
    xs [M, mb, ...], returns [M, mb, ...].
    """
    s = int(mesh.shape[axis])
    m = int(xs.shape[0])
    if m <= s:
        return gpipe(stage_fn, params, xs, mesh=mesh, axis=axis)
    waves = [gpipe(stage_fn, params, xs[w:w + s], mesh=mesh, axis=axis)
             for w in range(0, m, s)]
    return jnp.concatenate(waves, axis=0)
