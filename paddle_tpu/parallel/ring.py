"""Sequence/context parallelism: ring attention + Ulysses (all-to-all).

The reference's entire long-sequence story is ragged batching (LoDTensor,
lod_tensor.h:44-110) — no sequence parallelism existed in 2018. This module
is the north-star extension SURVEY.md §5 calls for: shard the *sequence*
axis of attention over the `sp` mesh axis so context length scales with the
number of chips.

Both primitives are written to run inside `shard_map` over a Mesh whose
axis names include `sp` (see ops/attention_ops.py for how the op lowers
itself into shard_map from inside a jitted program):

* ring_attention — each device holds a [B, S/n, H, D] shard of q/k/v; K/V
  shards rotate around the ring with `jax.lax.ppermute` (one ICI hop per
  step) while a flash-style online-softmax accumulator folds in each block.
  HBM never sees the full sequence; comm is overlapped by XLA with the
  per-step einsums.
* ulysses_attention — `jax.lax.all_to_all` reshards [B, S/n, H, D] →
  [B, S, H/n, D] (sequence gathered, heads scattered), runs *local* full
  attention per head group, then reshards back. One collective each way;
  best when heads % sp == 0 and sequence fits per-device HBM.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from .mesh import SP

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _block_attn(q, k, v, scale, mask):
    """One blockwise attention piece. q:[B,Sq,H,D] k,v:[B,Sk,H,D]
    mask:[Sq,Sk] bool (True = attend) or None.
    Returns (numerator [B,Sq,H,D] f32, row max m [B,H,Sq], row sum l)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                       # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    if mask is not None:
        # rows with no visible key: keep p at 0 (m == NEG_INF there)
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return num, m, l


def ring_attention(q, k, v, *, axis_name: str = SP, causal: bool = False,
                   scale: Optional[float] = None):
    """Ring attention over sequence shards. q,k,v: [B, S_local, H, D]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]   # shard i -> i+1

    qf = q.astype(jnp.float32)
    o = jnp.zeros((b, s_loc, h, d), jnp.float32)
    m = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)

    def step(i, carry):
        o, m, l, kb, vb = carry
        # kb arrived from shard (my - i) mod n — its global chunk index
        src = (my - i) % n
        if causal:
            qpos = my * s_loc + jnp.arange(s_loc)[:, None]
            kpos = src * s_loc + jnp.arange(s_loc)[None, :]
            mask = kpos <= qpos
        else:
            mask = None
        num, m_cur, l_cur = _block_attn(qf, kb.astype(jnp.float32),
                                        vb.astype(jnp.float32), scale, mask)
        m_new = jnp.maximum(m, m_cur)
        # guard exp(-inf - -inf)
        alpha = jnp.where(m == NEG_INF, 0.0, jnp.exp(m - m_new))
        beta = jnp.where(m_cur == NEG_INF, 0.0, jnp.exp(m_cur - m_new))
        l = l * alpha + l_cur * beta
        o = o * alpha.transpose(0, 2, 1)[..., None] \
            + num * beta.transpose(0, 2, 1)[..., None]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (o, m_new, l, kb, vb)

    o, m, l, _, _ = jax.lax.fori_loop(0, n, step, (o, m, l, k, v))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = o / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = SP,
                      causal: bool = False, scale: Optional[float] = None,
                      attn_fn=None):
    """DeepSpeed-Ulysses-style SP. q,k,v: [B, S_local, H, D]; requires
    H % sp_size == 0. attn_fn(q,k,v,causal,scale) runs on the full sequence
    with H/sp heads — defaults to the flash/reference dispatcher."""
    from ..kernels.flash_attention import dot_product_attention
    if attn_fn is None:
        def attn_fn(q, k, v, causal, scale):
            return dot_product_attention(q, k, v, causal=causal, scale=scale)

    def a2a(x, seq_to_head: bool):
        # [B, S/n, H, D] <-> [B, S, H/n, D]
        if seq_to_head:
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    qg, kg, vg = a2a(q, True), a2a(k, True), a2a(v, True)
    og = attn_fn(qg, kg, vg, causal, scale)
    return a2a(og, False).astype(q.dtype)
