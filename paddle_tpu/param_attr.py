"""ParamAttr: per-parameter configuration.

≙ reference python/paddle/fluid/param_attr.py (name, initializer,
learning_rate, regularizer, trainable, gradient_clip).
"""

from __future__ import annotations

from typing import Optional

from .initializer import Initializer


class ParamAttr:
    def __init__(self, name: Optional[str] = None,
                 initializer: Optional[Initializer] = None,
                 learning_rate: float = 1.0, regularizer=None,
                 trainable: bool = True, gradient_clip=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else ParamAttr(trainable=False)
        if isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


WeightNormParamAttr = ParamAttr  # placeholder parity alias
