"""Reader composition library (host data plane).

≙ reference python/paddle/reader/decorator.py:29-236 + python/paddle/batch.py.
Readers are nullary callables returning sample iterators; decorators compose
them. The device-side reader-op stack of the reference (double_buffer etc.,
layers/io.py:295-574) is subsumed by data/pipeline.py's prefetching feeder —
on a functional runtime prefetch is host logic, not graph ops.
"""

from .decorator import (map_readers, shuffle, chain, compose, buffered,
                        firstn, xmap_readers, cache)
from .decorator import batch
from .prefetch import double_buffer, DeviceFeeder
from .bucketing import bucket_by_length, bucket_bound, BucketedBatch

__all__ = ["map_readers", "shuffle", "chain", "compose", "buffered", "firstn",
           "xmap_readers", "cache", "batch", "double_buffer", "DeviceFeeder",
           "bucket_by_length", "bucket_bound", "BucketedBatch"]
