"""Length bucketing: bound ragged-batch recompiles.

≙ the reference's length-aware batching machinery (lod_rank_table +
sequence2batch length-sorted scheduling, operators/math/sequence2batch.h;
layers/control_flow.py:666-813): the 2018 design reorders sequences so no
padding is wasted. On a static-shape compiler the equivalent lever is
BUCKETS: batch sequences of similar length together and pad each batch to
its bucket's bound, so an epoch of arbitrary lengths compiles at most
len(bounds)+1 executables instead of one per distinct batch shape.
"""

from __future__ import annotations

import bisect
from typing import Callable, Optional, Sequence

__all__ = ["BucketedBatch", "bucket_bound", "bucket_by_length"]


def bucket_bound(n: int, bounds: Sequence[int]) -> int:
    """The pad length for a sample of length `n` under sorted `bounds`:
    the smallest bound >= n, or — past the last bound — the next multiple
    of the last bound (overflow shapes stay bounded: at most one per
    multiple actually seen). Shared by the training-side bucketing below
    and the serving micro-batcher (serving/batcher.py), so the two pad
    the same length to the same shape and hit the same compiled
    executable."""
    i = bisect.bisect_left(bounds, n)
    if i < len(bounds):
        return bounds[i]
    last = bounds[-1]
    return ((n + last - 1) // last) * last  # overflow multiples


class BucketedBatch(list):
    """A list of samples + the pinned pad length for its ragged slots.
    DataFeeder honors `pad_to` so every batch from the same bucket has
    the identical padded shape."""

    def __init__(self, samples, pad_to: int):
        super().__init__(samples)
        self.pad_to = pad_to


def bucket_by_length(reader: Callable, batch_size: int,
                     bounds: Sequence[int] = (16, 32, 64, 128, 256),
                     key: Optional[Callable] = None,
                     drop_last: bool = False):
    """Decorator: group samples into length buckets, yield BucketedBatch.

    key(sample) -> length; defaults to len(sample[0]). Samples longer than
    the last bound fall into an overflow bucket padded to the next
    multiple of the last bound (shapes stay bounded: at most one overflow
    shape per multiple actually seen).
    """
    bounds = sorted(bounds)
    key = key or (lambda sample: len(sample[0]))

    def bucketed():
        buckets = {}

        for sample in reader():
            b = bucket_bound(key(sample), bounds)
            bucket = buckets.setdefault(b, [])
            bucket.append(sample)
            if len(bucket) == batch_size:
                yield BucketedBatch(bucket, b)
                buckets[b] = []
        if not drop_last:
            for b in sorted(buckets):
                if buckets[b]:
                    yield BucketedBatch(buckets[b], b)

    return bucketed
