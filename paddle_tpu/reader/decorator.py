"""Reader decorators (≙ python/paddle/reader/decorator.py).

A *reader creator* is a nullary callable returning an iterator of samples.
"""

from __future__ import annotations

import itertools
import random
import queue as _queue
import threading
from typing import Callable, Iterable, List


def map_readers(func: Callable, *readers):
    """decorator.py:29 — zip N readers and map func over the tuples."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size: int):
    """decorator.py:51 — pool-based shuffling with a bounded buffer."""

    def shuffled():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                while buf:
                    yield buf.pop()
        random.shuffle(buf)
        while buf:
            yield buf.pop()

    return shuffled


def chain(*readers):
    """decorator.py:86 — concatenate readers."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, **kwargs):
    """decorator.py:118 — zip readers, yielding flattened tuples."""
    check_alignment = kwargs.get("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for vals in zip(*rs):
                yield sum((make_tuple(v) for v in vals), ())
        else:
            for vals in itertools.zip_longest(*rs):
                yield sum((make_tuple(v) for v in vals if v is not None), ())

    return reader


def buffered(reader, size: int):
    """decorator.py:165 — background-thread prefetch into a bounded queue."""

    class _End:
        pass

    def buffered_reader():
        q: _queue.Queue = _queue.Queue(maxsize=size)

        def produce():
            try:
                for s in reader():
                    q.put(s)
            finally:
                q.put(_End)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is _End:
                break
            yield s

    return buffered_reader


def firstn(reader, n: int):
    """decorator.py:208 — truncate to the first n samples."""

    def reader_n():
        for i, s in enumerate(reader()):
            if i >= n:
                break
            yield s

    return reader_n


def xmap_readers(mapper: Callable, reader, process_num: int, buffer_size: int,
                 order: bool = False):
    """decorator.py:236 — parallel map over samples with worker threads."""

    def xreader():
        in_q: _queue.Queue = _queue.Queue(buffer_size)
        out_q: _queue.Queue = _queue.Queue(buffer_size)
        end = object()

        def feed():
            for i, s in enumerate(reader()):
                in_q.put((i, s))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    return
                i, s = item
                out_q.put((i, mapper(s)))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        finished = 0
        if order:
            pending = {}
            next_idx = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, s = item
                pending[i] = s
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]

    return xreader


def cache(reader):
    """Materialize once, replay from memory thereafter."""
    all_data: List = []
    filled = [False]

    def cached():
        if not filled[0]:
            for s in reader():
                all_data.append(s)
                yield s
            filled[0] = True
        else:
            yield from all_data

    return cached


def batch(reader, batch_size: int, drop_last: bool = False):
    """≙ python/paddle/batch.py — group samples into lists."""

    def batched():
        b = []
        for s in reader():
            b.append(s)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    return batched
