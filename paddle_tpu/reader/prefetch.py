"""Double-buffered host→device prefetch — a two-stage pipeline.

≙ reference double_buffer (python/paddle/fluid/layers/io.py:556) +
create_double_buffer_reader_op.cc: background stages that prepare the
NEXT batches while the CURRENT one computes. Two decoupled stages, each
its own thread + bounded queue:

  reader/decode  ->  q_host  ->  device_put  ->  q_dev  ->  consumer

so batch N+2's host-side decode overlaps batch N+1's host→device upload
overlaps batch N's device compute. On a rig where upload is the
bottleneck (BENCH r05: real-data 245 img/s vs 2637 fake over a ~15 MB/s
tunnel) the single-thread form serialized decode behind upload inside
one worker; splitting them keeps the decode CPU busy through the whole
upload window. jax.device_put itself is asynchronous, so the upload
stage mostly pays host-side staging — but staging is exactly what must
not sit between the reader and the consumer.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Optional

__all__ = ["double_buffer", "DeviceFeeder", "bounded_put"]

_STOP = object()


def bounded_put(q: "queue.Queue", item, stop: "threading.Event",
                timeout: float = 0.1) -> bool:
    """Bounded put that gives up when `stop` is set — the one stop-aware
    queue-handoff primitive shared by every pipeline stage thread here
    and in data/pipeline.py. Without the stop check, an abandoned
    consumer (exception/break in the train loop) would pin producer
    threads, their file handles, and queued device batches forever."""
    while not stop.is_set():
        try:
            q.put(item, timeout=timeout)
            return True
        except queue.Full:
            continue
    return False


class _NullSpan:
    """No-op timing span for the uninstrumented (default) path."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_null_span = _NullSpan()


def double_buffer(reader: Callable, place=None, capacity: int = 2,
                  retry_policy=None, transform=None, instrument=None,
                  cursor0: int = 0):
    """Wrap a feed-dict reader so device uploads overlap compute.

    reader() yields dicts of numpy arrays (or anything jax.device_put
    accepts). A decode thread stays `capacity` batches ahead of an
    upload thread, which stays `capacity` batches ahead of the consumer;
    exceptions from either stage propagate to the consumer in order.
    ≙ layers/io.py:556 double_buffer.

    retry_policy (resilience.RetryPolicy): bound restarts of a flaky
    reader INSIDE the decode thread — the underlying reader is re-invoked
    and fast-forwarded past delivered batches, so the consumer never sees
    a duplicate; exhaustion propagates the original error as before.
    (The Trainer installs its own wrapper upstream — don't pass a policy
    there too, or each error spends two retry budgets. Stacking is now
    DETECTED: a reader already carrying an armed resilient wrapper is
    not re-wrapped — one warning, one budget; see docs/resilience.md.)

    transform(batch, idx): applied in the upload thread AFTER device_put
    (idx = 0-based batch index of this iteration) — the data pipeline's
    device-side augmentation hook: the traced call dispatches off the
    consumer's critical path and its execution overlaps compute.

    instrument: a data.metrics.PipelineMetrics (duck-typed: span()) —
    the upload/augment stages report their busy time through it.
    cursor0 offsets the cursor= attribute their emitted trace spans
    carry, so after a pipeline resume (iter_from(n)) the upload span of
    batch n agrees with its decode/encode spans upstream.
    """
    import jax
    if retry_policy is not None:
        if getattr(reader, "_pt_resilient", False):
            # the double-retry-budget footgun (docs/resilience.md): this
            # reader is ALREADY an armed resilient wrapper — wrapping it
            # again would make every reader error spend two budgets
            # (retries_outer x retries_inner restarts). Dedupe to the
            # existing layer and say so, once, loudly.
            import warnings
            warnings.warn(
                "double_buffer(retry_policy=...) received a reader that "
                "already carries an armed resilient_reader wrapper "
                "(e.g. Trainer.train(reader_retry=...)): ignoring the "
                "double_buffer policy — stacked wrappers would multiply "
                "retry budgets. Pick one layer (docs/resilience.md).",
                stacklevel=2)
        else:
            from ..resilience.retry import resilient_reader
            reader = resilient_reader(reader, policy=retry_policy)

    def buffered():
        q_host: "queue.Queue" = queue.Queue(maxsize=capacity)
        q_dev: "queue.Queue" = queue.Queue(maxsize=capacity)
        stop = threading.Event()
        err = []

        def put(q, item) -> bool:
            return bounded_put(q, item, stop)

        def get(q):
            """Bounded get for the MIDDLE stage (the consumer's own get
            can block hard — it is the one who sets stop)."""
            while not stop.is_set():
                try:
                    return q.get(timeout=0.1)
                except queue.Empty:
                    continue
            return _STOP

        def decode_worker():
            """Stage 1: pull (and thereby decode) reader batches."""
            try:
                for batch in reader():
                    if stop.is_set():
                        return
                    if not put(q_host, batch):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err.append(e)
            finally:
                put(q_host, _STOP)

        def upload_worker():
            """Stage 2: stage batches onto the device (then run the
            optional transform — device-side augmentation — on the
            uploaded batch). A single thread, so batch order is
            preserved end to end."""
            idx = 0
            try:
                while True:
                    item = get(q_host)
                    if item is _STOP:
                        return
                    span = (instrument.span("upload",
                                            cursor=cursor0 + idx)
                            if instrument else _null_span)
                    with span:
                        if isinstance(item, dict):
                            item = {k: jax.device_put(v)
                                    for k, v in item.items()}
                        else:
                            item = jax.device_put(item)
                    if transform is not None:
                        span = (instrument.span("augment",
                                                cursor=cursor0 + idx)
                                if instrument else _null_span)
                        with span:
                            item = transform(item, idx)
                    idx += 1
                    if not put(q_dev, item):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err.append(e)
            finally:
                put(q_dev, _STOP)

        td = threading.Thread(target=decode_worker, daemon=True)
        tu = threading.Thread(target=upload_worker, daemon=True)
        td.start()
        tu.start()
        try:
            while True:
                item = q_dev.get()
                if item is _STOP:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()  # unblock + terminate both workers on early exit

    #: stacking detection (docs/resilience.md double-retry footgun):
    #: Trainer.train(reader_retry=...) checks this mark so a policy baked
    #: in here is never silently multiplied by a trainer-level budget
    buffered._pt_retry_policy = retry_policy
    return buffered


class DeviceFeeder:
    """DataFeeder + double_buffer in one: converts raw reader rows with a
    DataFeeder and keeps the uploads ahead of compute."""

    def __init__(self, feeder, reader: Callable, capacity: int = 2,
                 retry_policy=None):
        self._feeder = feeder
        self._reader = reader
        self._capacity = capacity
        self._retry_policy = retry_policy

    def __iter__(self):
        def feed_reader():
            for data in self._reader():
                # dict batches are already feed-shaped (pre-batched readers,
                # e.g. RecordIO -> native batcher); rows go through the feeder
                yield data if isinstance(data, dict) else self._feeder.feed(data)

        yield from double_buffer(feed_reader, capacity=self._capacity,
                                 retry_policy=self._retry_policy)()
