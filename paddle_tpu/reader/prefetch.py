"""Double-buffered host→device prefetch.

≙ reference double_buffer (python/paddle/fluid/layers/io.py:556) +
create_double_buffer_reader_op.cc: a background stage that uploads the
NEXT batch to the device while the CURRENT one computes, hiding
host→device transfer latency. On the JAX runtime the upload is
jax.device_put; a worker thread keeps `capacity` batches in flight
(device transfers are async, so the thread only pays host-side staging).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Optional

__all__ = ["double_buffer", "DeviceFeeder"]

_STOP = object()


def double_buffer(reader: Callable, place=None, capacity: int = 2,
                  retry_policy=None):
    """Wrap a feed-dict reader so device uploads overlap compute.

    reader() yields dicts of numpy arrays (or anything jax.device_put
    accepts). A worker thread stays `capacity` batches ahead; exceptions
    propagate to the consumer. ≙ layers/io.py:556 double_buffer.

    retry_policy (resilience.RetryPolicy): bound restarts of a flaky
    reader INSIDE the worker thread — the underlying reader is re-invoked
    and fast-forwarded past delivered batches, so the consumer never sees
    a duplicate; exhaustion propagates the original error as before.
    (The Trainer installs its own wrapper upstream — don't pass a policy
    there too, or each error spends two retry budgets.)
    """
    import jax
    if retry_policy is not None:
        from ..resilience.retry import resilient_reader
        reader = resilient_reader(reader, policy=retry_policy)

    def buffered():
        q: "queue.Queue" = queue.Queue(maxsize=capacity)
        stop = threading.Event()
        err = []

        def put(item) -> bool:
            """Bounded put that gives up when the consumer went away —
            otherwise an abandoned epoch (exception/break in the train
            loop) would pin this thread, the reader's file handles, and
            `capacity` device batches forever."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker():
            try:
                for batch in reader():
                    if stop.is_set():
                        return
                    if isinstance(batch, dict):
                        batch = {k: jax.device_put(v)
                                 for k, v in batch.items()}
                    else:
                        batch = jax.device_put(batch)
                    if not put(batch):
                        return
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err.append(e)
            finally:
                put(_STOP)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    if err:
                        raise err[0]
                    return
                yield item
        finally:
            stop.set()  # unblock + terminate the worker on early exit

    return buffered


class DeviceFeeder:
    """DataFeeder + double_buffer in one: converts raw reader rows with a
    DataFeeder and keeps the uploads ahead of compute."""

    def __init__(self, feeder, reader: Callable, capacity: int = 2,
                 retry_policy=None):
        self._feeder = feeder
        self._reader = reader
        self._capacity = capacity
        self._retry_policy = retry_policy

    def __iter__(self):
        def feed_reader():
            for data in self._reader():
                # dict batches are already feed-shaped (pre-batched readers,
                # e.g. RecordIO -> native batcher); rows go through the feeder
                yield data if isinstance(data, dict) else self._feeder.feed(data)

        yield from double_buffer(feed_reader, capacity=self._capacity,
                                 retry_policy=self._retry_policy)()
