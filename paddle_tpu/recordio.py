"""RecordIO reader/writer — native-backed with a pure-Python fallback.

≙ reference paddle/fluid/recordio/ + python recordio_writer.py
(python/paddle/fluid/recordio_writer.py). Format documented in
paddle_tpu/native/recordio.cpp; both implementations produce and consume
the identical byte layout (tested against each other).
"""

from __future__ import annotations

import ctypes
import struct
import zlib

import numpy as np
from typing import Iterator, Optional

from .native import recordio_lib

_FILE_MAGIC = b"PTRIO1\0\0"
_CHUNK_MAGIC = b"CHNK"

NO_COMPRESS, ZLIB_COMPRESS = 0, 1


class _PyWriter:
    def __init__(self, path: str, compressor: int, chunk_bytes: int):
        self._f = open(path, "wb")
        self._f.write(_FILE_MAGIC)
        self._compressor = compressor
        self._chunk_bytes = chunk_bytes
        self._buf = bytearray()
        self._n = 0

    def write(self, record: bytes):
        self._buf += struct.pack("<I", len(record)) + record
        self._n += 1
        if len(self._buf) >= self._chunk_bytes:
            self._flush()

    def _flush(self):
        if not self._n:
            return
        payload = bytes(self._buf)
        out = zlib.compress(payload, 1) if self._compressor == ZLIB_COMPRESS \
            else payload
        self._f.write(_CHUNK_MAGIC)
        self._f.write(struct.pack("<IIQQI", self._n, self._compressor,
                                  len(out), len(payload),
                                  zlib.crc32(out) & 0xFFFFFFFF))
        self._f.write(out)
        self._buf = bytearray()
        self._n = 0

    def close(self):
        self._flush()
        self._f.close()


def _py_scan(path: str) -> Iterator[bytes]:
    with open(path, "rb") as f:
        if f.read(8) != _FILE_MAGIC:
            raise IOError(f"{path}: not a recordio file")
        while True:
            magic = f.read(4)
            if not magic:
                return
            if magic != _CHUNK_MAGIC:
                raise IOError(f"{path}: bad chunk magic")
            hdr = f.read(28)
            if len(hdr) != 28:
                raise IOError(f"{path}: truncated chunk header")
            n, comp, clen, rlen, crc = struct.unpack("<IIQQI", hdr)
            raw = f.read(clen)
            if len(raw) != clen:
                raise IOError(f"{path}: truncated chunk")
            if zlib.crc32(raw) & 0xFFFFFFFF != crc:
                raise IOError(f"{path}: crc mismatch")
            payload = zlib.decompress(raw) if comp == ZLIB_COMPRESS else raw
            if len(payload) != rlen:
                raise IOError(f"{path}: bad raw length")
            pos = 0
            for _ in range(n):
                (l,) = struct.unpack_from("<I", payload, pos)
                pos += 4
                yield payload[pos:pos + l]
                pos += l


class Writer:
    """with Writer(path) as w: w.write(b"...")  — chunks auto-flush."""

    def __init__(self, path: str, compressor: int = ZLIB_COMPRESS,
                 chunk_bytes: int = 1 << 20, force_python: bool = False):
        lib = None if force_python else recordio_lib()
        self._lib = lib
        if lib is not None:
            self._h = lib.rio_writer_open(path.encode(), compressor,
                                          chunk_bytes)
            if not self._h:
                raise IOError(f"cannot open {path} for writing")
        else:
            self._py = _PyWriter(path, compressor, chunk_bytes)

    def write(self, record: bytes):
        if self._lib is not None:
            if self._lib.rio_writer_write(self._h, record, len(record)) != 0:
                raise IOError("recordio write failed")
        else:
            self._py.write(record)

    def close(self):
        if self._lib is not None:
            if self._h is not None:
                if self._lib.rio_writer_close(self._h) != 0:
                    raise IOError("recordio close/flush failed")
                self._h = None
        else:
            self._py.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def scan(path: str, force_python: bool = False) -> Iterator[bytes]:
    """Yield records; raises IOError on corruption (crc/magic)."""
    lib = None if force_python else recordio_lib()
    if lib is None:
        yield from _py_scan(path)
        return
    h = lib.rio_scanner_open(path.encode())
    if not h:
        raise IOError(f"{path}: not a recordio file")
    try:
        ln = ctypes.c_long()
        while True:
            ptr = lib.rio_scanner_next(h, ctypes.byref(ln))
            if not ptr:
                if ln.value == -1:
                    raise IOError(
                        f"{path}: {lib.rio_scanner_error(h).decode()}")
                return
            yield ctypes.string_at(ptr, ln.value)
    finally:
        lib.rio_scanner_close(h)


def reader_creator(path: str):
    """Reader-protocol adapter (≙ open_recordio_file, layers/io.py:295)."""
    def reader():
        return scan(path)
    return reader


def _sample_to_bytes(sample) -> bytes:
    """One training sample (tuple/list of arrays-or-scalars, or a single
    array) -> npz bytes. A `__tuple__` marker records the container kind
    so 1-tuples round-trip as 1-tuples. ≙ the reference's DataFeeder
    serialization inside convert_reader_to_recordio_file
    (recordio_writer.py)."""
    import io as _io
    buf = _io.BytesIO()
    is_tuple = isinstance(sample, (tuple, list))
    arrs = sample if is_tuple else (sample,)
    np.savez(buf, *[np.asarray(a) for a in arrs],
             __tuple__=np.bool_(is_tuple))
    return buf.getvalue()


def _sample_from_bytes(raw: bytes):
    import io as _io
    with np.load(_io.BytesIO(raw), allow_pickle=False) as data:
        arrs = [data[k] for k in sorted(
            (n for n in data.files if n.startswith("arr_")),
            key=lambda n: int(n.split("_")[1]))]
        is_tuple = bool(data["__tuple__"])
    return tuple(arrs) if is_tuple else arrs[0]


def convert_reader_to_recordio_file(path: str, reader,
                                    compressor: int = ZLIB_COMPRESS,
                                    force_python: bool = False) -> int:
    """≙ fluid.recordio_writer.convert_reader_to_recordio_file: drain a
    sample reader into a RecordIO file; returns the record count."""
    n = 0
    w = Writer(path, compressor=compressor, force_python=force_python)
    try:
        for sample in reader():
            w.write(_sample_to_bytes(sample))
            n += 1
    finally:
        w.close()
    return n


def sample_reader_creator(path: str):
    """Reader over a file written by convert_reader_to_recordio_file:
    yields the original sample tuples (≙ open_recordio_file +
    DataFeeder deserialization)."""
    def reader():
        for raw in scan(path):
            yield _sample_from_bytes(raw)
    return reader
