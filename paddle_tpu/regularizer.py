"""Weight-decay regularizers appended as gradient ops.

≙ reference python/paddle/fluid/regularizer.py: L1/L2 decay terms are
appended to each parameter's gradient before the optimizer op consumes it.
"""

from __future__ import annotations

from .core.program import default_main_program


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._regularization_coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        decay = block.create_var(grad.name + "@L2DECAY", shape=param.shape,
                                 dtype=param.dtype)
        decay.stop_gradient = True
        block.append_op("scale", {"X": param}, {"Out": decay},
                        {"scale": self._regularization_coeff})
        block.append_op("elementwise_add", {"X": grad, "Y": decay},
                        {"Out": grad})
        return grad


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff: float = 0.0):
        self._regularization_coeff = regularization_coeff

    def append_regularization_op(self, param, grad, block):
        sign = block.create_var(grad.name + "@L1SIGN", shape=param.shape,
                                dtype=param.dtype)
        sign.stop_gradient = True
        decay = block.create_var(grad.name + "@L1DECAY", shape=param.shape,
                                 dtype=param.dtype)
        decay.stop_gradient = True
        block.append_op("sign", {"X": param}, {"Out": sign})
        block.append_op("scale", {"X": sign}, {"Out": decay},
                        {"scale": self._regularization_coeff})
        block.append_op("elementwise_add", {"X": grad, "Y": decay},
                        {"Out": grad})
        return grad


def append_regularization_ops(parameters_and_grads, regularization=None):
    """Per-param regularizer (ParamAttr) overrides the optimizer-level one
    (regularizer.py append_regularization_ops)."""
    params_and_grads = []
    block = default_main_program().global_block
    for param, grad in parameters_and_grads:
        regularization_term = None
        reg = getattr(param, "regularizer", None) or regularization
        if grad is None or reg is None:
            params_and_grads.append((param, grad))
            continue
        reg.append_regularization_op(param, grad, block)
        params_and_grads.append((param, grad))
    return params_and_grads


# fluid-compatible aliases
L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
