"""Fault tolerance: injection, retry, and verified-checkpoint primitives.

The reference pserver treats checkpoint-and-recover as a first-class
server duty (go/pserver/service.go:346); on preemptible TPU slices the
*trainer* carries that duty, and a recovery path that only runs on real
failures is a recovery path that has never run. This package makes the
failure side drivable (faults.py: a deterministic, seeded injector behind
the PT_FAULT_INJECT knob), the retry side reusable (retry.py: bounded
exponential backoff + the reader-restart wrapper), and the persistence
side provable (manifest.py: per-file size+crc32 manifests that
save_checkpoint commits *before* the _SUCCESS marker, so a torn or
bit-rotten serial is detected and quarantined at load instead of
restoring garbage). The numerics side lives in guard.py (in-graph
step-health flag + guarded weight update + the PT_GUARD recovery
policies) and watchdog.py (PT_STEP_DEADLINE_S bound on a hung device
step). See docs/resilience.md.
"""

from .faults import (FaultInjected, FaultPlan, active_plan, crash_point,
                     fire, reset)
from .retry import RetryPolicy, resilient_reader, retry_call
from . import manifest
from . import guard
from . import watchdog
from .guard import GuardConfigError, StepAnomalyError
from .watchdog import StepHungError
from . import elastic
from .elastic import (ElasticMetrics, ElasticSupervisor,
                      ReshardMemoryError, ReshardError, reshard_state)
from . import orchestrator
from .orchestrator import (OrchMetrics, Orchestrator, OrchestratorError,
                           WorkerContext, WorkerSpec, peer_worker)
from . import streaming
from .streaming import ChunkCorruptError, stream_reshard

__all__ = [
    "FaultInjected", "FaultPlan", "active_plan", "crash_point", "fire",
    "reset", "RetryPolicy", "resilient_reader", "retry_call", "manifest",
    "guard", "watchdog", "GuardConfigError", "StepAnomalyError",
    "StepHungError", "elastic", "ElasticSupervisor", "ElasticMetrics",
    "ReshardError", "ReshardMemoryError", "reshard_state",
    "orchestrator", "Orchestrator", "OrchestratorError", "OrchMetrics",
    "WorkerContext", "WorkerSpec", "peer_worker",
    "streaming", "ChunkCorruptError", "stream_reshard",
]
