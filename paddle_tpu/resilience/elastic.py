"""Elastic training: survive preemption and topology shrink.

A checkpoint written under one PlacementPlan used to be restorable only
onto the *same* mesh — lose a host on a preemptible slice and the run
was dead until the exact topology returned. This module closes the loop
the planner opened: checkpoints are plan-stamped (io.save_checkpoint
merges the plan's mesh axes + per-var specs + calibration version into
the manifest, bound by the _SUCCESS marker like every other byte), and
the ``ElasticSupervisor`` wraps a Trainer factory in a bounded restart
loop that, on every crash/preemption/topology change:

  1. restores the latest *verified* checkpoint (the Trainer's own
     auto-resume — manifest-verified selection, corrupt serials
     quarantined),
  2. invokes the planner for the topology that actually survives
     (``PT_ELASTIC_TOPOLOGY`` override, else the launch topology shrunk
     by the losses the fault sites reported: ``mesh_shrink`` halves it,
     ``device_loss`` drops one chip),
  3. reshards the restored state from the checkpoint's recorded plan
     onto the new winning plan — ``reshard_state`` gathers to full host
     arrays, structurally validates every dim of the new layout
     (dp/tp/sp re-splits including ZeRO dp-sharded accumulators), and
     the fresh ``ParallelExecutor(plan=...)`` rescatters on dispatch,
  4. resumes at the exact recorded step with the data-pipeline cursor
     intact (trainer_args + reader fast-forward) — degraded but alive
     on fewer chips.

The restart budget reuses ``retry.RetryPolicy`` (bounded attempts,
exponential backoff + seeded jitter, injectable sleep/clock), and
exhaustion re-raises the ORIGINAL error. Every leg is observable:
``pt_elastic_*`` metrics (restarts, reshards, downtime seconds,
current/target chips) on the unified registry, ``elastic:restart``
trace spans on the obs plane. ``tools/reshard.py`` is the offline CLI
over the same ``reshard_state``. Chaos-driven end to end: the
``device_loss`` / ``mesh_shrink`` sites fire deterministically at
trainer step boundaries under ``PT_FAULT_INJECT``. See
docs/resilience.md ("Elastic training").
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .faults import FaultInjected
from .retry import RetryPolicy

__all__ = ["ElasticSupervisor", "ElasticMetrics", "ReshardError",
           "ReshardMemoryError", "reshard_state",
           "validate_reshard_shapes", "current_topology",
           "DEFAULT_RESTARTS", "DEFAULT_BACKOFF_S"]

#: restart budget default (PT_ELASTIC_RESTARTS)
DEFAULT_RESTARTS = 3
#: base backoff default in seconds (PT_ELASTIC_BACKOFF_S)
DEFAULT_BACKOFF_S = 0.05


class ReshardError(RuntimeError):
    """The restored state cannot be laid out under the target plan
    (a dim not divisible by its new mesh-axis factor, a var the plan
    shards that the state lacks, a cross-process array this in-process
    gather cannot assemble). Structural — retrying cannot help, which
    is why it is not an OSError: retry layers must not re-run it."""


class ReshardMemoryError(ReshardError):
    """The gather-based reshard would materialize more host bytes than
    PT_RESHARD_MAX_HOST_GB allows. Raised from the up-front estimate —
    before any array is gathered — so a small survivor host refuses
    instead of silently OOMing mid-gather. The streaming path
    (``tools/reshard.py --stream``, resilience/streaming.py) moves the
    same state chunk-by-chunk under PT_RESHARD_CHUNK_MB."""


# ---------------------------------------------------------------------------
# resharding: gather -> validate -> (executor rescatters on dispatch)
# ---------------------------------------------------------------------------

def _dim_factor(entry, mesh: Dict[str, int]) -> int:
    """The shard factor one per-dim spec entry imposes: an axis name,
    a list of axis names (multi-axis dim), or None (replicated)."""
    if entry is None:
        return 1
    axes = entry if isinstance(entry, (list, tuple)) else (entry,)
    f = 1
    for a in axes:
        f *= int(mesh.get(a, 1))
    return f


def validate_reshard_shapes(shapes: Dict[str, tuple],
                            to_plan: dict) -> None:
    """Structural half of the reshard contract, shared by the gather
    path and the streaming path (which never holds full arrays, so it
    validates from npy-header shapes): every dim the target plan shards
    must divide by the product of its mesh-axis sizes. Raises
    ReshardError listing every offending (var, dim)."""
    mesh = {str(a): int(s)
            for a, s in (to_plan.get("mesh") or {}).items()}
    specs = to_plan.get("specs") or {}
    problems: List[str] = []
    for name, spec in specs.items():
        shape = shapes.get(name)
        if shape is None:
            # a plan var the state lacks: the executor's own missing-var
            # handling owns absence; resharding only validates presence
            continue
        for dim, entry in enumerate(spec):
            f = _dim_factor(entry, mesh)
            if f <= 1:
                continue
            size = int(shape[dim]) if dim < len(shape) else 1
            if size % f:
                problems.append(
                    f"{name}: dim {dim} of size {size} not divisible by "
                    f"its mesh factor {f} ({entry!r} under {mesh})")
    if problems:
        raise ReshardError(
            "state cannot be laid out under the target plan:\n  "
            + "\n  ".join(problems))


def gather_guardrail(total_bytes: int, origin: str = "reshard") -> None:
    """The PT_RESHARD_MAX_HOST_GB refusal, from an up-front estimate:
    today's alternative is the survivor host silently OOMing halfway
    through the gather. No-op when the knob is unset/0."""
    from ..flags import env_knob_float
    max_gb = env_knob_float("PT_RESHARD_MAX_HOST_GB", 0.0)
    if max_gb <= 0:
        return
    limit = int(max_gb * (1 << 30))
    if total_bytes > limit:
        raise ReshardMemoryError(
            f"{origin}: gathering full host arrays needs an estimated "
            f"{total_bytes} bytes, over the PT_RESHARD_MAX_HOST_GB="
            f"{max_gb:g} budget ({limit} bytes) — use the streaming "
            "path (tools/reshard.py --stream, sized by "
            "PT_RESHARD_CHUNK_MB) which bounds peak host memory by the "
            "chunk budget instead of the gathered state")


def reshard_state(state: Dict[str, "np.ndarray"],
                  from_plan: Optional[dict], to_plan: dict,
                  place: bool = False) -> Dict[str, np.ndarray]:
    """Re-lay out checkpointed/live state from `from_plan` onto
    `to_plan`: gather every value to a full host array, then validate
    that the target plan's per-var specs structurally fit the actual
    shapes (every sharded dim divisible by the product of its mesh-axis
    sizes — the ZeRO dp-sharded accumulators are ordinary specs here,
    because ``_annotate_defaults`` made the dp feed split and the
    zero accumulators explicit in the plan).

    Checkpoints hold FULL arrays per var (single-process saves; the
    multi-process shard pieces were reassembled by the loader), so the
    gather is exact and a round-trip A→B→A is bit-identical. The
    rescatter itself is the executor's job — ``ParallelExecutor
    (plan=to_plan)`` device_puts host arrays under the plan's
    NamedShardings on first dispatch — so this function returns host
    arrays; ``place=True`` additionally device_puts them eagerly under
    the target mesh (tools/reshard.py leaves it False: offline).

    Raises ReshardError on structural impossibility, listing every
    offending (var, dim). `from_plan` may be None (unstamped/legacy
    checkpoint — nothing to gather differently; validation still
    runs)."""
    specs = to_plan.get("specs") or {}
    est = 0
    for name, val in state.items():
        if val is None:
            continue
        if getattr(val, "is_fully_addressable", True) is False:
            raise ReshardError(
                f"{name!r} is a cross-process array — in-process "
                "resharding needs every shard addressable; gather the "
                "per-process checkpoint shard files into one directory "
                "and use tools/reshard.py offline instead")
        nbytes = getattr(val, "nbytes", None)
        if nbytes is not None:
            est += int(nbytes)
    gather_guardrail(est, origin="reshard_state")
    gathered: Dict[str, np.ndarray] = {}
    for name, val in state.items():
        if val is None:
            continue
        gathered[name] = np.asarray(val)  # host-sync: ok — the gather
    validate_reshard_shapes(
        {name: tuple(arr.shape) for name, arr in gathered.items()},
        to_plan)
    if place:
        import jax
        from jax.sharding import NamedSharding
        from ..parallel.mesh import mesh_from_plan, spec_for
        device_mesh = mesh_from_plan(to_plan)
        for name, arr in gathered.items():
            spec = specs.get(name)
            if spec is None:
                continue
            gathered[name] = jax.device_put(
                arr, NamedSharding(device_mesh, spec_for(spec,
                                                         device_mesh)))
    return gathered


# ---------------------------------------------------------------------------
# metrics provider (pt_elastic_*, REGISTRY section "elastic")
# ---------------------------------------------------------------------------

class ElasticMetrics:
    """One supervisor's counters. Thread-safe: the restart loop records
    while HTTP scrapes read."""

    def __init__(self, name: str = "elastic",
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.restarts = 0
            self.reshards = 0
            self.downtime_s = 0.0
            self.current_chips: Optional[int] = None
            self.target_chips: Optional[int] = None
            self.restarts_by_site: Dict[str, int] = {}

    def on_restart(self, site: Optional[str] = None) -> None:
        with self._lock:
            self.restarts += 1
            key = site or "error"
            self.restarts_by_site[key] = \
                self.restarts_by_site.get(key, 0) + 1

    def on_reshard(self) -> None:
        with self._lock:
            self.reshards += 1

    def add_downtime(self, seconds: float) -> None:
        with self._lock:
            self.downtime_s += max(0.0, float(seconds))

    def set_chips(self, current: Optional[int],
                  target: Optional[int]) -> None:
        with self._lock:
            if current is not None:
                self.current_chips = int(current)
            if target is not None:
                self.target_chips = int(target)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "restarts": self.restarts,
                "reshards": self.reshards,
                "downtime_s": round(self.downtime_s, 6),
                "current_chips": self.current_chips,
                "target_chips": self.target_chips,
                "restarts_by_site": dict(self.restarts_by_site),
            }


# ---------------------------------------------------------------------------
# topology detection
# ---------------------------------------------------------------------------

def current_topology(base=None):
    """The topology the next attempt should plan for: the
    ``PT_ELASTIC_TOPOLOGY`` override when set (the operator — or the
    resource manager's eviction hook — describing what actually
    survives, same grammar as PT_PLAN_TOPOLOGY), else `base`, else the
    planner's default. Read per restart, so a changed env between
    attempts is honored."""
    from ..parallel.mesh import Topology
    raw = os.environ.get("PT_ELASTIC_TOPOLOGY", "").strip()
    if raw:
        return Topology.parse(raw)
    if base is not None:
        return base
    from ..analysis import planner
    return planner.default_topology()


# ---------------------------------------------------------------------------
# the supervisor
# ---------------------------------------------------------------------------

class ElasticSupervisor:
    """Run a Trainer to completion across crashes, preemptions, and
    topology changes.

    `make_trainer` is a zero-arg factory returning a FRESH Trainer
    (new programs, new scope, a CheckpointConfig pointing at the run's
    checkpoint dir). Construction already performs the verified
    auto-resume; the supervisor then plans for the surviving topology
    (``analysis.planner.plan_for_devices`` — the search space needs
    nothing new, every divisor device count is already enumerated),
    validates the restored state against the winning plan
    (``reshard_state``), assigns it (``trainer.plan`` — the parallel
    executor rescatters, checkpoints stamp the NEW plan), and trains.

    On an exception the loop classifies it (``FaultInjected.site`` —
    ``mesh_shrink`` halves the tracked chip count, ``device_loss``
    drops one; anything else restarts on the same topology), backs off
    per the RetryPolicy (PT_ELASTIC_RESTARTS attempts,
    PT_ELASTIC_BACKOFF_S base, seeded jitter), and goes again.
    Exhaustion re-raises the ORIGINAL error. ``planning=False`` keeps
    the restart/restore loop but never re-plans (single-chip runs).

    Not multi-host: a multi-process slice restarts whole processes
    through the cluster scheduler; this supervisor is the single-
    process (and emulated-mesh) recovery path the chaos harness can
    drive deterministically."""

    def __init__(self, make_trainer: Callable[[], "object"],
                 batch: int = 1, base_topology=None,
                 policy: Optional[RetryPolicy] = None,
                 planning: bool = True, calibration=None,
                 metrics: Optional[ElasticMetrics] = None,
                 clock: Callable[[], float] = time.monotonic,
                 plan_kwargs: Optional[dict] = None):
        from ..flags import env_knob_float, env_knob_int
        self.make_trainer = make_trainer
        self.batch = int(batch)
        self.base_topology = base_topology
        self.planning = bool(planning)
        self.calibration = calibration
        self.plan_kwargs = dict(plan_kwargs or {})
        self._clock = clock
        if policy is None:
            policy = RetryPolicy(
                retries=env_knob_int("PT_ELASTIC_RESTARTS",
                                     DEFAULT_RESTARTS),
                base_delay=env_knob_float("PT_ELASTIC_BACKOFF_S",
                                          DEFAULT_BACKOFF_S),
                max_delay=30.0)
        self.policy = policy
        self.metrics = metrics or ElasticMetrics()
        from ..obs.metrics import REGISTRY
        REGISTRY.register("elastic", self.metrics.name, self.metrics)
        #: chips the supervisor believes survive (None until first run)
        self.current_chips: Optional[int] = None
        self.trainer = None
        self.restarts = 0

    # -- one attempt's setup: restore + re-plan + reshard-validate ---------
    def _site_of(self, exc: BaseException) -> Optional[str]:
        e: Optional[BaseException] = exc
        while e is not None:
            if isinstance(e, FaultInjected):
                return e.site
            e = e.__cause__ or e.__context__
        return None

    def _shrink_for(self, site: Optional[str]) -> None:
        if self.current_chips is None:
            return
        if site == "mesh_shrink":
            self.current_chips = max(1, self.current_chips // 2)
        elif site == "device_loss":
            self.current_chips = max(1, self.current_chips - 1)

    def _checkpoint_state(self, trainer) -> Dict[str, np.ndarray]:
        """The restored persistable state, by name, from the trainer's
        scope (params + optimizer accumulators — what checkpoints
        hold)."""
        out: Dict[str, np.ndarray] = {}
        for v in trainer.train_program.list_vars():
            if not getattr(v, "persistable", False):
                continue
            val = trainer.scope.find_var(v.name)
            if val is not None:
                out[v.name] = val
        return out

    def _prepare(self, restart_n: int, site: Optional[str]):
        """Build the attempt's trainer: restore, re-plan for the
        surviving topology, validate the reshard. Returns the trainer,
        ready to train."""
        from .. import io as io_mod
        from ..obs import trace as obs_trace
        topo = current_topology(self.base_topology)
        if self.base_topology is None:
            self.base_topology = topo
        if self.current_chips is None or topo is not self.base_topology:
            # a PT_ELASTIC_TOPOLOGY override IS the surviving fabric —
            # it wins over the in-process loss accounting
            self.current_chips = topo.n_devices
        with obs_trace.span("elastic:restart", cat="elastic",
                            restart=restart_n, site=site or "",
                            chips=self.current_chips):
            trainer = self.make_trainer()
            plan = None
            if self.planning:
                from ..analysis import planner
                art = planner.plan_for_devices(
                    trainer.train_program,
                    n_devices=self.current_chips,
                    base_topology=self.base_topology,
                    batch=self.batch, calibration=self.calibration,
                    **self.plan_kwargs)
                plan = art.top
                cfg = trainer.checkpoint_cfg
                stamp = (io_mod.read_plan_stamp(cfg.checkpoint_dir)
                         if cfg else None)
                if stamp and io_mod.check_plan_stamp(stamp, plan):
                    # the restore crossed plans: validate the new
                    # layout against the actual restored shapes, then
                    # count the reshard (the executor rescatters on
                    # first dispatch)
                    reshard_state(self._checkpoint_state(trainer),
                                  from_plan=stamp, to_plan=plan)
                    self.metrics.on_reshard()
                    obs_trace.instant(
                        "elastic_reshard", cat="elastic",
                        from_mesh=str(stamp.get("mesh")),
                        to_mesh=str(plan.get("mesh")))
                trainer.plan = plan
                trainer.parallel = True
        self.metrics.set_chips(self.current_chips,
                               self.base_topology.n_devices)
        return trainer

    def run(self, *args, **train_kwargs):
        """Train to completion under the restart budget; returns the
        (last) Trainer on success. Positional/keyword args are passed
        through to ``Trainer.train`` on every attempt — the reader must
        be re-invocable (any pipeline/callable reader is)."""
        delays = self.policy.delays()
        restart_n = 0
        site: Optional[str] = None
        down_since: Optional[float] = None
        while True:
            trainer = self._prepare(restart_n, site)
            self.trainer = trainer
            if down_since is not None:
                self.metrics.add_downtime(self._clock() - down_since)
                down_since = None
            try:
                trainer.train(*args, **train_kwargs)
                return trainer
            except Exception as e:  # noqa: BLE001 — policy filters below
                down_since = self._clock()
                site = self._site_of(e)
                delay = next(delays, None)
                if delay is None or not self.policy.should_retry(e):
                    raise
                self._shrink_for(site)
                self.restarts = restart_n = restart_n + 1
                self.metrics.on_restart(site)
                from ..obs import trace as obs_trace
                obs_trace.instant("elastic_crash", cat="elastic",
                                  site=site or type(e).__name__,
                                  restart=restart_n,
                                  chips=self.current_chips)
                self.policy.sleep(delay)
