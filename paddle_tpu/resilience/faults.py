"""Deterministic, seeded fault injection behind the PT_FAULT_INJECT knob.

Chaos runs and the resilience test suite need to crash the system at a
*named* point — mid-save, at the checkpoint commit, inside the reader, at
a trainer step boundary — without monkeypatching internals. Each such
point in the codebase calls ``crash_point(site)`` (or ``fire(site)`` when
the fault is a side effect rather than an exception, e.g. truncating a
write); with no plan armed these calls are a dict lookup and an early
return.

Grammar (the whole plan lives in one env var so a chaos run is just a
prefix on the launch command)::

    PT_FAULT_INJECT="io_write_truncate@3,step_crash@7,reader_raise@2:seed=0"

    plan    := spec (',' spec)* [':seed=' INT]
    spec    := site '@' trigger
    trigger := INT        one-shot: fire on the Nth hit of the site (1-based)
             | '*'        fire on every hit
             | 'p' FLOAT  fire each hit with probability FLOAT (seeded)

The same site may appear multiple times (``reader_raise@2,reader_raise@5``
fires on hits 2 and 5). Probabilistic triggers draw from a per-site
``random.Random`` seeded from ``seed`` + the site name, so a plan replays
identically across runs — determinism is the whole point: a chaos failure
must be reproducible by re-running with the same plan string.

Sites (the registry below is closed on purpose: a typo in a plan is an
error, not a silently-never-firing spec):

    io_crash            _atomic_save, before any bytes are written
    io_write_truncate   _atomic_save: half the bytes reach the final name,
                        then the "process dies" (torn write + crash)
    commit_crash        save_checkpoint, after all data is on disk but
                        before the _SUCCESS marker
    reader_raise        per batch inside the resilient reader wrapper
                        (retry.resilient_reader — the trainer data path)
    step_crash          Trainer.train, at the top of each step
    nan_loss            in-graph (guard.py): the step's loss becomes NaN
                        — hit once per GUARDED dispatch
    nan_grad            in-graph (guard.py): every parameter gradient
                        becomes NaN — hit once per GUARDED dispatch
    step_hang           watchdog.py: the device step never settles — hit
                        only when PT_STEP_DEADLINE_S is armed (an
                        unwatched injected hang would hang the run)
    serve_dispatch      serving/batcher.py: per flushed batch inside the
                        micro-batcher's dispatcher loop — the batch's
                        requests fail with a typed RequestFailed and the
                        loop keeps serving
    device_loss         Trainer.train, at the top of each step: one chip
                        drops out of the mesh (preemptible-VM eviction) —
                        the elastic supervisor re-plans on one fewer chip
    mesh_shrink         Trainer.train, at the top of each step: the mesh
                        halves (a host is preempted) — the elastic
                        supervisor re-plans on the surviving topology
    worker_crash        orchestrator.peer_worker, per heartbeat: the
                        worker dies (dead handle -> evicted as a crash)
    heartbeat_loss      orchestrator.peer_worker, per heartbeat: the
                        worker goes silent but stays alive (hung
                        collective -> killed, evicted as heartbeat_loss)
"""

from __future__ import annotations

import os
import random
import re
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["SITES", "FaultInjected", "FaultPlan", "active_plan",
           "crash_point", "fire", "reset"]

#: site -> description; the parser rejects anything else
SITES: Dict[str, str] = {
    "io_crash": "crash in _atomic_save before writing",
    "io_write_truncate": "torn write: truncated bytes reach the final "
                         "name, then crash",
    "commit_crash": "crash after checkpoint data, before _SUCCESS",
    "reader_raise": "raise from the reader iteration (retried region)",
    "step_crash": "crash at a trainer step boundary",
    "nan_loss": "in-graph: the step's loss becomes NaN (guarded runs)",
    "nan_grad": "in-graph: every parameter gradient becomes NaN "
                "(guarded runs)",
    "step_hang": "the device step never settles (armed watchdog only)",
    "serve_dispatch": "crash inside the serving micro-batcher's "
                      "dispatcher loop, per flushed batch "
                      "(serving/batcher.py)",
    "router_dispatch": "replica crash at fleet-router dispatch, per "
                       "routed request (serving/fleet/router.py): the "
                       "router fails over to the next-best replica and "
                       "rebuilds the crashed one",
    "device_loss": "one chip drops out of the mesh at a trainer step "
                   "boundary (preemptible eviction); the elastic "
                   "supervisor re-plans on one fewer chip",
    "mesh_shrink": "the mesh halves at a trainer step boundary (host "
                   "preemption); the elastic supervisor re-plans on "
                   "the surviving topology",
    "worker_crash": "orchestrated worker dies at a heartbeat boundary "
                    "(resilience/orchestrator.py): the supervisor sees "
                    "a dead handle and evicts with cause worker_crash",
    "heartbeat_loss": "orchestrated worker stops renewing its lease but "
                      "stays alive — the hung-collective case "
                      "(resilience/orchestrator.py): the supervisor "
                      "kills it and evicts with cause heartbeat_loss",
    "spec_verify": "drafter crash mid-step at the speculative-decode "
                   "draft gathering point (serving/decode/scheduler.py "
                   "_gather_drafts): the scheduler falls back to plain "
                   "decode for that sequence's step — output stays "
                   "token-identical, the session survives",
}

ENV_VAR = "PT_FAULT_INJECT"


class FaultInjected(RuntimeError):
    """The injected failure. Deliberately a plain RuntimeError subclass:
    production code must treat it like any crash — anything that
    special-cases it would be testing a path real failures never take."""

    def __init__(self, site: str, hit: int):
        self.site = site
        self.hit = hit
        super().__init__(f"injected fault {site!r} (hit {hit})")


class _Trigger:
    __slots__ = ("kind", "at", "prob")

    def __init__(self, kind: str, at: int = 0, prob: float = 0.0):
        self.kind = kind      # "nth" | "every" | "prob"
        self.at = at
        self.prob = prob


class FaultPlan:
    """A parsed plan: per-site hit counters + triggers. Thread-safe —
    reader faults fire from prefetch worker threads."""

    def __init__(self, triggers: Dict[str, List[_Trigger]], seed: int = 0,
                 spec: str = ""):
        self.spec = spec
        self.seed = seed
        self._triggers = triggers
        self._hits: Dict[str, int] = {}
        self._rng: Dict[str, random.Random] = {}
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        seed = 0
        body = spec.strip()
        m = re.search(r":seed=(\d+)$", body)
        if m:
            seed = int(m.group(1))
            body = body[:m.start()]
        triggers: Dict[str, List[_Trigger]] = {}
        for part in filter(None, (p.strip() for p in body.split(","))):
            sm = re.fullmatch(r"([a-z_]+)@(\*|p[0-9.]+|\d+)", part)
            if not sm:
                raise ValueError(
                    f"{ENV_VAR}: malformed spec {part!r} (want "
                    "site@N | site@* | site@pFLOAT)")
            site, trig = sm.group(1), sm.group(2)
            if site not in SITES:
                raise ValueError(
                    f"{ENV_VAR}: unknown site {site!r} (known: "
                    f"{', '.join(sorted(SITES))})")
            if trig == "*":
                t = _Trigger("every")
            elif trig.startswith("p"):
                p = float(trig[1:])
                if not 0.0 <= p <= 1.0:
                    raise ValueError(
                        f"{ENV_VAR}: probability out of [0,1] in {part!r}")
                t = _Trigger("prob", prob=p)
            else:
                n = int(trig)
                if n < 1:
                    raise ValueError(
                        f"{ENV_VAR}: hit index is 1-based in {part!r}")
                t = _Trigger("nth", at=n)
            triggers.setdefault(site, []).append(t)
        return cls(triggers, seed=seed, spec=spec)

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits.get(site, 0)

    def fire(self, site: str) -> Optional[int]:
        """Record one hit of `site`; return the hit index if a trigger
        fires, else None."""
        if site not in SITES:
            raise KeyError(f"unregistered fault site {site!r}")
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            for t in self._triggers.get(site, ()):
                if t.kind == "every":
                    return hit
                if t.kind == "nth" and t.at == hit:
                    return hit
                if t.kind == "prob":
                    rng = self._rng.get(site)
                    if rng is None:
                        # string seeding is deterministic in python 3
                        # (sha512), independent of PYTHONHASHSEED
                        rng = self._rng[site] = random.Random(
                            f"{self.seed}:{site}")
                    if rng.random() < t.prob:
                        return hit
        return None


_EMPTY = FaultPlan({}, spec="")
_cache: Tuple[Optional[str], FaultPlan] = (None, _EMPTY)
_cache_lock = threading.Lock()


def active_plan() -> FaultPlan:
    """The plan for the current PT_FAULT_INJECT value. Parsed once per
    distinct env value; counters persist while the value is unchanged."""
    global _cache
    spec = os.environ.get(ENV_VAR)
    with _cache_lock:
        if spec == _cache[0]:
            return _cache[1]
        plan = _EMPTY if not spec else FaultPlan.parse(spec)
        _cache = (spec, plan)
        return plan


def reset() -> None:
    """Drop the cached plan (counters restart on next use). Tests."""
    global _cache
    with _cache_lock:
        _cache = (None, _EMPTY)


def fire(site: str) -> Optional[int]:
    """Hit `site`; return the hit index if the plan triggers, else None.
    For sites whose fault is a side effect (e.g. truncating a write)."""
    plan = active_plan()
    if not plan._triggers:
        return None
    return plan.fire(site)


def crash_point(site: str) -> None:
    """Hit `site`; raise FaultInjected when the plan triggers."""
    hit = fire(site)
    if hit is not None:
        raise FaultInjected(site, hit)
