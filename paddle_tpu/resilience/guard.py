"""In-graph training guardrails: step-health flag + guarded weight update.

PR 2 made crashes survivable and the async hot path (lazy fetches,
device-resident state) made the step loop free of host syncs — which also
means a single NaN/Inf batch poisons the weights *on device* and the
damage only surfaces (if ever) at a log step or checkpoint. The guard
closes that hole INSIDE the compiled step, so it composes with donation,
with `run_loop`'s device-side scan, and with whatever update sharding the
ParallelExecutor picks (cf. cross-replica weight-update sharding, arxiv
2004.13336: a host-side pre-check would see per-replica shards; an
in-graph scalar is global by construction):

1. **step-health flag** — ONE fused scalar per step::

       healthy = isfinite(loss) ∧ isfinite(‖grads‖₂) ∧ ‖grads‖₂ ≤ PT_GUARD_MAX_GNORM

   computed by a `step_health` op that `optimizer.minimize` appends when
   PT_GUARD is armed (or `instrument(program)` on demand). The executor
   appends it to the fetch list under ``lazy=True``, so detection
   piggybacks on the existing LazyFetch materialization — zero extra
   host syncs.

2. **guarded update** — the lowering rewrites the step's state output to
   ``new_state = where(healthy, updated_state, old_state)``
   (core/lowering.py). An anomalous batch is *skipped* for free: params,
   optimizer accumulators, bn statistics — every persistable — keep
   their pre-step value, and donation stays ON (unlike the
   FLAGS_check_nan_inf/checkify debug path, which must disable it).

3. **recovery policy** (PT_GUARD=skip|rollback|raise, consumed by the
   Trainer at log/checkpoint boundaries): `skip` relies on (2) and logs;
   `raise` raises StepAnomalyError after PT_GUARD_PATIENCE consecutive
   anomalies; `rollback` restores the newest *verified* checkpoint
   serial (PR 2 manifests) and resumes bit-exactly.

The norm is measured on the RAW backward gradients (the autodiff op's
`@GRAD` bindings, before clip/regularization rewrites) — a
clip_by_global_norm would otherwise mask the very explosions the guard
exists to catch — and is divided by the autodiff `loss_scale`, so AMP
loss scaling does not shift the PT_GUARD_MAX_GNORM threshold. Host-RAM
embedding tables apply their rows-grads host-side; the Trainer gates
those applies on the same health flag (trainer._apply_host_grads), which
costs nothing extra because that path already materializes per step.

Deterministic fault sites `nan_loss` / `nan_grad` (resilience/faults.py)
poison the step in-graph via a tiny int32 fault-code feed the executor
injects per dispatch, so every recovery path is provable under seeds.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Optional

import jax.numpy as jnp

__all__ = [
    "HEALTH_VAR", "FAULT_FEED", "HEALTH_OP",
    "GuardConfigError", "StepAnomalyError", "RollbackSignal",
    "policy", "patience", "max_gnorm", "fault_code", "fault_feed",
    "instrument", "maybe_instrument", "is_instrumented",
    "assert_instrumented",
]

#: reserved name of the in-graph health scalar (the `step_health` output)
HEALTH_VAR = "__step_health__"
#: reserved feed name of the per-step fault-injection code (int32:
#: 0 = none, 1 = nan_loss, 2 = nan_grad)
FAULT_FEED = "__guard_fault__"
HEALTH_OP = "step_health"

POLICY_ENV = "PT_GUARD"
PATIENCE_ENV = "PT_GUARD_PATIENCE"
MAX_GNORM_ENV = "PT_GUARD_MAX_GNORM"

POLICIES = ("skip", "rollback", "raise")
_OFF = ("", "0", "off", "none", "false")


class GuardConfigError(RuntimeError):
    """Malformed or inconsistent PT_GUARD* configuration."""


class StepAnomalyError(RuntimeError):
    """PT_GUARD_PATIENCE consecutive anomalous steps under PT_GUARD=raise
    (or an exhausted/unavailable rollback under PT_GUARD=rollback)."""


class RollbackSignal(Exception):
    """Internal control flow: the Trainer's health drain requests a
    rollback to the newest verified checkpoint. Never escapes
    Trainer.train — deliberately NOT a RuntimeError so generic error
    handlers don't swallow it."""

    def __init__(self, epoch: int, step: int, streak: int):
        self.epoch, self.step, self.streak = epoch, step, streak
        super().__init__(
            f"{streak} consecutive anomalous steps "
            f"(last: epoch {epoch} step {step})")


def policy() -> Optional[str]:
    """The armed recovery policy, or None when the guard is off."""
    raw = os.environ.get(POLICY_ENV, "").strip().lower()
    if raw in _OFF:
        return None
    if raw not in POLICIES:
        raise GuardConfigError(
            f"{POLICY_ENV}={raw!r}: unknown policy "
            f"(want {' | '.join(POLICIES)}, or unset/0 to disable)")
    return raw


def patience() -> int:
    """Consecutive anomalous steps before raise/rollback act (default 3)."""
    raw = os.environ.get(PATIENCE_ENV, "").strip()
    if not raw:
        return 3
    try:
        k = int(raw)
    except ValueError as e:
        raise GuardConfigError(f"{PATIENCE_ENV}={raw!r}: not an int") from e
    if k < 1:
        raise GuardConfigError(f"{PATIENCE_ENV} must be >= 1, got {k}")
    return k


def max_gnorm() -> float:
    """Global-grad-norm ceiling baked into the compiled health flag
    (default inf: only non-finiteness trips the guard). Read at trace
    time; the executor keys its compile cache on the value, so changing
    it mid-process recompiles rather than silently keeping the old
    threshold."""
    raw = os.environ.get(MAX_GNORM_ENV, "").strip()
    if not raw:
        return float("inf")
    try:
        g = float(raw)
    except ValueError as e:
        raise GuardConfigError(f"{MAX_GNORM_ENV}={raw!r}: not a float") from e
    if not g > 0:
        raise GuardConfigError(f"{MAX_GNORM_ENV} must be > 0, got {g}")
    return g


# -- fault-code feed (deterministic in-graph injection) ----------------------

def fault_code() -> int:
    """One draw of the in-graph fault sites for one step. BOTH sites are
    hit on every guarded dispatch (their hit counters advance in step
    lockstep, so `nan_loss@N` means "step N of this process"); nan_loss
    wins when both fire on the same step."""
    from . import faults
    code = 1 if faults.fire("nan_loss") is not None else 0
    if faults.fire("nan_grad") is not None and code == 0:
        code = 2
    return code


def fault_feed(n_steps: Optional[int] = None):
    """The int32 fault-code array fed as FAULT_FEED: a scalar for
    Executor.run (and fake-feed run_loop windows — one draw per window),
    or [n_steps] for per_step_feeds windows (one draw per step)."""
    if n_steps is None:
        return jnp.int32(fault_code())
    return jnp.asarray([fault_code() for _ in range(n_steps)], jnp.int32)


# -- program instrumentation -------------------------------------------------

def is_instrumented(program) -> bool:
    return any(op.type == HEALTH_OP for op in program.global_block.ops)


def assert_instrumented(program) -> None:
    if not is_instrumented(program):
        raise GuardConfigError(
            "guarded execution requested but the program has no "
            f"{HEALTH_OP!r} op — set {POLICY_ENV} before building it "
            "(optimizer.minimize instruments the program) or call "
            "resilience.guard.instrument(program) explicitly")


def instrument(program=None):
    """Append the `step_health` op (idempotent): Health <- Loss + the raw
    `@GRAD` bindings named by the program's autodiff boundary. Called by
    `optimizer.minimize` when PT_GUARD is armed; callable directly (e.g.
    bench.py's overhead A/B) on any program that has been through
    append_backward. Host-table rows-grads merged into the autodiff op
    AFTER instrumentation are excluded from the norm (they are gated
    host-side by the Trainer instead)."""
    from ..core.program import default_main_program
    from ..core.lowering import AUTODIFF_OP
    program = program if program is not None else default_main_program()
    block = program.global_block
    bop = next((op for op in block.ops if op.type == AUTODIFF_OP), None)
    if bop is None:
        raise GuardConfigError(
            "cannot instrument a program without an autodiff boundary — "
            "run optimizer.minimize / append_backward first")
    existing = next((op for op in block.ops if op.type == HEALTH_OP), None)
    if existing is not None:
        existing.inputs["Loss"] = [bop.attrs["loss"]]
        existing.inputs["Grads"] = list(bop.attrs["grad_names"])
        program.invalidate_cache()
        return program
    hv = block.create_var(HEALTH_VAR, shape=(), dtype="bool")
    hv.stop_gradient = True
    op = block.append_op(HEALTH_OP,
                         {"Loss": [bop.attrs["loss"]],
                          "Grads": list(bop.attrs["grad_names"])},
                         {"Health": [hv]}, {})
    # position matters, not just dataflow: the optimizer suffix REBINDS
    # the @GRAD names in place (clip.py writes {'X': grad} -> {'Out':
    # grad}), so an end-of-block health op would measure post-clip
    # values — the ceiling masked by exactly the clipping it exists to
    # see through. Move it directly after the autodiff boundary, where
    # the names still hold the raw backward gradients.
    block.ops.remove(op)
    block.ops.insert(block.ops.index(bop) + 1, op)
    program.invalidate_cache()
    return program


def maybe_instrument(program=None):
    """Instrument iff PT_GUARD is armed (the optimizer.minimize hook)."""
    if policy() is None:
        return program
    return instrument(program)


# -- the step_health op ------------------------------------------------------

_checkify_warned = threading.Event()


def warn_checkify_conflict() -> None:
    """Exactly-one-instrumentation rule: FLAGS_check_nan_inf (checkify —
    names the generating primitive, disables donation) and the guard
    must not both rewrite the step. The guard wins: it is the production
    path; checkify is the debug tool. Warn once per process."""
    if not _checkify_warned.is_set():
        _checkify_warned.set()
        warnings.warn(
            "both FLAGS_check_nan_inf and the step guard are enabled; the "
            "guard takes precedence and checkify instrumentation is "
            "skipped for guarded runs (use FLAGS_check_nan_inf alone to "
            "debug WHICH primitive produced the NaN; see "
            "docs/resilience.md)", stacklevel=3)


def _register_op() -> None:
    from ..core.registry import register_op
    from ..core.selected_rows import RowSparseGrad
    from ..core.lowering import AUTODIFF_OP

    def _health_shape(op, block):
        out = block.var(op.output("Health")[0])
        out.shape, out.dtype = (), "bool"

    @register_op(HEALTH_OP, infer_shape=_health_shape, supports_sparse=True)
    def step_health(ctx, ins, attrs):
        loss = ins["Loss"][0]
        ssq = jnp.float32(0.0)
        for g in ins.get("Grads", ()):
            v = g.values if isinstance(g, RowSparseGrad) else g
            v = v.astype(jnp.float32)
            ssq = ssq + jnp.sum(v * v)
        # grads carry the autodiff loss_scale (AMP); unscale so the
        # PT_GUARD_MAX_GNORM threshold is in true-gradient units
        scale = 1.0
        prog = getattr(ctx, "program", None)
        if prog is not None:
            bop = next((op for op in prog.global_block.ops
                        if op.type == AUTODIFF_OP), None)
            if bop is not None:
                scale = float(bop.attrs.get("loss_scale", 1.0))
        gnorm = jnp.sqrt(ssq) / jnp.float32(scale)
        healthy = (jnp.all(jnp.isfinite(loss))
                   & jnp.isfinite(gnorm)
                   & (gnorm <= jnp.float32(max_gnorm())))
        return {"Health": [healthy]}


_register_op()
