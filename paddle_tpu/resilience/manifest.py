"""Checkpoint manifests: per-file size + crc32, committed before _SUCCESS.

The _SUCCESS marker proves a save *finished*; it says nothing about
whether the bytes on disk are the bytes that were written (torn writes
that beat the crash, bit rot on preemptible-VM local disks, a stray `cp`
into the directory). The manifest closes that gap:

  * ``write_manifest(dirname)`` scans the directory's regular files and
    writes ``manifest.json`` — {version, layout, files: {name: {size,
    crc32}}} — atomically.
  * the _SUCCESS marker then stores the manifest file's own crc32
    (``success_payload``/``check_success``), binding marker -> manifest ->
    data: a truncated manifest is as detectable as a truncated shard.
  * ``verify_dir(dirname)`` re-digests and returns ("ok"|"legacy"|
    "corrupt", problems). "legacy" = a committed dir from before
    manifests existed — accepted, there is nothing to check against.
  * ``quarantine(path)`` renames a corrupt dir to ``<path>.corrupt[-k]``
    so the fallback loader skips it while the evidence survives for a
    post-mortem (deleting a corrupt checkpoint destroys the only artifact
    that can explain the corruption).
"""

from __future__ import annotations

import json
import os
import re
import zlib
from typing import Dict, List, Optional, Tuple

__all__ = ["MANIFEST_FILENAME", "VerificationError", "verify_on_load",
           "write_manifest", "read_manifest", "verify_dir", "verify_file",
           "success_payload", "check_success", "quarantine"]

MANIFEST_FILENAME = "manifest.json"


def verify_on_load() -> bool:
    """The ONE reading of the PT_CKPT_VERIFY opt-out (default on) — every
    load-time verification gate (checkpoints, inference dirs, host-table
    shards) must consult the same switch."""
    return os.environ.get("PT_CKPT_VERIFY", "1").strip().lower() \
        not in ("0", "false", "never")


class VerificationError(IOError):
    """Deterministic integrity failure (manifest mismatch, mixed layouts)
    — distinct from transient OSErrors so retry layers never re-run a
    load that can only fail the same way."""


#: never digested: the manifest itself, markers, in-flight temp files
_SKIP_PREFIXES = ("_SUCCESS",)

#: _atomic_save / write_manifest temps are `<final>.tmp<pid>` — match the
#: SUFFIX only: persistable BN running stats are legitimately named
#: `batch_norm_N.tmp_0.npy` and MUST be digested (they are exactly the
#: silently-wrong-if-rotten state the manifest exists to protect)
_TMP_SUFFIX = re.compile(r"\.tmp\d*$")  # host_table uses bare ".tmp"


def _skip(name: str) -> bool:
    return (name == MANIFEST_FILENAME or name.startswith(_SKIP_PREFIXES)
            or _TMP_SUFFIX.search(name) is not None)


def _digest(path: str) -> Tuple[int, int]:
    size = 0
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            size += len(chunk)
            crc = zlib.crc32(chunk, crc)
    return size, crc & 0xFFFFFFFF


def write_manifest(dirname: str, layout: str = "checkpoint",
                   extra: Optional[dict] = None) -> dict:
    """Digest every regular file in `dirname` (flat — checkpoint serial
    dirs have no nesting) into manifest.json, atomically.

    `extra` keys are merged into the manifest document (e.g. the
    checkpoint's plan stamp). Because _SUCCESS stores the manifest
    file's own size+crc32, anything merged here rides the same
    marker -> manifest -> data integrity binding for free."""
    files: Dict[str, dict] = {}
    for name in sorted(os.listdir(dirname)):
        path = os.path.join(dirname, name)
        if _skip(name) or not os.path.isfile(path):
            continue
        size, crc = _digest(path)
        files[name] = {"size": size, "crc32": crc}
    man = {"version": 1, "layout": layout, "files": files}
    if extra:
        for k, v in extra.items():
            if k in man:
                raise ValueError(f"manifest extra key {k!r} collides "
                                 "with a reserved manifest field")
            man[k] = v
    path = os.path.join(dirname, MANIFEST_FILENAME)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(man, f, indent=0, sort_keys=True)
    os.replace(tmp, path)
    return man


def read_manifest(dirname: str) -> Optional[dict]:
    path = os.path.join(dirname, MANIFEST_FILENAME)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}  # unreadable/truncated manifest: corrupt, not legacy


def success_payload(dirname: str) -> str:
    """What save_checkpoint writes INTO the _SUCCESS marker: the manifest
    file's own size+crc32, binding marker -> manifest -> data."""
    size, crc = _digest(os.path.join(dirname, MANIFEST_FILENAME))
    return json.dumps({"manifest_size": size, "manifest_crc32": crc})


def check_success(dirname: str, marker_filename: str = "_SUCCESS"
                  ) -> Optional[str]:
    """Verify the marker's manifest binding. None = ok (or a legacy empty
    marker / marker without a manifest reference); else a problem."""
    path = os.path.join(dirname, marker_filename)
    if not os.path.exists(path):
        return None  # unmarked dir (e.g. inference export): nothing to bind
    try:
        with open(path) as f:
            text = f.read().strip()
    except OSError as e:
        return f"_SUCCESS unreadable: {e}"
    if not text:
        return None  # legacy marker
    try:
        ref = json.loads(text)
    except ValueError:
        return "_SUCCESS payload is not JSON"
    mpath = os.path.join(dirname, MANIFEST_FILENAME)
    if not os.path.exists(mpath):
        return "_SUCCESS references a manifest that is absent"
    size, crc = _digest(mpath)
    if (size != ref.get("manifest_size")
            or crc != ref.get("manifest_crc32")):
        return (f"manifest.json does not match _SUCCESS binding "
                f"(size {size} crc {crc} vs {ref})")
    return None


def verify_dir(dirname: str, marker_filename: str = "_SUCCESS"
               ) -> Tuple[str, List[str]]:
    """("ok" | "legacy" | "corrupt", problems). "legacy" means no
    manifest to check against (pre-manifest checkpoint): accepted."""
    problems: List[str] = []
    mproblem = check_success(dirname, marker_filename)
    if mproblem:
        return "corrupt", [mproblem]
    man = read_manifest(dirname)
    if man is None:
        return "legacy", []
    files = man.get("files")
    if not isinstance(files, dict):
        return "corrupt", ["manifest.json unreadable or malformed"]
    for name, want in sorted(files.items()):
        path = os.path.join(dirname, name)
        if not os.path.isfile(path):
            problems.append(f"{name}: listed in manifest but absent")
            continue
        size, crc = _digest(path)
        if size != want.get("size"):
            problems.append(f"{name}: size {size} != manifest "
                            f"{want.get('size')}")
        elif crc != want.get("crc32"):
            problems.append(f"{name}: crc32 {crc} != manifest "
                            f"{want.get('crc32')}")
    return ("corrupt" if problems else "ok"), problems


def verify_file(dirname: str, name: str) -> Optional[str]:
    """Check ONE file against the dir's manifest. None = ok or nothing to
    check (no manifest / file unlisted); else the problem. For loaders
    that read a single file out of a manifested dir (host_table.load)."""
    man = read_manifest(dirname)
    if not man:
        return None
    want = (man.get("files") or {}).get(name)
    if want is None:
        return None
    path = os.path.join(dirname, name)
    if not os.path.isfile(path):
        return f"{name}: listed in manifest but absent"
    size, crc = _digest(path)
    if size != want.get("size") or crc != want.get("crc32"):
        return (f"{name}: size/crc32 ({size}, {crc}) != manifest "
                f"({want.get('size')}, {want.get('crc32')})")
    return None


def quarantine(path: str) -> str:
    """Rename a corrupt dir out of the serial namespace; returns the new
    path. Never deletes — the corpse is the post-mortem evidence."""
    dest = path + ".corrupt"
    k = 0
    while os.path.exists(dest):
        k += 1
        dest = f"{path}.corrupt-{k}"
    os.rename(path, dest)
    return dest
