"""Host-level elastic orchestration: heartbeat leases, hang-vs-crash
discrimination, and survivor restart onto the shrunk topology.

PR 17's ``ElasticSupervisor`` makes a single *process* survive — restore,
re-plan, reshard, resume. What it cannot see is the process that never
raises: a host whose collective is wedged looks exactly like a healthy
host to in-process supervision. This module is the layer above — the
ROADMAP's "cluster-scheduler hook" — a supervisor that owns N workers
through an injectable runner and a heartbeat-lease protocol:

* Every worker renews a **lease** at its step boundaries: an atomic JSON
  file in ``lease_dir`` carrying a monotonically increasing beat counter
  (plus the orchestration round and the training step). The orchestrator
  never trusts worker clocks — it records ``seen_at`` with its OWN
  (injectable) clock whenever the ``(round, beat)`` marker advances, so
  a worker with a skewed or frozen clock is still judged correctly.

* A worker whose lease age exceeds ``lease_s + grace_s`` is evicted.
  The *cause* is discriminated by the handle, not the lease:

  - handle dead with an error  -> ``worker_crash`` (the device-loss
    shape: the process is gone, nothing to kill)
  - handle alive, lease stale  -> ``heartbeat_loss`` (a hung collective
    or stuck step: the orchestrator KILLS it, then evicts)

  Both paths converge on one recovery: gracefully stop the survivors
  (cooperative stop -> ``Trainer.request_preemption()`` -> checkpoint at
  the next step boundary), compute the surviving slice, write it to
  ``PT_ELASTIC_TOPOLOGY``, and restart the survivors so each one's
  ``ElasticSupervisor`` re-plans onto the shrunk fabric and resumes at
  the exact recorded step.

Runners follow the fleet tier's pattern (serving/fleet/pool.py): the
default ``ThreadRunner`` hosts workers as daemon threads — what tier-1
and the chaos harness drive on CPU, with an injectable clock so eviction
timing is deterministic — while ``SubprocessRunner`` spawns real
processes for clusters (graceful stop is SIGTERM, which the Trainer
already treats as preemption; kill is SIGKILL). A killed thread cannot
actually be destroyed, so ``ThreadHandle.kill`` abandons it exactly like
the step watchdog abandons a wedged dispatch: the handle reports dead,
the daemon thread unblocks on the stop event and exits on its own.

Knobs: PT_ORCH_LEASE_S, PT_ORCH_GRACE_S, PT_ORCH_STOP_GRACE_S,
PT_ORCH_EVICTIONS (all declared in flags.py). Metrics ride the unified
exposition as the ``pt_orch_*`` family; evictions and recoveries emit
``orch:evict`` / ``orch:recover`` trace spans.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..flags import env_knob_float, env_knob_int

__all__ = [
    "DEFAULT_LEASE_S", "DEFAULT_POLL_S", "DEFAULT_STOP_GRACE_S",
    "LeaseTable", "OrchMetrics", "Orchestrator", "OrchestratorError",
    "SubprocessRunner", "ThreadHandle", "ThreadRunner", "WorkerContext",
    "WorkerSpec", "peer_worker", "read_lease", "worker_context_from_env",
]

DEFAULT_LEASE_S = 10.0
DEFAULT_STOP_GRACE_S = 30.0
DEFAULT_EVICTIONS = 3
DEFAULT_POLL_S = 0.02

CAUSE_CRASH = "worker_crash"
CAUSE_HANG = "heartbeat_loss"


class OrchestratorError(RuntimeError):
    """Unrecoverable orchestration failure: eviction budget exhausted,
    every worker evicted, or the primary (training) worker itself was
    evicted — conditions where shrinking again has nothing to shrink
    onto."""


# ---------------------------------------------------------------------------
# the lease protocol
# ---------------------------------------------------------------------------

def _lease_path(lease_dir: str, wid: str) -> str:
    return os.path.join(lease_dir, f"{wid}.lease.json")


def _write_atomic_json(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_lease(lease_dir: str, wid: str) -> Optional[dict]:
    """The worker's last renewal, or None when it never renewed (or the
    file is unreadable — treated as no renewal, never as a crash of the
    orchestrator)."""
    try:
        with open(_lease_path(lease_dir, wid)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class WorkerContext:
    """What a worker body receives: its identity, the lease to renew,
    and the cooperative stop signal. ``heartbeat()`` is the per-step
    renewal; ``should_stop()`` is polled at the same boundaries (the
    orchestrator's graceful-stop request during a recovery)."""

    def __init__(self, wid: str, lease_dir: str, round_n: int = 0,
                 stop_event: Optional[threading.Event] = None,
                 clock: Callable[[], float] = time.time):
        self.wid = wid
        self.lease_dir = lease_dir
        os.makedirs(lease_dir, exist_ok=True)
        self.round_n = int(round_n)
        self._stop = stop_event if stop_event is not None \
            else threading.Event()
        self._clock = clock
        self._beat = 0

    def heartbeat(self, step: Optional[int] = None) -> int:
        """Renew the lease; returns the beat counter (monotonic within
        this context — the orchestrator keys staleness off (round, beat)
        advancing, so the counter restarting at 1 on a new round is
        itself an advance)."""
        self._beat += 1
        _write_atomic_json(_lease_path(self.lease_dir, self.wid), {
            "wid": self.wid, "round": self.round_n, "beat": self._beat,
            "step": step, "pid": os.getpid(), "wall": self._clock(),
        })
        return self._beat

    def should_stop(self) -> bool:
        return self._stop.is_set()


def worker_context_from_env(
        clock: Callable[[], float] = time.time) -> WorkerContext:
    """The subprocess side of the wire protocol: SubprocessRunner passes
    identity via PT_ORCH_WORKER_ID / PT_ORCH_LEASE_DIR / PT_ORCH_ROUND;
    a worker __main__ builds its context from them. Graceful stop for
    real processes is SIGTERM — the Trainer's existing preemption path —
    so ``should_stop`` stays False here."""
    wid = os.environ.get("PT_ORCH_WORKER_ID", "").strip()
    lease_dir = os.environ.get("PT_ORCH_LEASE_DIR", "").strip()
    if not wid or not lease_dir:
        raise OrchestratorError(
            "worker_context_from_env: PT_ORCH_WORKER_ID / "
            "PT_ORCH_LEASE_DIR unset — not launched by SubprocessRunner")
    round_n = env_knob_int("PT_ORCH_ROUND", 1) - 1 \
        if os.environ.get("PT_ORCH_ROUND") else 0
    return WorkerContext(wid, lease_dir, round_n=round_n, clock=clock)


class LeaseTable:
    """Orchestrator-side lease ages. ``observe`` reads the worker's
    file; ``seen_at`` advances on OUR clock only when the (round, beat)
    marker changes, so staleness judgment never depends on worker
    clocks — and an injectable clock makes it fake-time testable."""

    def __init__(self, lease_dir: str,
                 clock: Callable[[], float] = time.monotonic):
        self.lease_dir = lease_dir
        self._clock = clock
        self._seen_at: Dict[str, float] = {}
        self._marker: Dict[str, Optional[Tuple]] = {}
        self._payload: Dict[str, Optional[dict]] = {}

    def register(self, wid: str) -> None:
        """(Re)start accounting for a worker: it gets a full lease from
        now to produce its first beat of the new round."""
        self._seen_at[wid] = self._clock()
        self._marker[wid] = None
        self._payload[wid] = None

    def observe(self, wid: str) -> float:
        """Refresh from disk; returns the lease age in orchestrator
        seconds (0 right after a fresh beat or registration)."""
        payload = read_lease(self.lease_dir, wid)
        if payload is not None:
            marker = (payload.get("round"), payload.get("beat"))
            if marker != self._marker.get(wid):
                self._marker[wid] = marker
                self._seen_at[wid] = self._clock()
                self._payload[wid] = payload
        return self._clock() - self._seen_at.get(wid, self._clock())

    def age(self, wid: str) -> float:
        return self._clock() - self._seen_at.get(wid, self._clock())

    def last_payload(self, wid: str) -> Optional[dict]:
        return self._payload.get(wid)


# ---------------------------------------------------------------------------
# runners (the injectable process layer — fleet/pool.py's pattern)
# ---------------------------------------------------------------------------

class WorkerSpec:
    """One worker's identity and resources. ``target`` is what the
    runner executes: a callable taking the WorkerContext under
    ThreadRunner, an argv list under SubprocessRunner. ``primary`` marks
    the worker whose clean completion ends the run (the training chief
    in the emulated-mesh setup; real clusters train on every worker and
    mark rank 0)."""

    def __init__(self, wid: str, target, chips: int = 1,
                 primary: bool = False,
                 lease_s: Optional[float] = None):
        if chips < 1:
            raise ValueError(f"WorkerSpec {wid!r}: chips must be >= 1")
        self.wid = str(wid)
        self.target = target
        self.chips = int(chips)
        self.primary = bool(primary)
        self.lease_s = None if lease_s is None else float(lease_s)


class ThreadHandle:
    """A thread-hosted worker. ``kill`` abandons the daemon thread (a
    thread cannot be destroyed) and reports it dead — the watchdog's
    abandonment idiom; the body unblocks on the same event a graceful
    stop sets, so an injected 'hang' only hangs the lease protocol, not
    the interpreter."""

    def __init__(self, thread: threading.Thread,
                 stop_event: threading.Event):
        self._thread = thread
        self._stop_event = stop_event
        self.error: Optional[BaseException] = None
        self.stop_requested = False
        self.killed = False

    def alive(self) -> bool:
        return not self.killed and self._thread.is_alive()

    def stop(self) -> None:
        self.stop_requested = True
        self._stop_event.set()

    def kill(self) -> None:
        self.killed = True
        self._stop_event.set()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)


class ThreadRunner:
    """Tier-1's runner: each worker is a daemon thread running
    ``spec.target(ctx)``; exceptions land on ``handle.error`` (how the
    orchestrator discriminates a crash from a clean return)."""

    def __call__(self, spec: WorkerSpec, ctx: WorkerContext) \
            -> ThreadHandle:
        stop_event = ctx._stop
        holder: List[ThreadHandle] = []

        def body():
            try:
                spec.target(ctx)
            except BaseException as e:  # noqa: BLE001 — recorded, judged
                holder[0].error = e

        thread = threading.Thread(
            target=body, name=f"pt-orch-{spec.wid}", daemon=True)
        handle = ThreadHandle(thread, stop_event)
        holder.append(handle)
        thread.start()
        return handle


class SubprocessHandle:
    """A real-process worker (clusters). Graceful stop is SIGTERM — the
    Trainer's installed preemption handler checkpoints at the next step
    boundary; kill is SIGKILL."""

    def __init__(self, proc: "subprocess.Popen"):
        self._proc = proc
        self.stop_requested = False
        self.killed = False

    @property
    def error(self) -> Optional[BaseException]:
        rc = self._proc.poll()
        if rc is None or rc == 0:
            return None
        return RuntimeError(
            f"worker pid {self._proc.pid} exited with status {rc}")

    def alive(self) -> bool:
        return self._proc.poll() is None

    def stop(self) -> None:
        self.stop_requested = True
        try:
            self._proc.send_signal(signal.SIGTERM)
        except OSError:  # pragma: no cover — already gone
            pass

    def kill(self) -> None:
        self.killed = True
        try:
            self._proc.kill()
        except OSError:  # pragma: no cover — already gone
            pass

    def join(self, timeout: Optional[float] = None) -> None:
        try:
            self._proc.wait(timeout)
        except subprocess.TimeoutExpired:
            pass


class SubprocessRunner:
    """Cluster runner: ``spec.target`` is an argv list (e.g.
    ``[sys.executable, "train.py"]``); identity rides the environment
    (PT_ORCH_WORKER_ID / PT_ORCH_LEASE_DIR / PT_ORCH_ROUND — see
    ``worker_context_from_env``) along with the current
    PT_ELASTIC_TOPOLOGY, so a restarted worker plans for the surviving
    slice without any new wire format."""

    def __init__(self, python: Optional[str] = None):
        self.python = python or sys.executable

    def __call__(self, spec: WorkerSpec, ctx: WorkerContext) \
            -> SubprocessHandle:
        argv = list(spec.target)
        env = dict(os.environ)
        env["PT_ORCH_WORKER_ID"] = spec.wid
        env["PT_ORCH_LEASE_DIR"] = ctx.lease_dir
        env["PT_ORCH_ROUND"] = str(ctx.round_n + 1)
        proc = subprocess.Popen(argv, env=env)
        return SubprocessHandle(proc)


# ---------------------------------------------------------------------------
# metrics (pt_orch_* on the unified exposition)
# ---------------------------------------------------------------------------

class OrchMetrics:
    """One orchestrator's counters. Thread-safe: the poll loop records
    while HTTP scrapes read."""

    def __init__(self, name: str = "orch"):
        self.name = name
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.workers_live = 0
            self.workers_total = 0
            self.rounds = 0
            self.current_chips: Optional[int] = None
            self.target_chips: Optional[int] = None
            self.lease_age_max_s = 0.0
            self.last_detect_s: Optional[float] = None
            self.last_recovery_s: Optional[float] = None
            self.recoveries = 0
            self.recovery_s_total = 0.0
            self.evictions = 0
            self.evictions_by_cause: Dict[str, int] = {}

    def on_evict(self, cause: str, detect_s: float) -> None:
        with self._lock:
            self.evictions += 1
            self.evictions_by_cause[cause] = \
                self.evictions_by_cause.get(cause, 0) + 1
            self.last_detect_s = max(0.0, float(detect_s))

    def on_recover(self, recovery_s: float) -> None:
        with self._lock:
            self.recoveries += 1
            self.last_recovery_s = max(0.0, float(recovery_s))
            self.recovery_s_total += max(0.0, float(recovery_s))

    def set_state(self, live: int, total: int, rounds: int,
                  lease_age_max_s: float) -> None:
        with self._lock:
            self.workers_live = int(live)
            self.workers_total = int(total)
            self.rounds = int(rounds)
            self.lease_age_max_s = max(0.0, float(lease_age_max_s))

    def set_chips(self, current: Optional[int],
                  target: Optional[int]) -> None:
        with self._lock:
            if current is not None:
                self.current_chips = int(current)
            if target is not None:
                self.target_chips = int(target)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "workers_live": self.workers_live,
                "workers_total": self.workers_total,
                "rounds": self.rounds,
                "current_chips": self.current_chips,
                "target_chips": self.target_chips,
                "lease_age_max_s": round(self.lease_age_max_s, 6),
                "last_detect_s": self.last_detect_s,
                "last_recovery_s": self.last_recovery_s,
                "recoveries": self.recoveries,
                "recovery_s_total": round(self.recovery_s_total, 6),
                "evictions": self.evictions,
                "evictions_by_cause": dict(self.evictions_by_cause),
            }


# ---------------------------------------------------------------------------
# worker bodies
# ---------------------------------------------------------------------------

def peer_worker(ctx: WorkerContext, interval_s: float = 0.05,
                sleep: Callable[[float], None] = time.sleep) -> None:
    """A non-training host's worker body: renew the lease on a cadence
    until asked to stop. Hosts the two chaos sites — ``worker_crash``
    raises out of the body (a dead handle), ``heartbeat_loss`` silences
    every later renewal while the body stays alive (the hung-collective
    shape: only a kill ends it)."""
    from . import faults
    silent = False
    step = 0
    while not ctx.should_stop():
        if not silent:
            faults.crash_point(CAUSE_CRASH)
            if faults.fire(CAUSE_HANG) is not None:
                silent = True
            else:
                ctx.heartbeat(step=step)
        step += 1
        sleep(interval_s)


# ---------------------------------------------------------------------------
# the orchestrator
# ---------------------------------------------------------------------------

class _Worker:
    __slots__ = ("spec", "ctx", "handle", "state", "cause", "round_n")

    def __init__(self, spec: WorkerSpec):
        self.spec = spec
        self.ctx: Optional[WorkerContext] = None
        self.handle = None
        self.state = "new"       # live | done | stopped | evicted
        self.cause: Optional[str] = None
        self.round_n = 0


class Orchestrator:
    """Own N workers; evict on lease expiry (discriminating hang from
    crash); recover by restarting survivors onto the shrunk topology.

    ``run()`` drives the poll loop to completion and returns a report
    dict. Completion: the primary worker returning cleanly (remaining
    workers are stopped), or — with no primary — every worker returning
    cleanly. Exhausting the eviction budget, losing every worker, or
    losing the primary raises OrchestratorError (after killing what
    remains: no orphaned threads/processes behind an exception)."""

    def __init__(self, specs: Sequence[WorkerSpec], lease_dir: str,
                 runner=None, chip: str = "cpu",
                 lease_s: Optional[float] = None,
                 grace_s: Optional[float] = None,
                 stop_grace_s: Optional[float] = None,
                 max_evictions: Optional[int] = None,
                 poll_s: float = DEFAULT_POLL_S,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep,
                 metrics: Optional[OrchMetrics] = None,
                 name: str = "orch"):
        specs = list(specs)
        if not specs:
            raise ValueError("Orchestrator: no workers")
        wids = [s.wid for s in specs]
        if len(set(wids)) != len(wids):
            raise ValueError(f"Orchestrator: duplicate worker ids {wids}")
        if sum(1 for s in specs if s.primary) > 1:
            raise ValueError("Orchestrator: at most one primary worker")
        self.workers = [_Worker(s) for s in specs]
        self.lease_dir = lease_dir
        self.runner = runner or ThreadRunner()
        self.chip = chip
        self.lease_s = lease_s if lease_s is not None \
            else env_knob_float("PT_ORCH_LEASE_S", DEFAULT_LEASE_S)
        self.grace_s = grace_s if grace_s is not None \
            else env_knob_float("PT_ORCH_GRACE_S", self.lease_s / 2.0)
        self.stop_grace_s = stop_grace_s if stop_grace_s is not None \
            else env_knob_float("PT_ORCH_STOP_GRACE_S",
                                DEFAULT_STOP_GRACE_S)
        self.max_evictions = max_evictions if max_evictions is not None \
            else env_knob_int("PT_ORCH_EVICTIONS", DEFAULT_EVICTIONS)
        self.poll_s = float(poll_s)
        self._clock = clock
        self._sleep = sleep
        self.table = LeaseTable(lease_dir, clock=clock)
        self.metrics = metrics or OrchMetrics(name)
        from ..obs.metrics import REGISTRY
        REGISTRY.register("orch", self.metrics.name, self.metrics)
        self.round_n = 0
        self.evictions: List[dict] = []
        self.recoveries: List[float] = []
        self.topology: Optional[str] = None
        target = sum(s.chips for s in specs)
        self.metrics.set_chips(target, target)

    # -- helpers -----------------------------------------------------------

    def _worker_lease(self, w: _Worker) -> float:
        return w.spec.lease_s if w.spec.lease_s is not None \
            else self.lease_s

    def _live(self) -> List[_Worker]:
        return [w for w in self.workers if w.state == "live"]

    def _start(self, w: _Worker) -> None:
        w.round_n = self.round_n
        w.ctx = WorkerContext(w.spec.wid, self.lease_dir,
                              round_n=self.round_n)
        self.table.register(w.spec.wid)
        w.handle = self.runner(w.spec, w.ctx)
        w.state = "live"
        w.cause = None

    def _topology_str(self, survivors: List[_Worker]) -> str:
        per = sorted({w.spec.chips for w in survivors})
        if len(per) == 1 and len(survivors) > 1:
            return f"{self.chip}:{per[0]}x{len(survivors)}"
        if len(per) == 1:
            return f"{self.chip}:{per[0]}"
        # heterogeneous survivors: describe the flat chip count
        total = sum(w.spec.chips for w in survivors)
        return f"{self.chip}:{total}"

    def _stop_workers(self, targets: List[_Worker]) -> None:
        """Graceful stop: cooperative stop request, wait out the stop
        grace (the chief needs a step boundary to checkpoint at), then
        kill stragglers."""
        for w in targets:
            w.handle.stop()
        deadline = self._clock() + self.stop_grace_s
        while (any(w.handle.alive() for w in targets)
                and self._clock() < deadline):
            self._sleep(self.poll_s)
        for w in targets:
            if w.handle.alive():
                w.handle.kill()

    def _kill_all_live(self) -> None:
        for w in self._live():
            w.handle.kill()
            w.state = "stopped"

    def _beat_round(self, w: _Worker) -> int:
        payload = self.table.last_payload(w.spec.wid)
        if not payload:
            return -1
        try:
            return int(payload.get("round", -1))
        except (TypeError, ValueError):
            return -1

    # -- recovery ----------------------------------------------------------

    def _recover(self, evicted: List[Tuple[_Worker, str, float]]) -> None:
        from ..obs import trace as obs_trace
        for w, cause, age in evicted:
            with obs_trace.span("orch:evict", cat="orch",
                                wid=w.spec.wid, cause=cause,
                                round=self.round_n,
                                detect_s=round(age, 6)):
                if w.handle.alive():
                    # the hang case: a live worker holding a dead lease
                    # is wedged — reclaim the slot before re-planning
                    w.handle.kill()
                w.state = "evicted"
                w.cause = cause
            self.evictions.append({
                "wid": w.spec.wid, "cause": cause,
                "round": self.round_n, "detect_s": round(age, 6)})
            self.metrics.on_evict(cause, age)
        if len(self.evictions) > self.max_evictions:
            raise OrchestratorError(
                f"eviction budget exhausted ({len(self.evictions)} > "
                f"PT_ORCH_EVICTIONS={self.max_evictions}); last causes: "
                + ", ".join(e["cause"] for e in self.evictions[-3:]))
        if any(w.spec.primary and w.state == "evicted"
               for w in self.workers):
            raise OrchestratorError(
                "primary worker evicted (cause "
                + str(next(w.cause for w in self.workers
                           if w.spec.primary))
                + ") — nothing left to resume the run")
        survivors = self._live()
        if not survivors:
            raise OrchestratorError("all workers evicted — no surviving "
                                    "slice to restart onto")
        t0 = self._clock()
        # workers already finished cleanly keep their result; only live
        # survivors are cycled through stop -> restart (restarting a
        # completed trainer would replay steps past its final
        # checkpoint)
        done_early = [w for w in survivors
                      if not w.handle.alive() and w.handle.error is None]
        for w in done_early:
            w.state = "done"
        survivors = [w for w in survivors if w.state == "live"]
        chips = sum(w.spec.chips for w in survivors)
        with obs_trace.span("orch:recover", cat="orch",
                            survivors=len(survivors), chips=chips,
                            round=self.round_n + 1):
            if survivors:
                self._stop_workers(survivors)
                self.topology = self._topology_str(survivors)
                os.environ["PT_ELASTIC_TOPOLOGY"] = self.topology
                self.round_n += 1
                for w in survivors:
                    self._start(w)
                self._await_resumed(survivors)
        recovery_s = self._clock() - t0
        self.recoveries.append(round(recovery_s, 6))
        self.metrics.on_recover(recovery_s)
        self.metrics.set_chips(chips, None)

    def _await_resumed(self, restarted: List[_Worker]) -> None:
        """Block until every restarted worker has either beaten in the
        new round or left the live state (finished / died — the main
        loop classifies those next poll). This is what makes
        recovery_seconds an end-to-end number: restore + re-plan +
        reshard + compile + first step, not just the restart syscall.
        Bounded by the workers' own lease windows: a restarted worker
        that never beats is the MAIN loop's problem (it will be evicted
        like any other silent worker), not a recovery deadlock."""
        deadline = self._clock() + max(
            self._worker_lease(w) + self.grace_s for w in restarted)
        while self._clock() < deadline:
            pending = False
            for w in restarted:
                self.table.observe(w.spec.wid)
                if self._beat_round(w) >= w.round_n:
                    continue
                if w.handle.alive():
                    pending = True
            if not pending:
                return
            self._sleep(self.poll_s)

    # -- the run loop ------------------------------------------------------

    def run(self) -> dict:
        """Drive to completion; returns the report. Restores the
        pre-run PT_ELASTIC_TOPOLOGY on exit (the orchestrator mutates
        process-global env so thread-hosted supervisors can read it —
        single orchestrator per process at a time)."""
        prior_topo = os.environ.get("PT_ELASTIC_TOPOLOGY")
        started = self._clock()
        for w in self.workers:
            self._start(w)
        try:
            report = self._loop()
            report["wall_s"] = round(self._clock() - started, 6)
            return report
        finally:
            self._kill_all_live()
            if prior_topo is None:
                os.environ.pop("PT_ELASTIC_TOPOLOGY", None)
            else:
                os.environ["PT_ELASTIC_TOPOLOGY"] = prior_topo

    def _loop(self) -> dict:
        while True:
            self._sleep(self.poll_s)
            evicted: List[Tuple[_Worker, str, float]] = []
            max_age = 0.0
            primary_done = False
            for w in self._live():
                age = self.table.observe(w.spec.wid)
                if not w.handle.alive():
                    if w.handle.error is not None:
                        evicted.append((w, CAUSE_CRASH, age))
                    else:
                        w.state = "done"
                        if w.spec.primary:
                            primary_done = True
                    continue
                max_age = max(max_age, age)
                limit = self._worker_lease(w) + self.grace_s
                if age > limit:
                    evicted.append((w, CAUSE_HANG, age))
            self.metrics.set_state(
                live=len(self._live()), total=len(self.workers),
                rounds=self.round_n, lease_age_max_s=max_age)
            if primary_done:
                self._stop_workers(self._live())
                for w in self._live():
                    w.state = "stopped"
                return self._report(completed=True)
            if evicted:
                self._recover(evicted)
                continue
            if not self._live():
                # no primary declared: completion means every worker
                # that was not evicted returned cleanly
                done = [w for w in self.workers if w.state == "done"]
                ok = bool(done) and all(
                    w.state in ("done", "evicted")
                    for w in self.workers)
                return self._report(completed=ok)

    def _report(self, completed: bool) -> dict:
        return {
            "completed": bool(completed),
            "rounds": self.round_n,
            "evictions": list(self.evictions),
            "recoveries": list(self.recoveries),
            "workers": {w.spec.wid: w.state for w in self.workers},
            "topology": self.topology,
            "surviving_chips": sum(
                w.spec.chips for w in self.workers
                if w.state in ("live", "done", "stopped")),
            "target_chips": sum(w.spec.chips for w in self.workers),
        }
