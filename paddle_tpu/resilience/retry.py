"""Bounded retry with exponential backoff + jitter, and the reader-restart
wrapper built on it.

One retry primitive for the whole package (checkpoint I/O, reader
restarts) instead of ad-hoc loops: the policy is a value (bounded
attempts, capped backoff, seeded jitter, a predicate for *which* errors
are worth retrying, an optional total-time deadline), and exhaustion
always re-raises the ORIGINAL error — a retry layer that replaces the
root cause with its own exception is a debugging hazard.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Iterable, Optional, Tuple, Union

from . import faults

__all__ = ["RetryPolicy", "retry_call", "resilient_reader"]


class RetryPolicy:
    """How to retry: `retries` additional attempts after the first, delay
    ``base_delay * 2**k`` capped at `max_delay`, each scaled by a seeded
    jitter factor in [1, 1+jitter] (decorrelates a fleet of preempted
    workers hammering shared storage in lockstep). `retry_on` is an
    exception class/tuple or a predicate ``exc -> bool``; `deadline`
    (seconds of total elapsed time, None = unbounded) stops retrying even
    with attempts left. `sleep`/`clock` are injectable for tests."""

    def __init__(self, retries: int = 3, base_delay: float = 0.05,
                 max_delay: float = 2.0, jitter: float = 0.5,
                 retry_on: Union[type, Tuple[type, ...],
                                 Callable[[BaseException], bool]] = Exception,
                 deadline: Optional[float] = None, seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        if retries < 0:
            raise ValueError("retries must be >= 0")
        self.retries = retries
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.jitter = jitter
        self.retry_on = retry_on
        self.deadline = deadline
        self.seed = seed
        self.sleep = sleep
        self.clock = clock

    def should_retry(self, exc: BaseException) -> bool:
        if callable(self.retry_on) and not isinstance(self.retry_on, type):
            return bool(self.retry_on(exc))
        return isinstance(exc, self.retry_on)

    def delays(self) -> Iterable[float]:
        """The backoff schedule, one delay per retry attempt."""
        rng = random.Random(f"{self.seed}:backoff")
        for k in range(self.retries):
            d = min(self.base_delay * (2.0 ** k), self.max_delay)
            yield d * (1.0 + self.jitter * rng.random())


class _Attempts:
    """Shared retry bookkeeping for retry_call and resilient_reader: one
    place decides retry-vs-reraise (filter, attempt budget, deadline) so
    the two loop shapes can never drift apart."""

    def __init__(self, policy: Optional[RetryPolicy],
                 on_retry: Optional[Callable]):
        self.policy = policy
        self.on_retry = on_retry
        self.n = 0
        self._delays = iter(policy.delays()) if policy is not None \
            else iter(())
        self._start = policy.clock() if policy is not None else 0.0

    def backoff_or_reraise(self, exc: BaseException) -> None:
        """Called from an except block: either sleeps the next backoff
        delay (recording the attempt, invoking on_retry) or re-raises the
        exception being handled — on a non-retryable error, attempt
        exhaustion, or a blown deadline."""
        p = self.policy
        if p is None or not p.should_retry(exc):
            raise
        delay = next(self._delays, None)
        if delay is None:
            raise
        if (p.deadline is not None
                and p.clock() - self._start + delay > p.deadline):
            raise
        self.n += 1
        if self.on_retry is not None:
            self.on_retry(exc, self.n, delay)
        p.sleep(delay)


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               on_retry: Optional[Callable] = None, **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying per `policy`. `on_retry` is
    invoked as ``on_retry(exc, attempt, delay)`` before each backoff
    sleep. Exhaustion (attempts or deadline) re-raises the original
    error."""
    attempts = _Attempts(policy or RetryPolicy(), on_retry)
    while True:
        try:
            return fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — filtered just below
            attempts.backoff_or_reraise(e)


def resilient_reader(reader: Callable, policy: Optional[RetryPolicy] = None,
                     on_retry: Optional[Callable] = None) -> Callable:
    """Wrap a reader (a callable returning an iterator of batches) so that
    an exception mid-epoch restarts it — re-invoking `reader()` and
    fast-forwarding past the batches already delivered, so the consumer
    sees each batch exactly once, in order, with no duplicates.

    This is the trainer's reader fault boundary: every delivered batch
    passes the ``reader_raise`` injection site (faults.py), INSIDE the
    retried region, so ``PT_FAULT_INJECT=reader_raise@N`` exercises
    exactly the restart machinery a flaky data source would. With
    ``policy=None`` the wrapper only hosts the fault site — no retries,
    errors propagate unchanged.

    Fast-forward replays the source's batches without delivering them:
    correct for deterministic readers (files, RecordIO, seeded shuffles);
    a nondeterministic source resumes on a *different* stream, which is
    exactly what it would give a fresh process too. A reader exposing
    ``iter_from(n)`` (the data-pipeline protocol, data/pipeline.py) fast-
    forwards through it instead — the skipped batches are never decoded.

    The wrapper is itself skippable (``wrapped.iter_from(n)`` starts with
    n batches already delivered — the Trainer's mid-epoch resume path)
    and forwards the pipeline's ``set_epoch``/``state`` surface, so a
    wrapped pipeline keeps its deterministic-resume contract."""

    cheap_skip = hasattr(reader, "iter_from")

    def wrapped(start: int = 0):
        delivered = int(start)
        attempts = _Attempts(policy, on_retry)
        while True:
            try:
                # freeze the fast-forward target: `delivered` grows as
                # this attempt yields, but only batches delivered by
                # PRIOR attempts (or the caller's `start`) are skipped
                to_skip = delivered
                if to_skip and cheap_skip:
                    it = reader.iter_from(to_skip)
                    skipped = to_skip
                else:
                    it = reader()
                    skipped = 0
                for item in it:
                    if skipped < to_skip:
                        skipped += 1
                        continue
                    faults.crash_point("reader_raise")
                    delivered += 1
                    yield item
                return
            except BaseException as e:  # noqa: BLE001 — filtered below
                attempts.backoff_or_reraise(e)

    wrapped.iter_from = wrapped
    for attr in ("set_epoch", "state", "restore", "metrics_snapshot"):
        if hasattr(reader, attr):
            setattr(wrapped, attr, getattr(reader, attr))
    #: True only when a budget is ARMED — double_buffer's stacking
    #: detection keys on this (a policy-less wrapper just hosts the
    #: fault site and stacks harmlessly)
    wrapped._pt_resilient = policy is not None
    return wrapped
