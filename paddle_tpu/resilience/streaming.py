"""Streaming reshard: move a serial dir chunk-by-chunk under a bounded
host-memory budget.

The gather path (``elastic.reshard_state`` / ``tools/reshard.py``)
materializes every var as a full host array — exactly what a small
survivor host resharding a big model cannot do. This engine never holds
more than one slab: sources are opened as read-only memmaps (full
``<var>.npy`` files and multi-process ``<var>.shard.<spans>.npy``
pieces alike), the destination is an ``open_memmap`` full array, and
data moves in slabs of at most ``PT_RESHARD_CHUNK_MB`` (rows of the
outer dim; a single row larger than the budget degrades to
one-row slabs, so the bound is ``max(chunk, one row) + constant``).
Because checkpoints hold full logical arrays, the result is
bit-identical to the gather path.

Every slab is digested (crc32) and recorded in a progress sidecar
(atomic JSON, one write per chunk), which buys two properties:

* **Resumable**: an interrupted stream re-run with the same chunk
  budget verifies already-written chunks against their recorded digests
  and copies only the remainder.
* **Corruption refusal**: a verified chunk whose bytes on disk no
  longer match its digest raises ``ChunkCorruptError`` (typed, names
  the chunk) instead of silently shipping a bit-rotten region into a
  "fresh" checkpoint.

Structural validation is header-only (``elastic.validate_reshard_shapes``
over npy-header shapes) — the whole point is never needing the arrays in
memory. The caller (tools/reshard.py --stream) stamps the manifest +
_SUCCESS after the stream completes; the progress sidecar is deleted on
completion so a committed serial carries no streaming residue.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Callable, List, Optional, Tuple

import numpy as np

from .elastic import ReshardError, validate_reshard_shapes

__all__ = ["ChunkCorruptError", "DEFAULT_CHUNK_MB", "PROGRESS_FILENAME",
           "chunk_bytes_default", "iter_slabs", "stream_reshard"]

#: sidecar recording per-chunk digests; lives in the DESTINATION dir
PROGRESS_FILENAME = ".reshard_progress.json"
DEFAULT_CHUNK_MB = 64


class ChunkCorruptError(ReshardError):
    """A chunk recorded as copied no longer matches its digest — the
    destination rotted (or was edited) between the interrupted stream
    and the resume. Refusal, not repair: the caller decides whether to
    delete the destination and restream from scratch."""


def chunk_bytes_default() -> int:
    from ..flags import env_knob_int
    return env_knob_int("PT_RESHARD_CHUNK_MB", DEFAULT_CHUNK_MB) << 20


def iter_slabs(shape: Tuple[int, ...], itemsize: int,
               chunk_bytes: int) -> List[Tuple[int, int]]:
    """Row ranges over dim 0 sizing each slab at <= chunk_bytes (one
    row minimum — the degenerate bound documented above). A 0-d or
    empty array is a single (0, len) slab."""
    if not shape:
        return [(0, 1)]
    rows = int(shape[0])
    if rows == 0:
        return [(0, 0)]
    row_bytes = int(itemsize)
    for d in shape[1:]:
        row_bytes *= int(d)
    per = max(1, chunk_bytes // max(1, row_bytes))
    return [(a, min(a + per, rows)) for a in range(0, rows, per)]


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(memoryview(np.ascontiguousarray(arr)))


def _write_atomic(path: str, payload: dict) -> None:
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _load_progress(dst_dir: str, chunk_bytes: int,
                   mesh_key: str) -> dict:
    """The resume ledger — discarded (fresh start) when the chunk
    budget or target mesh changed, because chunk ids embed slab
    boundaries and the digest set is only meaningful for one
    (budget, target) pair."""
    path = os.path.join(dst_dir, PROGRESS_FILENAME)
    try:
        with open(path) as f:
            prog = json.load(f)
    except (OSError, ValueError):
        prog = None
    if (not isinstance(prog, dict) or prog.get("version") != 1
            or prog.get("chunk_bytes") != chunk_bytes
            or prog.get("mesh") != mesh_key):
        prog = {"version": 1, "chunk_bytes": chunk_bytes,
                "mesh": mesh_key, "vars": {}}
    return prog


def stream_reshard(src_dir: str, dst_dir: str, to_plan: dict,
                   chunk_bytes: Optional[int] = None,
                   resume: bool = True,
                   chunk_hook: Optional[Callable[[str, str], None]]
                   = None) -> dict:
    """Stream every persisted var of ``src_dir`` into full ``.npy``
    arrays in ``dst_dir``, laid out for (and validated against)
    ``to_plan``. Returns a report dict (vars, chunk counts, bytes).

    ``chunk_hook(var, chunk_id)`` is called after each chunk commits —
    the test harness's interruption point (raise to simulate dying
    mid-stream); ``resume=False`` ignores any progress sidecar."""
    from .. import io as io_mod
    if chunk_bytes is None:
        chunk_bytes = chunk_bytes_default()
    chunk_bytes = int(chunk_bytes)
    if chunk_bytes < 1:
        raise ValueError(f"stream_reshard: chunk_bytes={chunk_bytes}")
    if os.path.abspath(src_dir) == os.path.abspath(dst_dir):
        raise ReshardError(
            "stream_reshard: src and dst are the same directory — the "
            "stream reads source memmaps while writing destination "
            "arrays; in-place resharding is the gather path's job")
    sources = io_mod.serial_var_sources(src_dir)
    validate_reshard_shapes(
        {name: tuple(info["shape"]) for name, info in sources.items()},
        to_plan)
    os.makedirs(dst_dir, exist_ok=True)
    mesh_key = json.dumps(to_plan.get("mesh") or {}, sort_keys=True)
    prog_path = os.path.join(dst_dir, PROGRESS_FILENAME)
    if not resume:
        try:
            os.remove(prog_path)
        except OSError:
            pass
    prog = _load_progress(dst_dir, chunk_bytes, mesh_key)
    copied = skipped = moved_bytes = 0
    for base in sorted(sources):
        info = sources[base]
        shape = tuple(int(d) for d in info["shape"])
        dtype = np.dtype(info["dtype"])
        dst_path = os.path.join(dst_dir, base + ".npy")
        ledger = prog["vars"].setdefault(base, {"done": False,
                                                "chunks": {}})
        if ledger.get("done") and os.path.exists(dst_path):
            head = io_mod._npy_header(dst_path)
            if head == (shape, dtype):
                continue
            ledger.update(done=False, chunks={})
        reuse = (bool(ledger["chunks"]) and os.path.exists(dst_path)
                 and io_mod._npy_header(dst_path) == (shape, dtype))
        if not reuse:
            ledger.update(done=False, chunks={})
        dst = np.lib.format.open_memmap(
            dst_path, mode="r+" if reuse else "w+",
            shape=shape, dtype=dtype)
        try:
            for pn, piece in enumerate(info["pieces"]):
                src = np.load(piece["path"], mmap_mode="r")
                spans = piece["index"]
                if spans is None:
                    spans = tuple((0, d) for d in shape)
                p_shape = tuple(b - a for a, b in spans)
                if spans and tuple(src.shape) != p_shape:
                    raise ReshardError(
                        f"stream_reshard: piece {piece['path']!r} has "
                        f"shape {tuple(src.shape)}, expected {p_shape} "
                        "— the directory mixes saves from different "
                        "runs/layouts")
                off = spans[0][0] if spans else 0
                tail = tuple(slice(a, b) for a, b in spans[1:])
                for a, b in iter_slabs(p_shape or (), dtype.itemsize,
                                       chunk_bytes):
                    cid = f"{pn}:{a}:{b}"
                    if spans:
                        dst_idx = (slice(off + a, off + b),) + tail
                        src_idx = (slice(a, b),)
                    else:  # 0-d
                        dst_idx = src_idx = ()
                    recorded = ledger["chunks"].get(cid)
                    if recorded is not None:
                        have = _crc(np.asarray(dst[dst_idx]))
                        if have != recorded:
                            raise ChunkCorruptError(
                                f"stream_reshard: chunk {base}/{cid} in "
                                f"{dst_path!r} fails digest verification "
                                f"(crc {have} != recorded {recorded}) — "
                                "the interrupted destination rotted; "
                                "delete it and restream")
                        skipped += 1
                        continue
                    # ONE slab materialized: this copy is the whole
                    # peak-memory story (mmap pages on either side are
                    # the OS's, evictable under pressure)
                    slab = np.array(src[src_idx])
                    dst[dst_idx] = slab
                    dst.flush()
                    ledger["chunks"][cid] = _crc(slab)
                    moved_bytes += int(slab.nbytes)
                    # free BEFORE the next slab allocates: holding it
                    # across the loop edge would double the peak to two
                    # chunks (caught by the pinned tracemalloc test)
                    del slab
                    copied += 1
                    _write_atomic(prog_path, prog)
                    if chunk_hook is not None:
                        chunk_hook(base, cid)
                del src
        finally:
            del dst
        ledger["done"] = True
        _write_atomic(prog_path, prog)
    try:
        os.remove(prog_path)
    except OSError:  # pragma: no cover
        pass
    return {"vars": len(sources), "chunks_copied": copied,
            "chunks_skipped": skipped, "bytes_copied": moved_bytes,
            "chunk_bytes": chunk_bytes}
