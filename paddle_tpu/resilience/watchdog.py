"""Step watchdog: bound the wait on a dispatched device step.

The async hot path dispatches step N+1 while N executes and only ever
blocks at a LazyFetch materialization (core/async_fetch.py). If a device
step never settles — a deadlocked collective on a sick slice, a runaway
custom kernel, a wedged transfer over a flaky control plane — that
materialization blocks the trainer FOREVER, with no indication of what
was in flight. With ``PT_STEP_DEADLINE_S`` set, the blocking wait is
delegated to a monitor thread and the caller waits on it with a
deadline; on expiry the caller gets a `StepHungError` carrying the
diagnosis instead of a silent hang:

* which phase is stuck (always ``device`` at this boundary: dispatch
  returned, ``block_until_ready`` never did),
* the in-flight fetch's provenance — (epoch, step, fetch name) as
  annotated by the Trainer,
* the executor's accounted PhaseTimer phases, so "the device stopped
  answering" is distinguishable from "we never dispatched".

XLA offers no way to cancel an enqueued computation, so the hung wait is
abandoned on its daemon thread — the point is a loud, attributable error
the orchestration layer can act on (kill the worker, resume from the
last verified checkpoint) instead of an eternal stall.

The deterministic ``step_hang`` fault site (PT_FAULT_INJECT) simulates a
hung step inside the monitor thread, so the watchdog path is provable in
CI. The site is only reached when a deadline is armed — an injected hang
with no watchdog would hang the suite itself.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Optional

__all__ = ["StepHungError", "deadline", "wait_until_ready", "DEADLINE_ENV"]

DEADLINE_ENV = "PT_STEP_DEADLINE_S"


class StepHungError(RuntimeError):
    """A dispatched step did not settle within PT_STEP_DEADLINE_S."""


def deadline() -> Optional[float]:
    """The armed deadline in seconds, or None (watchdog off). Read at
    every materialization, so it can be armed/disarmed at runtime."""
    raw = os.environ.get(DEADLINE_ENV, "").strip()
    if not raw:
        return None
    try:
        d = float(raw)
    except ValueError as e:
        raise ValueError(f"{DEADLINE_ENV}={raw!r}: not a float") from e
    return d if d > 0 else None


def _dump(provenance, timer, deadline_s: float, spans=None) -> str:
    lines = [
        f"device step did not settle within {deadline_s:g}s "
        f"({DEADLINE_ENV}) — stuck in phase 'device' (dispatch returned, "
        "block_until_ready never did)",
    ]
    if provenance:
        ctx = ", ".join(f"{k}={v!r}" for k, v in sorted(provenance.items()))
        lines.append(f"in-flight fetch: {ctx}")
    if spans:
        # the active span stack (obs/trace.py): which phase/stage/
        # request the caller was inside when the step hung — the
        # structured-trace reading of "where were we"
        chain = " > ".join(
            f"{s['cat']}:{s['name']}"
            + (f"{s['attrs']}" if s.get("attrs") else "")
            for s in spans)
        lines.append(f"active spans (outermost first): {chain}")
    if timer is not None:
        lines.append(f"accounted phases since last reset: {timer.snapshot()}")
    lines.append("the hung wait was abandoned on its monitor thread (XLA "
                 "cannot cancel an enqueued computation); resume from the "
                 "newest verified checkpoint after restarting the worker")
    return "\n".join(lines)


class _Monitor:
    """ONE persistent monitor thread serving all watchdog waits — a
    thread per materialization would put thread spawn/teardown on the
    very hot path the lazy-fetch design keeps sync-free. Waits are
    serviced FIFO (the trainer materializes sequentially; concurrent
    callers share the worker, so a caller's deadline includes any wait
    queued ahead of it). A wait that times out ABANDONS the monitor —
    the stuck thread keeps its hung block_until_ready, and the next
    wait gets a fresh monitor; a late completion of an abandoned item
    only sets an Event nobody is watching."""

    def __init__(self):
        self.requests: "queue.Queue" = queue.Queue()
        self.thread = threading.Thread(target=self._loop, daemon=True,
                                       name="pt-watchdog-monitor")
        self.thread.start()

    def _loop(self):
        import jax
        from . import faults
        while True:
            value, settled, err = self.requests.get()
            try:
                if faults.fire("step_hang") is not None:
                    threading.Event().wait()  # simulated hung device step
                jax.block_until_ready(value)
            except BaseException as e:  # noqa: BLE001 — re-raised by caller
                err.append(e)
            finally:
                settled.set()


_monitor: Optional[_Monitor] = None
_monitor_lock = threading.Lock()


def _submit(value):
    global _monitor
    with _monitor_lock:
        if _monitor is None:
            _monitor = _Monitor()
        mon = _monitor
    settled = threading.Event()
    err: list = []
    mon.requests.put((value, settled, err))
    return mon, settled, err


def _abandon(mon: "_Monitor") -> None:
    global _monitor
    with _monitor_lock:
        if _monitor is mon:
            _monitor = None


def wait_until_ready(value, deadline_s: Optional[float] = None,
                     provenance: Optional[dict] = None, timer=None):
    """block_until_ready(value) under the armed deadline.

    With no deadline (PT_STEP_DEADLINE_S unset and deadline_s None) this
    is a plain blocking wait. Otherwise the wait is delegated to the
    persistent monitor thread; if it does not settle in time,
    StepHungError carries the phase dump + provenance and the stuck
    monitor is abandoned. Exceptions from the wait itself (deferred
    device errors) propagate unchanged."""
    import jax

    d = deadline_s if deadline_s is not None else deadline()
    if d is None:
        jax.block_until_ready(value)
        return value

    mon, settled, err = _submit(value)
    if not settled.wait(d):
        _abandon(mon)
        from ..obs import trace as obs_trace
        raise StepHungError(_dump(provenance, timer, d,
                                  spans=obs_trace.active_stack()))
    if err:
        raise err[0]
    return value
