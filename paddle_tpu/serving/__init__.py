"""paddle_tpu.serving — batched, multi-model online inference.

The deployment story up to now ran ONE request at a time against ONE
model (`io.load_serving_model`, the C API in serving_embed): correct,
but on an accelerator it leaves most of every dispatch idle — XLA
executables cost per dispatch, not per example. This subsystem is the
throughput-oriented online layer over the same AOT `jax.export`
artifacts (≙ the role of the reference's PaddlePredictor::Run, rebuilt
around coalescing):

    ServingEngine                 the facade: config + registry + metrics
      ├── registry.ModelRegistry  named, versioned models; warmup-on-load;
      │     ModelVersion          atomic drain-based hot reload
      ├── batcher.MicroBatcher    bounded queue + dispatcher thread:
      │                           coalesce -> bucket-pad -> run -> scatter
      ├── admission               typed Overloaded/DeadlineExceeded errors,
      │                           reject-fast load shedding
      ├── metrics                 QPS, batch-fill, queue depth, phase
      │                           latency percentiles (snapshot-able)
      └── http                    stdlib ThreadingHTTPServer front end

Both front ends — HTTP (serving/http.py) and the embedded C API
(serving_embed.py) — reach the SAME engine, so batching, admission, and
metrics behave identically regardless of how a request arrives.

Engine-wide knobs (constructor args win; PT_SERVE_* env knobs supply
deployment defaults; declared in paddle_tpu/flags.py):

    PT_SERVE_MAX_BATCH     micro-batch bound (default: artifact batch)
    PT_SERVE_MAX_WAIT_MS   batch close deadline, ms (default 2)
    PT_SERVE_QUEUE_DEPTH   bounded queue per model (default 256)
    PT_SERVE_DEADLINE_MS   default per-request deadline, 0 = none

See docs/serving.md for architecture and tuning guidance.
"""

from __future__ import annotations

from typing import Dict, Optional

from .admission import (AdmissionController, DeadlineExceeded,
                        InvalidRequest, ModelUnavailable, Overloaded,
                        RequestFailed, ServingError, retryable)
from .batcher import (DEFAULT_MAX_WAIT_MS, MicroBatcher, env_float,
                      env_int)
from .metrics import ServingMetrics
from .registry import ModelRegistry, ModelVersion

__all__ = ["ServingEngine", "ServingError", "Overloaded",
           "DeadlineExceeded", "ModelUnavailable", "InvalidRequest",
           "RequestFailed", "retryable", "MicroBatcher", "ModelRegistry",
           "ModelVersion", "AdmissionController", "ServingMetrics"]


class ServingEngine:
    """In-process multi-model serving engine.

    >>> engine = ServingEngine()
    >>> engine.load_model("ranker", "/models/ranker_v7")
    >>> out = engine.predict("ranker", {"x": example})       # blocking
    >>> fut = engine.submit("ranker", {"x": example})        # async
    >>> engine.load_model("ranker", "/models/ranker_v8")     # hot reload
    >>> engine.metrics_snapshot()["models"]["ranker"]["qps"]
    """

    def __init__(self, max_batch_size: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None):
        self.max_batch_size = max_batch_size  # None = per-model artifact
        self.max_wait_ms = (env_float("PT_SERVE_MAX_WAIT_MS",
                                      DEFAULT_MAX_WAIT_MS)
                            if max_wait_ms is None else float(max_wait_ms))
        self.queue_depth = (env_int("PT_SERVE_QUEUE_DEPTH", 256)
                            if queue_depth is None else int(queue_depth))
        self.deadline_ms = (env_float("PT_SERVE_DEADLINE_MS", 0.0)
                            if deadline_ms is None else float(deadline_ms))
        self.metrics = ServingMetrics()
        self.registry = ModelRegistry(self._make_batcher)
        self._decode: Dict[str, object] = {}
        self._closed = False

    # -- wiring --------------------------------------------------------------
    def _make_batcher(self, name: str, model: ModelVersion) -> MicroBatcher:
        max_batch = self.max_batch_size
        if max_batch is None:
            max_batch = env_int("PT_SERVE_MAX_BATCH", model.batch_size)
        admission = AdmissionController(
            queue_depth=self.queue_depth,
            max_batch_size=min(max_batch, model.batch_size),
            default_deadline_ms=self.deadline_ms)
        return MicroBatcher(model, max_batch_size=max_batch,
                            max_wait_ms=self.max_wait_ms,
                            admission=admission,
                            metrics=self.metrics.model(name), name=name)

    # -- model lifecycle -----------------------------------------------------
    def load_model(self, name: str, model_dir: str,
                   version: Optional[int] = None,
                   warmup: bool = True) -> int:
        """Load `name` from a serving artifact dir; if `name` is already
        serving, this is an atomic hot reload (new version warmed before
        the swap, old version drained after). Returns the version id."""
        if self._closed:
            raise ModelUnavailable("engine is shut down")
        ver = self.registry.load(name, model_dir, version, warmup=warmup)
        if ver > 1:
            self.metrics.model(name).on_reload()
        return ver

    def load_model_object(self, name: str, model,
                          version: Optional[int] = None) -> int:
        """Serve an in-memory model object (batch_size / bucket_of /
        execute_batch surface) behind the full batcher + admission +
        metrics stack — the synthetic-replica hook the fleet tier's
        bench and tests load replicas with. Same swap semantics as
        load_model."""
        if self._closed:
            raise ModelUnavailable("engine is shut down")
        ver = self.registry.load_object(name, model, version)
        if ver > 1:
            self.metrics.model(name).on_reload()
        return ver

    def unload_model(self, name: str) -> None:
        self.registry.unload(name)

    def models(self) -> Dict[str, dict]:
        out = self.registry.describe()
        for name, eng in list(self._decode.items()):
            out[name] = dict(out.get(name, {}), decode=eng.describe())
        return out

    # -- the generation plane (paged KV + continuous batching) ---------------
    def load_decode_model(self, name: str, model_dir: str,
                          warmup: bool = True, **opts) -> dict:
        """Load (or hot-swap) a decode bundle (io.export_decode_model)
        under `name`. The new engine is built and warmed off to the
        side, the routing pointer swaps, then the old engine drains —
        the reload contract of the one-shot plane, kept. opts pass
        through to DecodeEngine (queue_depth, deadline_ms,
        max_new_tokens, continuous)."""
        if self._closed:
            raise ModelUnavailable("engine is shut down")
        from .decode import DecodeEngine
        eng = DecodeEngine(model_dir, name=name, warmup=warmup,
                           metrics=self.metrics.decode(name), **opts)
        old = self._decode.get(name)
        self._decode[name] = eng
        if old is not None:
            old.shutdown(drain=True)
        return eng.describe()

    def unload_decode_model(self, name: str) -> None:
        eng = self._decode.pop(name, None)
        if eng is not None:
            eng.shutdown(drain=True)

    def decode_engine(self, name: str):
        eng = self._decode.get(name)
        if eng is None:
            raise ModelUnavailable(
                f"no decode model named {name!r} is loaded")
        return eng

    def decode_engines(self) -> Dict[str, object]:
        """Snapshot of the loaded decode engines (name -> DecodeEngine).
        The fleet tier reads shared-KV residency and speculative
        acceptance off these for replica health."""
        return dict(self._decode)

    def generate(self, name: str, prompt_ids, **kw):
        """Admit one generation request; returns a GenerationHandle
        (stream() for live tokens, result() for the final dict). Typed
        admission errors raise here, reject-fast."""
        if self._closed:
            raise ModelUnavailable("engine is shut down")
        return self.decode_engine(name).generate(prompt_ids, **kw)

    # -- the request path ----------------------------------------------------
    def submit(self, name: str, feeds: Dict,
               deadline_ms: Optional[float] = None):
        """Async: admit + enqueue one example; returns a Future whose
        result is {fetch_name: np.ndarray}. Typed admission errors raise
        HERE (reject-fast), execution errors surface on the Future."""
        if self._closed:
            raise ModelUnavailable("engine is shut down")
        entry = self.registry.get(name)
        while True:
            try:
                return entry.batcher.submit(feeds, deadline_ms=deadline_ms)
            except ModelUnavailable:
                # raced a hot reload: the version we routed to closed
                # between registry.get() and submit(). A reload swaps the
                # routing pointer BEFORE draining the old batcher, so if
                # the name now routes to a different version, retry there
                # — the zero-drop contract covers this window too. A
                # truly unloaded name re-raises (from get(), or because
                # the routed entry is the one that just refused us).
                nxt = self.registry.get(name)
                if nxt is entry:
                    raise
                entry = nxt

    def predict(self, name: str, feeds: Dict,
                deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> Dict:
        """Blocking single-request convenience over submit()."""
        fut = self.submit(name, feeds, deadline_ms=deadline_ms)
        if timeout is None and deadline_ms:
            timeout = deadline_ms / 1000.0 + 30.0   # deadline + margin
        return fut.result(timeout=timeout)

    # -- observability / shutdown -------------------------------------------
    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def shutdown(self, drain: bool = True) -> None:
        """Stop all batchers + decode engines. drain=True serves the
        backlog first."""
        self._closed = True
        self.registry.close(drain=drain)
        for eng in list(self._decode.values()):
            eng.shutdown(drain=drain)
        self._decode.clear()
