"""Admission control for the online serving engine: typed errors +
reject-fast load shedding.

An overloaded serving system has exactly two honest answers: serve within
the deadline, or say NO immediately. Queuing a request it cannot serve in
time converts a cheap rejection (client retries elsewhere) into an
expensive timeout (client waited, capacity was burned padding and running
a batch whose result nobody reads). So admission is checked at SUBMIT
time against the queue bound AND the request's deadline — using a
decaying estimate of batch service time, so a deadline the queue ahead of
the request would already blow is rejected before it enqueues.

Error taxonomy (the typed surface every front end maps from — HTTP
status codes in serving/http.py, C-API error strings in serving_embed):

    Overloaded        queue at capacity — RETRYABLE (another replica, or
                      the same one after backoff, may accept)
    DeadlineExceeded  the request cannot / did not make its deadline —
                      not retryable as-is (a retry restarts the deadline;
                      that is the CLIENT's decision, not the layer's)
    ModelUnavailable  unknown model name, or the engine is shut down
    InvalidRequest    feed names / shapes / dtypes don't fit the model
                      (no bucket can hold it)
    RequestFailed     the dispatcher crashed while running the batch;
                      carries the original error as __cause__

`retryable(exc)` is the RetryPolicy-convention predicate (resilience/
retry.py): ``RetryPolicy(retry_on=serving.retryable)`` gives a client
bounded backoff on Overloaded without ever retrying a rejection that
would deterministically repeat.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

__all__ = ["ServingError", "Overloaded", "DeadlineExceeded",
           "ModelUnavailable", "InvalidRequest", "RequestFailed",
           "retryable", "AdmissionController"]


class ServingError(RuntimeError):
    """Base of the serving engine's typed errors."""
    retryable = False
    http_status = 500


class Overloaded(ServingError):
    """Queue at capacity — rejected fast, worth retrying after backoff.

    When the fleet tier sheds under overload (serving/fleet/), the
    error carries WHICH priority class paid: `shed_class` is the class
    of the request that was shed (strictly the lowest class present —
    free tier absorbs overload before paid tier). None on single-engine
    queue-bound rejections, which predate classes."""
    retryable = True
    http_status = 429

    def __init__(self, message: str, shed_class: Optional[int] = None):
        super().__init__(message)
        self.shed_class = shed_class


class DeadlineExceeded(ServingError):
    """The request's deadline passed (or provably would) before service."""
    http_status = 504


class ModelUnavailable(ServingError):
    """No such model, or the engine/batcher is shut down."""
    http_status = 404


class InvalidRequest(ServingError):
    """Feed names/shapes/dtypes don't fit any bucket of the model."""
    http_status = 400


class RequestFailed(ServingError):
    """The dispatcher failed while executing this request's batch; the
    original error is chained as __cause__ (never swallowed)."""

    def __init__(self, message: str, cause: Optional[BaseException] = None):
        super().__init__(message)
        if cause is not None:
            self.__cause__ = cause


def retryable(exc: BaseException) -> bool:
    """RetryPolicy(retry_on=...) predicate: retry only errors a later
    attempt can plausibly outrun (today: Overloaded)."""
    return bool(getattr(exc, "retryable", False))


class AdmissionController:
    """Bounded queue depth + deadline-aware shedding.

    `observe_batch` feeds an exponentially-decayed estimate of batch
    service seconds; `admit` uses it to estimate how long the queue ahead
    of a new request will take (`ceil(queued / max_batch) * est`) and
    rejects a deadline that estimate already blows. The estimate starts
    at None (no shedding-by-estimate until the first real batch) so a
    cold engine never rejects on a guess.
    """

    def __init__(self, queue_depth: int, max_batch_size: int,
                 default_deadline_ms: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self.queue_depth = queue_depth
        self.max_batch_size = max(1, int(max_batch_size))
        self.default_deadline_ms = float(default_deadline_ms)
        self.clock = clock
        self._lock = threading.Lock()
        self._batch_s: Optional[float] = None  # EWMA of batch service time

    # -- deadlines -----------------------------------------------------------
    def deadline_for(self, deadline_ms: Optional[float]) -> Optional[float]:
        """Absolute monotonic deadline for a request, or None. Falls back
        to the engine-wide default (PT_SERVE_DEADLINE_MS; 0 = none)."""
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if not deadline_ms or deadline_ms <= 0:
            return None
        return self.clock() + deadline_ms / 1000.0

    # -- service-time estimate ----------------------------------------------
    def observe_batch(self, seconds: float) -> None:
        with self._lock:
            if self._batch_s is None:
                self._batch_s = seconds
            else:
                self._batch_s = 0.8 * self._batch_s + 0.2 * seconds

    def estimated_batch_s(self) -> Optional[float]:
        with self._lock:
            return self._batch_s

    # -- the admission decision ---------------------------------------------
    def admit(self, queued: int, deadline_t: Optional[float],
              model: str = "") -> None:
        """Raise Overloaded / DeadlineExceeded instead of enqueuing a
        request that cannot be served; return silently to admit."""
        if queued >= self.queue_depth:
            raise Overloaded(
                f"serving queue for {model!r} at capacity "
                f"({queued}/{self.queue_depth} queued)")
        if deadline_t is None:
            return
        now = self.clock()
        if now >= deadline_t:
            raise DeadlineExceeded(
                f"request deadline already expired at admission "
                f"(model {model!r})")
        est = self.estimated_batch_s()
        if est is not None and queued > 0:
            # batches ahead of this request, pessimistically one more for
            # the batch it will ride in
            batches_ahead = -(-queued // self.max_batch_size) + 1
            if now + batches_ahead * est > deadline_t:
                raise DeadlineExceeded(
                    f"deadline-aware shed: ~{batches_ahead} batches x "
                    f"{est * 1000:.1f} ms queued ahead exceed the "
                    f"{(deadline_t - now) * 1000:.1f} ms budget "
                    f"(model {model!r})")
