"""Dynamic micro-batcher: a bounded request queue drained by one
dispatcher thread per model version.

TPUs (and XLA executables generally) pay per DISPATCH, not per example:
a batch-8 bucket costs nearly the same wall time at fill 1 as at fill 8.
The micro-batcher turns independent online requests into full batches by
waiting — but only a little: a bucket's pending group is flushed the
moment it holds `max_batch_size` requests, or when its OLDEST request
has waited `max_wait_ms`, whichever comes first. Latency is therefore
bounded by max_wait_ms + one batch service time, and throughput
approaches batch_size x the sequential rate under load (the bench
`serving` config measures exactly this ratio).

Shape buckets: requests are grouped by the bucket key the model derives
from their variable-length dims (reader/bucketing.py's bucket_bound over
the artifact's exported bounds), so requests of different padded shapes
never share a batch and each bucket replays one pre-compiled executable.

Failure containment: the dispatcher loop is wrapped per-batch — a crash
inside execution (including the `serve_dispatch` chaos site,
resilience/faults.py) fails THAT batch's futures with a typed
RequestFailed carrying the original error, and the loop keeps serving.
An engine thread dying silently would turn every later request into a
hang; this one cannot.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional

from ..obs import trace as obs_trace
from ..resilience import faults
from .admission import (AdmissionController, DeadlineExceeded,
                        ModelUnavailable, RequestFailed)
from .metrics import ModelMetrics

__all__ = ["Request", "MicroBatcher", "DEFAULT_MAX_WAIT_MS",
           "env_float", "env_int"]

#: PT_SERVE_MAX_WAIT_MS fallback — the single source for both a
#: standalone MicroBatcher and a ServingEngine-built one
DEFAULT_MAX_WAIT_MS = 2.0


def env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    try:
        return int(v) if v not in (None, "") else default
    except ValueError:
        return default


class Request:
    """One queued example: feeds + deadline + the Future its caller
    holds. Timing fields feed the queue-phase latency metric.

    With tracing armed (PT_TRACE), each request is minted an id at
    submission and captures the submitter's span context (the HTTP
    ingress span, for front-end traffic) — the dispatcher thread
    parents the request's queue/batch spans under it, so one request's
    queue -> pad -> device -> scatter lifeline reads as one trace."""

    __slots__ = ("feeds", "bucket", "future", "deadline_t", "t_enqueue",
                 "rid", "ctx")

    def __init__(self, feeds, bucket, deadline_t: Optional[float]):
        self.feeds = feeds
        self.bucket = bucket
        self.future: Future = Future()
        self.deadline_t = deadline_t
        self.t_enqueue = time.monotonic()
        if obs_trace.enabled():
            self.rid = obs_trace.new_id()
            self.ctx = obs_trace.current_context()
        else:
            self.rid = None
            self.ctx = None


class MicroBatcher:
    """One model version's queue + dispatcher thread.

    model: an object with `batch_size`, `bucket_of(feeds)`, and
    `execute_batch(bucket, examples, timer=)` (registry.ModelVersion, or
    a stub in unit tests). Close with drain=True to serve every queued
    request before the thread exits (the hot-reload contract)."""

    def __init__(self, model, *, max_batch_size: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 admission: Optional[AdmissionController] = None,
                 metrics: Optional[ModelMetrics] = None,
                 name: str = "model"):
        self.model = model
        self.name = name
        self.max_batch_size = int(max_batch_size or model.batch_size)
        if self.max_batch_size > model.batch_size:
            # the artifact is shape-locked at its exported batch; a
            # larger micro-batch could never run in one dispatch
            self.max_batch_size = model.batch_size
        self.max_wait_ms = (
            env_float("PT_SERVE_MAX_WAIT_MS", DEFAULT_MAX_WAIT_MS)
            if max_wait_ms is None else float(max_wait_ms))
        self.admission = admission or AdmissionController(
            queue_depth=256, max_batch_size=self.max_batch_size)
        self.metrics = metrics or ModelMetrics(name)
        self._cv = threading.Condition()
        self._queue: deque = deque()
        #: dispatcher-owned: bucket key -> [Request] accumulating a batch
        self._pending: Dict[object, List[Request]] = {}
        self._closed = False
        self._drained = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"pt-serve[{name}]")
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def queued(self) -> int:
        with self._cv:
            return len(self._queue) + sum(len(v) for v in
                                          self._pending.values())

    def service_estimate_s(self) -> Optional[float]:
        """The admission EWMA of batch service seconds (None until the
        first real batch) — the fleet tier's per-replica health signal
        (fleet/pool.py), read from the one estimate deadline shedding
        already maintains rather than a second bookkeeping path."""
        return self.admission.estimated_batch_s()

    def submit(self, feeds, deadline_ms: Optional[float] = None) -> Future:
        """Admit + enqueue one example; returns its Future. Raises the
        typed admission errors (Overloaded / DeadlineExceeded /
        InvalidRequest / ModelUnavailable) instead of queueing a request
        that cannot be served."""
        bucket = self.model.bucket_of(feeds)   # InvalidRequest on misfit
        deadline_t = self.admission.deadline_for(deadline_ms)
        with self._cv:
            if self._closed:
                raise ModelUnavailable(
                    f"model {self.name!r} is draining/unloaded")
            queued = len(self._queue) + sum(len(v) for v in
                                            self._pending.values())
            try:
                self.admission.admit(queued, deadline_t, model=self.name)
            except DeadlineExceeded:
                self.metrics.on_shed("deadline")
                raise
            except Exception:
                self.metrics.on_shed("overload")
                raise
            req = Request(feeds, bucket, deadline_t)
            self._queue.append(req)
            self.metrics.on_received(queued + 1)
            self._cv.notify()
        return req.future

    def close(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop accepting requests. drain=True serves everything already
        queued (hot reload / graceful shutdown); drain=False fails the
        backlog fast with ModelUnavailable."""
        with self._cv:
            self._closed = True
            if not drain:
                backlog = list(self._queue)
                self._queue.clear()
                for g in self._pending.values():
                    backlog.extend(g)
                self._pending.clear()
                for r in backlog:
                    if not r.future.done():
                        r.future.set_exception(ModelUnavailable(
                            f"model {self.name!r} unloaded before "
                            "dispatch"))
            self._cv.notify()
        self._drained.wait(timeout)
        self._thread.join(timeout)

    # -- dispatcher side -----------------------------------------------------
    def _flush_due(self, now: float) -> List:
        """Pop the batches that must run NOW: full chunks of
        max_batch_size (a group can outgrow the bound while the
        dispatcher was busy — each chunk is its own dispatch), groups
        whose oldest request aged past max_wait_ms, everything on
        close."""
        due = []
        max_wait = self.max_wait_ms / 1000.0
        for key in list(self._pending):
            group = self._pending[key]
            while len(group) >= self.max_batch_size:
                due.append((key, group[:self.max_batch_size]))
                group = group[self.max_batch_size:]
            if group and (self._closed
                          or now - group[0].t_enqueue >= max_wait):
                due.append((key, group))
                group = []
            if group:
                self._pending[key] = group
            else:
                self._pending.pop(key)
        return due

    def _next_deadline(self) -> Optional[float]:
        """Monotonic time of the earliest pending flush, else None."""
        if not self._pending:
            return None
        oldest = min(g[0].t_enqueue for g in self._pending.values() if g)
        return oldest + self.max_wait_ms / 1000.0

    def _loop(self) -> None:
        try:
            while True:
                with self._cv:
                    while True:
                        while self._queue:
                            r = self._queue.popleft()
                            self._pending.setdefault(r.bucket,
                                                     []).append(r)
                        now = time.monotonic()
                        due = self._flush_due(now)
                        if due:
                            break
                        if self._closed and not self._pending:
                            return
                        nxt = self._next_deadline()
                        self._cv.wait(None if nxt is None
                                      else max(nxt - now, 0.0))
                for _key, group in due:
                    self._run_batch(_key, group)
        finally:
            self._drained.set()

    def _run_batch(self, bucket, group: List[Request]) -> None:
        now = time.monotonic()
        live: List[Request] = []
        for r in group:
            if r.deadline_t is not None and now >= r.deadline_t:
                # expired while queued: shed instead of burning a batch
                # slot on a result nobody is waiting for anymore
                self.metrics.on_shed("deadline")
                if not r.future.done():
                    r.future.set_exception(DeadlineExceeded(
                        f"request spent {(now - r.t_enqueue) * 1000:.1f} "
                        "ms queued, past its deadline"))
            else:
                live.append(r)
        if not live:
            return
        queue_s = [now - r.t_enqueue for r in live]
        if obs_trace.enabled():
            # per-request queue spans, parented under each submitter's
            # context (the HTTP ingress span) — the measured wait ended
            # now, so the span is emitted with its known duration
            for r, qs in zip(live, queue_s):
                obs_trace.complete("queue", qs, cat="serve",
                                   parent=r.ctx, model=self.name,
                                   rid=r.rid)
        self.metrics.on_batch(len(live), self.max_batch_size)
        # the batch span parents the pad/device/scatter phase spans the
        # model's timer emits; a single-request batch adopts THAT
        # request's trace (the common online case — one request, one
        # causal timeline end to end), a coalesced batch records every
        # rid it serves
        batch_span = obs_trace.span(
            "batch", cat="serve",
            parent=(live[0].ctx if len(live) == 1 else None),
            model=self.name, n=len(live),
            rids=[r.rid for r in live])
        t0 = time.monotonic()
        try:
            with batch_span:
                faults.crash_point("serve_dispatch")
                results, phase_s = self.model.execute_batch(
                    bucket, [r.feeds for r in live],
                    timer=self.metrics.timer)
        except BaseException as e:  # noqa: BLE001 — typed + re-delivered
            batch_s = time.monotonic() - t0
            self.admission.observe_batch(batch_s)
            depth = self.queued()
            for r in live:
                if not r.future.done():
                    r.future.set_exception(RequestFailed(
                        f"dispatcher failed running a batch of "
                        f"{len(live)} on model {self.name!r}: {e}",
                        cause=e))
                self.metrics.on_done(False, depth)
            return  # the loop keeps serving: one bad batch != a dead engine
        batch_s = time.monotonic() - t0
        self.admission.observe_batch(batch_s)
        self.metrics.timer.count_run()
        done_t = time.monotonic()
        depth = self.queued()
        for r, res, qs in zip(live, results, queue_s):
            if not r.future.done():
                r.future.set_result(res)
            self.metrics.on_done(
                True, depth,
                phase_s=dict(phase_s, queue=qs),
                total_s=done_t - r.t_enqueue)
