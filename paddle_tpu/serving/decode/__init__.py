"""paddle_tpu.serving.decode — autoregressive generation over the
serving engine: paged KV cache + continuous batching.

PR 5's micro-batcher coalesces fixed-shape one-shot requests — right for
classifiers, wrong for LLM decode, where every sequence wants hundreds
of dependent single-token dispatches and sequences finish at different
times. This subsystem is the decode-shaped counterpart, layered on the
same artifact plane:

    DecodeEngine            facade: admission + scheduler + metrics
      ├── DecodeModel       the two-artifact bundle
      │                     (io.export_decode_model): length-bucketed
      │                     PREFILL artifacts served through the PR-5
      │                     ModelVersion, plus ONE fixed-shape
      │                     DECODE-STEP artifact whose KV pools thread
      │                     device-resident from fetch to feed
      ├── DecodeScheduler   continuous batching: admit into free slots
      │                     of the in-flight batch (no drain barrier),
      │                     evict lowest-priority under pool pressure,
      │                     deadline-aware shedding by remaining-token
      │                     estimate (typed Overloaded /
      │                     DeadlineExceeded)
      ├── KVBlockPool       host accounting for the paged device pool:
      │                     fixed-size blocks, per-sequence block
      │                     tables, per-block refcounts,
      │                     alloc/share/free/defrag
      ├── PrefixIndex       KV economics half 1 (prefix.py): hash of
      │                     token prefixes at block granularity; prompts
      │                     sharing a resident prefix ALIAS its blocks
      │                     (one copy backs N sessions), copy-on-write
      │                     keeps shared blocks immutable
      └── drafters          KV economics half 2 (spec.py): speculative
                            decoding — a drafter proposes k tokens, the
                            SAME fixed-shape step verifies the chain
                            through idle slots, greedy acceptance stays
                            token-identical to plain decode

Correctness contract (tested): continuous-batched paged decode is
token-identical to a sequential per-sequence reference decode under
greedy sampling — including sequences admitted mid-flight, sequences
evicted then resumed, sequences aliasing a shared prefix, and
speculative steps under any drafter.

Env knobs (export-time geometry + runtime budget; declared in
paddle_tpu/flags.py):

    PT_DECODE_BLOCK_SIZE      tokens per KV block (export default 16)
    PT_DECODE_POOL_BLOCKS     pool blocks incl. the null block (64)
    PT_DECODE_MAX_SLOTS       decode-step slot count (8)
    PT_DECODE_MAX_NEW_TOKENS  default generation budget (64)
    PT_KV_SHARE               1 = copy-on-write prefix sharing (off)
    PT_SPEC_DRAFT             drafter: ngram | self | <bundle dir> (off)
    PT_SPEC_K                 drafted tokens per speculative step (4)
"""

from __future__ import annotations

from .engine import DecodeEngine, DecodeModel
from .kv_cache import KVBlockPool, PoolExhausted, blocks_for_tokens
from .prefix import PrefixIndex
from .scheduler import DecodeScheduler, GenerationHandle, Sequence
from .spec import NGramDrafter, PrefillDrafter, accept_greedy

__all__ = ["DecodeEngine", "DecodeModel", "DecodeScheduler",
           "GenerationHandle", "Sequence", "KVBlockPool", "PoolExhausted",
           "blocks_for_tokens", "PrefixIndex", "NGramDrafter",
           "PrefillDrafter", "accept_greedy"]
