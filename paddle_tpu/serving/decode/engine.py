"""DecodeEngine: the generation facade over one exported decode bundle.

DecodeModel owns the device side — the deserialized prefill buckets
(served through the PR-5 ModelVersion: same bucket selection, padding,
scatter) and the single decode-step executable, plus the device-resident
KV pools that thread from one step's fetches into the next step's feeds
(they never round-trip through host numpy). DecodeScheduler owns the
host side — slots, block accounting, admission, eviction. DecodeEngine
wires them and is what ServingEngine.load_decode_model constructs.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..admission import AdmissionController, InvalidRequest, Overloaded
from ..batcher import env_float, env_int
from ..metrics import DecodeMetrics
from ..registry import ModelVersion
from .kv_cache import (KVBlockPool, blocks_for_tokens, write_prefill_pages)
from .prefix import PrefixIndex
from .scheduler import DecodeScheduler, GenerationHandle
from .spec import resolve_drafter

__all__ = ["DecodeModel", "DecodeEngine"]


class DecodeModel:
    """One loaded decode bundle (io.export_decode_model artifact dir)."""

    def __init__(self, model_dir: str, *, warmup: bool = True):
        import jax.numpy as jnp
        from ...core.compat import jax_export

        with open(os.path.join(model_dir, "serving.json")) as f:
            meta = json.load(f)
        dec = meta.get("decode")
        if not dec:
            raise ValueError(
                f"{model_dir} has no decode section in serving.json — "
                "export with io.export_decode_model, not "
                "export_serving_model")
        self.model_dir = model_dir
        self.prefill_model = ModelVersion.load(model_dir, version=1,
                                               warmup=warmup)
        with open(os.path.join(model_dir, dec["file"]), "rb") as f:
            self._decode_call = jax_export().deserialize(
                bytearray(f.read())).call
        self.slots = int(dec["slots"])
        self.block_size = int(dec["block_size"])
        self.pool_blocks = int(dec["pool_blocks"])
        self.max_blocks_per_seq = int(dec["max_blocks_per_seq"])
        self.max_context = int(dec["max_context"])
        self.n_layers = int(dec["n_layers"])
        self.vocab_size = int(dec["vocab_size"])
        self.eos_id = dec.get("eos_id")
        self.max_prompt_len = self.prefill_model.bounds[-1]
        self._feed_meta = dec["feeds"]
        roles = dec["prefill_roles"]
        self._logits_role = roles["logits"]
        self._kv_roles = [tuple(p) for p in roles["kv"]]
        self._pool_dtype = jnp.float32
        self.reset_pools()
        if warmup:
            self._warmup_decode()

    # -- device pools --------------------------------------------------------
    def reset_pools(self) -> None:
        import jax.numpy as jnp
        shape = tuple(self._feed_meta[3]["shape"])
        self._pools: List = [jnp.zeros(shape, self._pool_dtype)
                             for _ in range(2 * self.n_layers)]

    def _warmup_decode(self) -> None:
        """One all-inactive step so the executable is compiled (or pulled
        from the persistent cache) before the first real sequence."""
        pools = self._pools
        self.decode_step(np.zeros(self.slots, np.int64),
                         np.zeros(self.slots, np.int32),
                         np.zeros((self.slots, self.max_blocks_per_seq),
                                  np.int32))
        self._pools = pools   # discard the warmup writes

    # -- prefill -------------------------------------------------------------
    def prefill(self, token_ids: Sequence[int]):
        """Run the prompt (or a resumed prompt+generated prefix) through
        its length bucket. Returns (last-position logits [vocab],
        [(k_rows, v_rows)] per layer at the TRUE length)."""
        n = len(token_ids)
        dt = self.prefill_model.feed_dtypes()["src_ids"]
        ex = {"src_ids": np.asarray(token_ids, dtype=dt)}
        bucket = self.prefill_model.bucket_of(ex)
        results, _ = self.prefill_model.execute_batch(bucket, [ex])
        out = results[0]
        logits = out[self._logits_role][n - 1]
        kv = [(out[k][:n], out[v][:n]) for k, v in self._kv_roles]
        return logits, kv

    def seed_sequence(self, block_ids: Sequence[int], kv_rows,
                      skip_rows: int = 0) -> None:
        """Write one sequence's prefill K/V rows into its blocks.
        `skip_rows` rows at the front are already resident (aliased
        shared-prefix blocks, kv_cache.py refcounts) and MUST NOT be
        rewritten — only the tail past the shared prefix is written,
        into the tail blocks. A non-block-aligned skip means the whole
        prompt was matched (partial-tail alias), so nothing is written
        at all."""
        skip = int(skip_rows)
        nb = skip // self.block_size
        for i, (k_rows, v_rows) in enumerate(kv_rows):
            if k_rows.shape[0] <= skip:
                continue   # fully aliased: every row already resident
            if skip % self.block_size:
                raise ValueError(
                    f"skip_rows {skip} neither block-aligned nor the "
                    f"full prefill ({k_rows.shape[0]} rows)")
            self._pools[2 * i] = write_prefill_pages(
                self._pools[2 * i], block_ids[nb:], k_rows[skip:],
                self.block_size)
            self._pools[2 * i + 1] = write_prefill_pages(
                self._pools[2 * i + 1], block_ids[nb:], v_rows[skip:],
                self.block_size)

    # -- the decode step -----------------------------------------------------
    def decode_step(self, token_ids: np.ndarray, context_lens: np.ndarray,
                    block_tables: np.ndarray) -> np.ndarray:
        """One fixed-shape step over all slots; updates the resident
        pools from the step's fetches and returns logits [slots, vocab]."""
        metas = self._feed_meta
        feeds = [np.asarray(token_ids, dtype=np.dtype(metas[0]["dtype"])),
                 np.asarray(context_lens,
                            dtype=np.dtype(metas[1]["dtype"])),
                 np.asarray(block_tables,
                            dtype=np.dtype(metas[2]["dtype"]))]
        feeds.extend(self._pools)
        outs = self._decode_call(*feeds)
        if isinstance(outs, dict):
            outs = list(outs.values())
        elif not isinstance(outs, (list, tuple)):
            outs = [outs]
        # pools stay device-resident: the fetched arrays become the next
        # step's feeds without a host materialization
        self._pools = list(outs[1:])
        return np.asarray(outs[0])

    def permute_blocks(self, mapping: Dict[int, int]) -> None:
        """Apply a kv_cache defrag mapping to the device pools: block
        old -> new for every moved block."""
        if not mapping:
            return
        import jax.numpy as jnp
        src = jnp.asarray(list(mapping.keys()), dtype=jnp.int32)
        dst = jnp.asarray(list(mapping.values()), dtype=jnp.int32)
        self._pools = [p.at[dst].set(p[src]) for p in self._pools]

    def copy_block(self, src: int, dst: int) -> None:
        """Device-copy one pool block (every layer, K and V) — the
        copy-on-write primitive: a sequence about to write into a
        shared block gets its own copy first."""
        self._pools = [p.at[dst].set(p[src]) for p in self._pools]

    def describe(self) -> dict:
        return {
            "model_dir": self.model_dir,
            "slots": self.slots, "block_size": self.block_size,
            "pool_blocks": self.pool_blocks,
            "max_context": self.max_context,
            "max_prompt_len": self.max_prompt_len,
            "prefill_buckets": self.prefill_model.bounds,
            "n_layers": self.n_layers, "vocab_size": self.vocab_size,
            "eos_id": self.eos_id,
        }


class DecodeEngine:
    """Continuous-batching generation over one decode bundle.

    >>> eng = DecodeEngine("/models/lm_decode")
    >>> h = eng.generate([5, 17, 9], max_new_tokens=32)
    >>> for tok in h.stream(): ...
    >>> h.result()["tokens"]

    Knobs (constructor args win; env supplies deployment defaults):
    PT_DECODE_MAX_NEW_TOKENS (default generation budget),
    PT_SERVE_QUEUE_DEPTH / PT_SERVE_DEADLINE_MS (admission — shared with
    the one-shot engine on purpose: one admission policy per process),
    PT_KV_SHARE (copy-on-write prefix sharing, decode/prefix.py),
    PT_SPEC_DRAFT / PT_SPEC_K (speculative decoding, decode/spec.py).
    """

    def __init__(self, model_dir: Optional[str] = None, *,
                 model: Optional[DecodeModel] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 max_new_tokens: Optional[int] = None,
                 continuous: bool = True,
                 pool_blocks: Optional[int] = None,
                 metrics: Optional[DecodeMetrics] = None,
                 kv_share: Optional[bool] = None,
                 drafter: Optional[str] = None,
                 spec_k: Optional[int] = None,
                 name: str = "model", warmup: bool = True):
        if model is None:
            if model_dir is None:
                raise ValueError("DecodeEngine needs model_dir or model")
            model = DecodeModel(model_dir, warmup=warmup)
        self.model = model
        self.name = name
        self.max_new_tokens = (
            env_int("PT_DECODE_MAX_NEW_TOKENS", 64)
            if max_new_tokens is None else int(max_new_tokens))
        # pool_blocks may RESTRICT accounting below the artifact's pool
        # (partitioning one exported pool across tenants; forcing
        # eviction pressure in tests) — never exceed the device shape
        self.pool = KVBlockPool(min(pool_blocks or model.pool_blocks,
                                    model.pool_blocks), model.block_size)
        self.admission = AdmissionController(
            queue_depth=(env_int("PT_SERVE_QUEUE_DEPTH", 256)
                         if queue_depth is None else int(queue_depth)),
            max_batch_size=1,
            default_deadline_ms=(env_float("PT_SERVE_DEADLINE_MS", 0.0)
                                 if deadline_ms is None
                                 else float(deadline_ms)))
        self.metrics = metrics or DecodeMetrics(name)
        # KV economics: both OFF unless asked for — the plain engine's
        # accounting (exact block ids, zero blocks at idle) is a tested
        # contract, and sharing retains blocks past sequence lifetime
        self.kv_share = (bool(env_int("PT_KV_SHARE", 0))
                         if kv_share is None else bool(kv_share))
        self.index = (PrefixIndex(self.pool) if self.kv_share else None)
        spec = (os.environ.get("PT_SPEC_DRAFT", "")
                if drafter is None else drafter)
        self.drafter = resolve_drafter(spec, model)
        self.spec_k = (env_int("PT_SPEC_K", 4)
                       if spec_k is None else int(spec_k))
        self.scheduler = DecodeScheduler(model, self.pool, self.admission,
                                         self.metrics,
                                         continuous=continuous, name=name,
                                         prefix_index=self.index,
                                         drafter=self.drafter,
                                         spec_k=self.spec_k)

    # -- the request path ----------------------------------------------------
    def generate(self, prompt_ids: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 deadline_ms: Optional[float] = None, priority: int = 0,
                 eos_id: Optional[int] = None) -> GenerationHandle:
        """Admit one prompt; returns a GenerationHandle (stream() /
        result()). Raises typed admission errors reject-fast."""
        prompt = [int(t) for t in prompt_ids]
        max_new = (self.max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if not prompt:
            raise InvalidRequest("prompt_ids must be non-empty")
        if max_new < 1:
            raise InvalidRequest(f"max_new_tokens {max_new} < 1")
        if any(t < 0 or t >= self.model.vocab_size for t in prompt):
            raise InvalidRequest(
                f"prompt ids outside [0, {self.model.vocab_size})")
        if len(prompt) > self.model.max_prompt_len:
            raise InvalidRequest(
                f"prompt length {len(prompt)} exceeds the largest "
                f"prefill bucket {self.model.max_prompt_len}")
        if len(prompt) + max_new > self.model.max_context:
            raise InvalidRequest(
                f"prompt {len(prompt)} + max_new {max_new} exceeds "
                f"max_context {self.model.max_context}")
        # a sequence the pool can NEVER hold is pool exhaustion by
        # construction: shed typed at submit instead of deadlocking the
        # admit loop (peak residency is prompt+max_new-1 cached tokens)
        peak = blocks_for_tokens(len(prompt) + max_new - 1,
                                 self.model.block_size)
        if peak > self.pool.capacity:
            self.metrics.on_shed("overload")
            raise Overloaded(
                f"sequence needs {peak} KV blocks at peak but the pool "
                f"holds {self.pool.capacity} — raise "
                f"PT_DECODE_POOL_BLOCKS or lower max_new_tokens")
        return self.scheduler.submit(prompt, max_new,
                                     deadline_ms=deadline_ms,
                                     priority=priority, eos_id=eos_id)

    # -- maintenance ---------------------------------------------------------
    def defrag(self) -> int:
        """Compact live blocks onto the lowest pool ids (host accounting
        + device permute). Returns blocks moved. Runs under the
        scheduler lock with zero live sequences — submission blocks on
        the same lock, so no sequence can be admitted (no decode step
        can touch the pools) mid-permute; raises RuntimeError when the
        engine is not idle."""

        def _do():
            mapping = self.pool.defrag()
            self.model.permute_blocks(mapping)
            if self.index is not None:
                # cached prefixes MOVE with their blocks — the index's
                # chains stay valid across compaction
                self.index.remap(mapping)
            return len(mapping)

        return self.scheduler.while_idle(_do)

    def kv_residency(self) -> dict:
        """Shared-block residency, the session-affinity health signal:
        a session's cached prefix lives HERE, so the fleet router's
        rendezvous hash should keep its follow-ups here too."""
        out = {"kv_blocks_shared": self.pool.blocks_shared,
               "kv_blocks_in_use": self.pool.blocks_in_use,
               "kv_blocks_indexed": (self.index.blocks_indexed
                                     if self.index is not None else 0)}
        if self.index is not None:
            out.update(prefix_hits=self.index.hits,
                       prefix_hit_tokens=self.index.hit_tokens)
        return out

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()

    def describe(self) -> dict:
        out = self.model.describe()
        out["continuous"] = self.scheduler.continuous
        out["max_new_tokens_default"] = self.max_new_tokens
        out["kv_share"] = self.kv_share
        out["drafter"] = (getattr(self.drafter, "name", "custom")
                          if self.drafter is not None else None)
        out["spec_k"] = self.spec_k if self.drafter is not None else 0
        return out

    def shutdown(self, drain: bool = True) -> None:
        self.scheduler.close(drain=drain)
