"""Paged KV-cache management: fixed-size blocks in a preallocated pool.

The device side is dumb on purpose — per layer, one K and one V array of
shape [pool_blocks, block_size, n_heads, head_dim] that the decode-step
artifact reads and writes through per-slot block tables. Everything
smart lives HERE, on the host: which blocks belong to which sequence,
what is free, when a sequence must be evicted because the pool is under
pressure, and the accounting an operator needs to size the pool
(utilization, high-water mark, eviction counts live in DecodeMetrics).

Block id 0 is the reserved NULL block: inactive decode slots point every
block-table entry at it, so their (masked, never-read) writes land
somewhere harmless. The allocator therefore never hands out block 0, and
usable capacity is (pool_blocks - 1) * block_size cached tokens.

Invariant the no-stale-leak test rides on: a sequence only ever reads
pool positions it has itself written — prefill writes rows [0, len) of
its blocks, each decode step writes exactly position context_len-1, and
attention is masked to [0, context_len). A freed block's stale contents
are unreachable from any later owner because the new owner rewrites
every position below its own mask before reading it.

Prefix sharing (serving/decode/prefix.py) extends the invariant with
per-block REFCOUNTS: a block holding the K/V of a token prefix may back
several owners at once — N sequences whose prompts share the prefix,
plus the prefix index's own cache reference. `alloc` hands a block out
at refcount 1, `share` adds an owner, `free` only RETURNS the block to
the free list when the last owner lets go. Aliasing preserves the
no-stale-leak reading because causal K/V rows are a pure function of
the token prefix — an aliased row IS the row the new owner's own
prefill would have written, byte for byte. A write into a shared block
is never allowed: the scheduler copies-on-write into a fresh block
first (DecodeModel.copy_block), so a shared block's contents are frozen
for as long as anyone else can read them.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Sequence

import numpy as np

__all__ = ["PoolExhausted", "KVBlockPool", "blocks_for_tokens",
           "write_prefill_pages", "block_table_row"]


class PoolExhausted(Exception):
    """Internal allocator signal; the scheduler translates pool pressure
    into eviction or a typed admission error (Overloaded)."""


def blocks_for_tokens(tokens: int, block_size: int) -> int:
    return -(-max(int(tokens), 0) // block_size)


class KVBlockPool:
    """Host-side free-list accounting for the device block pool.

    Lowest-id-first allocation (a heap) keeps layouts deterministic —
    tests assert exact block ids — and makes `defrag` meaningful: after
    churn, live blocks can be compacted back down to the low ids so the
    high tail of the pool is contiguous free space (useful for shrinking
    a pool between load phases; the device remap is the caller's job,
    `DecodeEngine.defrag`).
    """

    def __init__(self, pool_blocks: int, block_size: int):
        if pool_blocks < 2:
            raise ValueError("pool needs >= 2 blocks (0 is the null block)")
        self.pool_blocks = int(pool_blocks)
        self.block_size = int(block_size)
        self._free: List[int] = list(range(1, pool_blocks))
        heapq.heapify(self._free)
        #: block id -> owner count; a block is live while its count > 0
        self._ref: Dict[int, int] = {}
        self.high_water = 0

    # -- accounting ----------------------------------------------------------
    @property
    def capacity(self) -> int:
        """Allocatable blocks (the null block excluded)."""
        return self.pool_blocks - 1

    @property
    def blocks_in_use(self) -> int:
        return len(self._ref)

    @property
    def blocks_free(self) -> int:
        return len(self._free)

    @property
    def blocks_shared(self) -> int:
        """Blocks with more than one live owner (the aliasing win)."""
        return sum(1 for n in self._ref.values() if n > 1)

    def utilization(self) -> float:
        return self.blocks_in_use / max(self.capacity, 1)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def blocks_for_tokens(self, tokens: int) -> int:
        return blocks_for_tokens(tokens, self.block_size)

    def refcount(self, block: int) -> int:
        return self._ref.get(block, 0)

    # -- alloc/free ----------------------------------------------------------
    def alloc(self, n: int) -> List[int]:
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"({self.blocks_in_use}/{self.capacity} in use)")
        out = [heapq.heappop(self._free) for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        self.high_water = max(self.high_water, self.blocks_in_use)
        return out

    def share(self, ids: Sequence[int]) -> None:
        """Add one owner to each live block — aliasing a resident prefix
        into another sequence's block table. Only live blocks can gain
        owners; sharing a free block would resurrect stale contents."""
        for b in ids:
            if b == 0 or b not in self._ref:
                raise ValueError(f"sharing block {b} not allocated")
        for b in ids:
            self._ref[b] += 1

    def free(self, ids: Sequence[int]) -> None:
        """Drop one owner per block; a block returns to the free list
        only when its LAST owner lets go."""
        for b in ids:
            if b == 0 or b not in self._ref:
                raise ValueError(f"freeing block {b} not allocated")
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                heapq.heappush(self._free, b)

    # -- defrag --------------------------------------------------------------
    def defrag(self) -> Dict[int, int]:
        """Compact live blocks onto the lowest ids. Returns the {old: new}
        mapping for every MOVED block (identity entries omitted); the
        caller must remap its block tables — including the prefix
        index's (PrefixIndex.remap) — and permute the device pools
        accordingly before the next step. Shared blocks MOVE like any
        other live block (every owner sees the same remap); refcounts
        ride along with the id."""
        live = sorted(self._ref)
        mapping: Dict[int, int] = {}
        target = 1
        for b in live:
            if b != target:
                mapping[b] = target
            target += 1
        if mapping:
            self._ref = {mapping.get(b, b): n for b, n in self._ref.items()}
            self._free = list(range(target, self.pool_blocks))
            heapq.heapify(self._free)
        return mapping


def write_prefill_pages(pool, block_ids: Sequence[int], rows: np.ndarray,
                        block_size: int):
    """Scatter a sequence's prefill K or V rows ([written, H, D]) into
    its freshly allocated blocks of the device pool. Returns the updated
    pool (a new jax.Array; the old one is dropped by the caller)."""
    import jax.numpy as jnp

    n = len(block_ids)
    written = rows.shape[0]
    pad = n * block_size - written
    if pad < 0:
        raise ValueError(f"{written} rows exceed {n} blocks x {block_size}")
    if pad:
        rows = np.concatenate(
            [rows, np.zeros((pad,) + rows.shape[1:], rows.dtype)], axis=0)
    pages = jnp.asarray(rows).reshape((n, block_size) + rows.shape[1:])
    return jnp.asarray(pool).at[jnp.asarray(list(block_ids),
                                            dtype=jnp.int32)].set(pages)


def block_table_row(blocks: Sequence[int], width: int) -> np.ndarray:
    """A sequence's block list padded with the null block to table width."""
    row = np.zeros(width, np.int32)
    row[:len(blocks)] = blocks
    return row
