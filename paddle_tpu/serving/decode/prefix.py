"""Host-side prefix index: which resident KV blocks cache which token
prefixes, at block granularity.

The economics: every session against the same deployment repeats the
same system prompt, so the pool fills with N identical copies of the
same K/V rows. Causal K/V is a pure function of the token prefix — row
i depends only on tokens [0, i] — so those copies are bitwise
interchangeable, and ONE resident copy can back every sequence that
shares the prefix. The index maps hashed token-prefix chains to block
ids; admission consults it and aliases matched blocks into the new
sequence's block table (KVBlockPool.share) instead of rewriting them.

Structure: a trie of nodes, one per FULL block of cached tokens. Each
node keys its direct children by the child block's token tuple (the
root children live in `_root`), so the whole prefix up to and including
a block identifies it, built incrementally. A lookup walks the chain
from the root; the first miss ends the match. Two different prefixes
can never collide onto one node because the full token content is the
key, not a lossy digest — and both the full-block walk and the
partial-tail probe only ever touch ONE parent's children, so admission
cost scales with the prompt, not with everything indexed.

Partial-block tail matches: a prompt that ends INSIDE a cached block
(prompt tail is a proper prefix of the block's cached tokens) aliases
that block too — rows [0, tail) of it are exactly the rows this prompt
would have written, and attention masks the rest. That aliased block is
where copy-on-write earns its name: the sequence's FIRST decode write
lands inside it, so the scheduler copies the block out before writing
(scheduler._cow_for_write). A prompt that DIVERGES inside a block gets
no alias for that block — rows past the divergence point belong to a
different prefix.

Ownership: the index holds its own pool reference on every indexed
block (share on insert, free on release) — a cached prefix stays
resident after the sequence that wrote it finishes, which is the whole
point. Under pool pressure the scheduler releases index references
leaf-first in LRU order (`release_lru`) BEFORE evicting running
sequences: cache beats nothing, but live work beats cache.

Single-threaded on purpose: only the scheduler thread touches the
index (same ownership rule as scheduler._waiting/_running).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["PrefixIndex"]


class _Node:
    __slots__ = ("key", "parent", "block", "tokens", "kids", "tick")

    def __init__(self, key, parent: Optional["_Node"], block: int,
                 tokens: Tuple[int, ...]):
        self.key = key
        self.parent = parent
        self.block = block
        self.tokens = tokens
        #: direct children keyed by their token tuple — the next-block
        #: lookup AND the partial-tail probe scan only this dict
        self.kids: Dict[Tuple[int, ...], "_Node"] = {}
        self.tick = 0


class PrefixIndex:
    """Block-granular prefix cache over one KVBlockPool."""

    def __init__(self, pool, block_size: Optional[int] = None):
        self.pool = pool
        self.block_size = int(block_size or pool.block_size)
        #: flat registry (for counting, LRU-leaf scans, defrag remap);
        #: lookups go through the per-node `kids` dicts instead
        self._nodes: Dict[tuple, _Node] = {}
        self._root: Dict[Tuple[int, ...], _Node] = {}
        self._tick = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0
        self.released = 0

    # -- accounting ----------------------------------------------------------
    @property
    def blocks_indexed(self) -> int:
        return len(self._nodes)

    def stats(self) -> dict:
        return {"blocks_indexed": self.blocks_indexed, "hits": self.hits,
                "misses": self.misses, "hit_tokens": self.hit_tokens,
                "released": self.released}

    def _key(self, parent: Optional[_Node], tokens: Tuple[int, ...]):
        return (id(parent) if parent is not None else None, tokens)

    def _kids(self, parent: Optional[_Node]) -> Dict[Tuple[int, ...],
                                                     _Node]:
        return parent.kids if parent is not None else self._root

    # -- lookup --------------------------------------------------------------
    def match(self, tokens: Sequence[int]) -> Tuple[List[int], int]:
        """Longest resident prefix of `tokens`: (block ids, matched token
        count). Full blocks match whole; the final block may match
        PARTIALLY — only when the remaining prompt tail is a proper
        prefix of its cached tokens, so matched == len(tokens) and the
        caller's first decode write (position matched) lands inside the
        aliased block (the CoW case). The caller owns taking pool
        references (share) on the returned blocks."""
        bs = self.block_size
        toks = [int(t) for t in tokens]
        blocks: List[int] = []
        matched = 0
        parent: Optional[_Node] = None
        self._tick += 1
        while matched + bs <= len(toks):
            chunk = tuple(toks[matched:matched + bs])
            node = self._kids(parent).get(chunk)
            if node is None:
                break
            node.tick = self._tick
            blocks.append(node.block)
            matched += bs
            parent = node
        tail = len(toks) - matched
        if 0 < tail < bs:
            # one cached child whose tokens START with the tail gives a
            # partial alias; only this parent's DIRECT children are
            # candidates, so the probe scans just them
            want = tuple(toks[matched:])
            for ktoks, node in self._kids(parent).items():
                if ktoks[:tail] == want:
                    node.tick = self._tick
                    blocks.append(node.block)
                    matched = len(toks)
                    break
        if matched:
            self.hits += 1
            self.hit_tokens += matched
        else:
            self.misses += 1
        return blocks, matched

    # -- registration --------------------------------------------------------
    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> int:
        """Register a just-prefilled sequence's FULL blocks (the first
        floor(len/bs) of `blocks`, which cache tokens the sequence will
        never rewrite — decode writes land strictly past the prompt).
        New nodes take one pool reference each; existing nodes (the
        shared prefix the sequence itself aliased) are left alone.
        Returns the number of newly indexed blocks."""
        bs = self.block_size
        toks = [int(t) for t in tokens]
        parent: Optional[_Node] = None
        self._tick += 1
        added = 0
        for i in range(len(toks) // bs):
            chunk = tuple(toks[i * bs:(i + 1) * bs])
            node = self._kids(parent).get(chunk)
            if node is None:
                block = int(blocks[i])
                self.pool.share([block])
                key = self._key(parent, chunk)
                node = _Node(key, parent, block, chunk)
                self._nodes[key] = node
                self._kids(parent)[chunk] = node
                added += 1
            node.tick = self._tick
            parent = node
        return added

    # -- pressure ------------------------------------------------------------
    def release_lru(self, n: int = 1) -> int:
        """Drop the index's pool reference on up to `n` least-recently-
        used LEAF blocks (a parent must outlive its children — a chain
        is only walkable from the root). Returns blocks released; the
        pool reclaims each one whose other owners are also gone."""
        dropped = 0
        while dropped < n:
            leaves = [node for node in self._nodes.values()
                      if not node.kids]
            if not leaves:
                break
            victim = min(leaves, key=lambda nd: nd.tick)
            del self._nodes[victim.key]
            del self._kids(victim.parent)[victim.tokens]
            self.pool.free([victim.block])
            self.released += 1
            dropped += 1
        return dropped

    def clear(self) -> int:
        """Release every index reference (tests, shutdown)."""
        return self.release_lru(len(self._nodes))

    # -- defrag --------------------------------------------------------------
    def remap(self, mapping: Dict[int, int]) -> None:
        """Apply a pool defrag's {old: new} block mapping — the index's
        cached chains move with their blocks."""
        if not mapping:
            return
        for node in self._nodes.values():
            node.block = mapping.get(node.block, node.block)
