"""Continuous-batching scheduler: prefill/decode split, slot admission,
eviction under KV-pool pressure.

The shape insight (vLLM-style continuous batching, translated to AOT
artifacts): the decode step is ONE fixed-shape dispatch — one token per
slot — so sequences of wildly different lengths share a batch, and a
sequence that finishes frees its slot for a WAITING sequence at the very
next iteration. There is no drain-to-empty barrier: admission happens
into the in-flight batch. The alternative (static batching: admit N,
decode until ALL N finish) wastes every slot whose sequence finished
early — the `decode` bench config measures exactly that gap, and this
scheduler also implements the static mode (`continuous=False`) to BE the
honest baseline.

Split responsibilities:

    prefill   the prompt runs ONCE through the length-bucketed
              full-attention artifacts (the PR-5 ModelVersion, padding
              and all), emitting the first token AND every layer's K/V
              rows, which seed the sequence's pool blocks;
    decode    each iteration advances every RUNNING sequence one token
              through the paged decode-step artifact.

Eviction/preemption: when a sequence needs a KV block and the pool has
none, the lowest-priority (then youngest) victim is preempted — blocks
freed, sequence re-queued at the waiting front. A resumed sequence
re-prefills prompt+generated (greedy decode is a pure function of the
prefix, so the continuation is token-identical — tested). Shedding is
typed through PR-5's admission machinery: `Overloaded` (queue/pool
pressure, retryable) and `DeadlineExceeded` (the remaining-token
estimate — tokens left x EWMA step seconds — says the deadline is
unmeetable, or it already passed).

KV economics (PT_KV_SHARE / PT_SPEC_DRAFT, decode/prefix.py +
decode/spec.py): with a prefix index armed, admission aliases the
resident prefix of a new prompt into its block table (pool refcounts;
one copy backs N sessions) and copy-on-write keeps shared blocks
immutable — the first decode write into an aliased partial block
copies it out first (`_cow_for_write`). Under pool pressure the
scheduler releases cached-prefix references LRU-leaf-first BEFORE
preempting running sequences. With a drafter armed, idle slots verify
drafted tokens in the same fixed-shape step (decode/spec.py explains
the slot-packing), greedy acceptance keeps output token-identical to
plain decode, and block growth is provisioned for the FULL draft
window up front — speculation may be dropped for a step (never evicts
a peer) when the pool can't cover it.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence as Seq

import numpy as np

from ...obs import trace as obs_trace
from ...resilience import faults
from ..admission import (AdmissionController, DeadlineExceeded,
                         ModelUnavailable, Overloaded)
from ..metrics import DecodeMetrics
from .kv_cache import KVBlockPool, PoolExhausted, block_table_row
from .spec import accept_greedy

__all__ = ["GenerationHandle", "Sequence", "DecodeScheduler"]

_TOK, _DONE, _ERR = 0, 1, 2


class GenerationHandle:
    """The caller's view of one generation: a token stream plus a final
    result. Tokens arrive on an internal queue as the scheduler emits
    them; `stream()` yields them live, `result()` blocks to the end.
    Terminal failures (typed serving errors) raise from either."""

    def __init__(self, prompt_len: int):
        self.prompt_len = prompt_len
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._result: Optional[dict] = None
        self._error: Optional[BaseException] = None

    # -- scheduler side ------------------------------------------------------
    def _put_token(self, tok: int) -> None:
        self._q.put((_TOK, tok))

    def _finish(self, result: dict) -> None:
        self._result = result
        self._done.set()
        self._q.put((_DONE, result))

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._done.set()
        self._q.put((_ERR, exc))

    # -- caller side ---------------------------------------------------------
    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as they are generated; returns when the sequence
        finishes, raises its typed error if it was shed/failed, raises
        TimeoutError (like result()) when no token arrives in time."""
        while True:
            try:
                kind, val = self._q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    "generation still in progress") from None
            if kind == _TOK:
                yield val
            elif kind == _DONE:
                return
            else:
                raise val

    def result(self, timeout: Optional[float] = None) -> dict:
        """Block until the sequence finishes; returns {"tokens",
        "finish_reason", "evictions", "prompt_len"}."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in progress")
        if self._error is not None:
            raise self._error
        return dict(self._result)

    def done(self) -> bool:
        return self._done.is_set()


class Sequence:
    """Scheduler-internal state of one generation request."""

    __slots__ = ("sid", "prompt", "max_new", "deadline_t", "priority",
                 "eos_id", "handle", "t_submit", "generated", "blocks",
                 "slot", "cached_len", "evictions", "ctx")

    def __init__(self, sid: int, prompt: List[int], max_new: int,
                 deadline_t: Optional[float], priority: int,
                 eos_id: Optional[int], handle: GenerationHandle):
        self.sid = sid
        self.prompt = prompt
        self.max_new = max_new
        self.deadline_t = deadline_t
        self.priority = priority
        self.eos_id = eos_id
        self.handle = handle
        self.t_submit = time.monotonic()
        self.generated: List[int] = []
        self.blocks: List[int] = []
        self.slot: Optional[int] = None
        #: pool positions holding this sequence's K/V; the LAST generated
        #: token is never cached (it is the next step's input)
        self.cached_len = 0
        self.evictions = 0
        #: submitter's trace context (the HTTP ingress span) — the
        #: scheduler thread parents this sequence's prefill/evict/resume
        #: events under it (obs/trace.py)
        self.ctx = obs_trace.current_context() if obs_trace.enabled() \
            else None

    @property
    def tokens_so_far(self) -> List[int]:
        return self.prompt + self.generated

    @property
    def remaining(self) -> int:
        return self.max_new - len(self.generated)


class DecodeScheduler:
    """One model's generation scheduler: a submission queue drained by
    one scheduler thread that interleaves prefill admission with
    fixed-shape decode steps over the in-flight slot batch.

    model: DecodeModel-like — max_prompt_len, max_context, slots,
    block_size, eos_id, prefill(tokens) -> (last_logits, kv_rows),
    seed_sequence(blocks, kv_rows), decode_step(tokens, lens, tables)
    -> logits [slots, vocab], free capacity given by the injected pool.
    """

    def __init__(self, model, pool: KVBlockPool,
                 admission: AdmissionController,
                 metrics: Optional[DecodeMetrics] = None, *,
                 continuous: bool = True, name: str = "model",
                 prefix_index=None, drafter=None, spec_k: int = 0):
        self.model = model
        self.pool = pool
        self.admission = admission
        self.metrics = metrics or DecodeMetrics(name)
        self.continuous = continuous
        self.name = name
        #: scheduler-thread-owned, like _waiting/_running
        self.index = prefix_index
        self.drafter = drafter
        self.spec_k = max(0, int(spec_k)) if drafter is not None else 0
        self._cv = threading.Condition()
        self._incoming: List[Sequence] = []
        self._waiting: List[Sequence] = []   # scheduler-thread-owned
        self._running: List[Sequence] = []   # scheduler-thread-owned
        self._load = 0                       # live sequences, any state
        self._next_sid = 0
        self._closed = False
        self._drained = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"pt-decode[{name}]")
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def queued(self) -> int:
        with self._cv:
            return self._load

    def submit(self, prompt: Seq[int], max_new: int,
               deadline_ms: Optional[float] = None, priority: int = 0,
               eos_id: Optional[int] = None) -> GenerationHandle:
        """Admit one generation request. Typed admission errors raise
        HERE (reject-fast); later shedding surfaces on the handle."""
        deadline_t = self.admission.deadline_for(deadline_ms)
        handle = GenerationHandle(len(prompt))
        with self._cv:
            if self._closed:
                raise ModelUnavailable(
                    f"decode engine {self.name!r} is shut down")
            try:
                self.admission.admit(self._load, deadline_t,
                                     model=self.name)
            except DeadlineExceeded:
                self.metrics.on_shed("deadline")
                raise
            except Exception:
                self.metrics.on_shed("overload")
                raise
            seq = Sequence(self._next_sid, list(prompt), int(max_new),
                           deadline_t, int(priority),
                           eos_id if eos_id is not None
                           else self.model.eos_id, handle)
            self._next_sid += 1
            self._incoming.append(seq)
            self._load += 1
            self.metrics.on_received()
            self._cv.notify()
        return handle

    def while_idle(self, fn):
        """Run fn() under the scheduler lock with ZERO live sequences —
        submit() blocks on the same lock, so nothing can be admitted (and
        no decode step can start) while fn mutates pool state. Raises if
        any sequence is live in any state (incoming/waiting/running)."""
        with self._cv:
            if self._load:
                raise RuntimeError(
                    f"engine {self.name!r} has {self._load} live "
                    "sequence(s); idle-only maintenance refused")
            return fn()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """drain=True generates every admitted sequence to completion
        first; drain=False fails the backlog fast."""
        with self._cv:
            self._closed = True
            self._drain_on_close = drain
            self._cv.notify()
        self._drained.wait(timeout)
        self._thread.join(timeout)

    # -- scheduler thread ----------------------------------------------------
    def _loop(self) -> None:
        self._drain_on_close = True
        try:
            while True:
                with self._cv:
                    while True:
                        if self._incoming:
                            self._waiting.extend(self._incoming)
                            self._incoming.clear()
                        if self._closed:
                            break
                        if self._waiting or self._running:
                            break
                        self._cv.wait()
                    if self._closed and not self._drain_on_close:
                        self._fail_backlog()
                    if self._closed and not (self._waiting
                                             or self._running):
                        return
                # heavy work outside the lock: only this thread touches
                # _waiting/_running
                self._shed_unmeetable()
                self._admit()
                self._step()
                self._publish_gauges()
        finally:
            self._drained.set()

    def _fail_backlog(self) -> None:
        for seq in self._waiting + self._running:
            self._terminate(seq, error=ModelUnavailable(
                f"decode engine {self.name!r} shut down before "
                "completion"))
        self._waiting.clear()
        self._running.clear()

    def _publish_gauges(self) -> None:
        self.metrics.set_gauges(
            active=len(self._running), waiting=len(self._waiting),
            blocks_in_use=self.pool.blocks_in_use,
            blocks_capacity=self.pool.capacity,
            high_water=self.pool.high_water,
            blocks_shared=self.pool.blocks_shared,
            blocks_indexed=(self.index.blocks_indexed
                            if self.index is not None else 0))

    # -- terminal transitions ------------------------------------------------
    def _terminate(self, seq: Sequence, *, result: Optional[dict] = None,
                   error: Optional[BaseException] = None) -> None:
        """Free-on-finish: every block goes back to the pool, whatever
        the outcome."""
        if seq.blocks:
            self.pool.free(seq.blocks)
            seq.blocks = []
        seq.slot = None
        with self._cv:
            self._load -= 1
        if error is not None:
            self.metrics.on_finished(False)
            seq.handle._fail(error)
        else:
            self.metrics.on_finished(True)
            seq.handle._finish(result)

    def _finish(self, seq: Sequence, reason: str) -> None:
        self._terminate(seq, result={
            "tokens": list(seq.generated), "finish_reason": reason,
            "evictions": seq.evictions, "prompt_len": len(seq.prompt)})

    def _finish_reason(self, seq: Sequence, tok: int) -> Optional[str]:
        if seq.eos_id is not None and tok == seq.eos_id:
            return "eos"
        if len(seq.generated) >= seq.max_new:
            return "length"
        return None

    # -- deadline shedding ---------------------------------------------------
    def _shed_unmeetable(self) -> None:
        """Expired deadlines always shed; un-expired ones shed when the
        remaining-token estimate (tokens left x EWMA step seconds) says
        the deadline cannot be met — the cold engine (no estimate yet)
        never sheds on a guess."""
        now = time.monotonic()
        est = self.admission.estimated_batch_s()
        for lst in (self._waiting, self._running):
            for seq in list(lst):
                if seq.deadline_t is None:
                    continue
                expired = now >= seq.deadline_t
                unmeetable = (est is not None and
                              now + seq.remaining * est > seq.deadline_t)
                if expired or unmeetable:
                    lst.remove(seq)
                    self.metrics.on_shed("deadline")
                    why = ("deadline expired" if expired else
                           f"~{seq.remaining} tokens x {est * 1000:.1f} "
                           "ms/step exceed the deadline")
                    self._terminate(seq, error=DeadlineExceeded(
                        f"sequence shed: {why} (model {self.name!r})"))

    # -- eviction ------------------------------------------------------------
    def _evict(self, victim: Sequence) -> None:
        """Preempt: free blocks+slot, requeue at the waiting FRONT. If
        its grown context can no longer re-prefill (past the largest
        bucket), shed instead — resuming would be impossible."""
        self._running.remove(victim)
        self.pool.free(victim.blocks)
        victim.blocks = []
        victim.slot = None
        victim.cached_len = 0
        victim.evictions += 1
        self.metrics.on_evicted()
        obs_trace.instant("evict", cat="decode", parent=victim.ctx,
                          model=self.name, sid=victim.sid,
                          generated=len(victim.generated))
        if len(victim.tokens_so_far) > self.model.max_prompt_len:
            self.metrics.on_shed("overload")
            self._terminate(victim, error=Overloaded(
                f"evicted under KV-pool pressure and its context "
                f"({len(victim.tokens_so_far)} tokens) exceeds the "
                f"largest prefill bucket {self.model.max_prompt_len} — "
                "cannot resume (model {0!r})".format(self.name)))
        else:
            self._waiting.insert(0, victim)

    def _evict_for(self, seq: Sequence, need: int,
                   allow_peers: bool) -> bool:
        """Evict running sequences until `need` blocks are free. Victims
        must rank strictly below `seq` — lower priority, or (only when
        allow_peers, the mid-decode growth case, which guarantees the
        oldest sequence always progresses) same priority but younger."""

        def rank(s: Sequence):
            return (s.priority, -s.t_submit)   # low priority, young first

        while not self.pool.can_alloc(need):
            # cached prefixes go first: dropping an index reference costs
            # a future alias, evicting a running sequence costs a full
            # re-prefill — cache beats nothing, live work beats cache
            if self.index is not None and self.index.release_lru(1):
                continue
            victims = [s for s in self._running if s is not seq
                       and (s.priority < seq.priority
                            or (allow_peers
                                and s.priority == seq.priority
                                and s.t_submit > seq.t_submit))]
            if not victims:
                return False
            self._evict(min(victims, key=rank))
        return True

    # -- admission (prefill) -------------------------------------------------
    def _admit(self) -> None:
        if not self._waiting:
            return
        if not self.continuous and self._running:
            return   # the static baseline: drain-to-empty barrier
        # priority first, then arrival order (evictees keep their
        # original t_submit, so they resume before younger peers)
        order = sorted(self._waiting, key=lambda s: (-s.priority,
                                                     s.t_submit))
        for seq in order:
            if len(self._running) >= self.model.slots:
                break
            try:
                self._admit_one(seq)
            except Exception as e:  # noqa: BLE001 — one bad sequence
                # must never kill the scheduler thread: fail IT typed
                # (its blocks free in _terminate) and keep admitting
                if seq in self._waiting:
                    self._waiting.remove(seq)
                self._terminate(seq, error=e if isinstance(
                    e, (Overloaded, DeadlineExceeded)) else
                    _request_failed(self.name, e))

    def _admit_one(self, seq: Sequence) -> None:
        tokens = seq.tokens_so_far
        shared: List[int] = []
        matched = 0
        if self.index is not None:
            shared, matched = self.index.match(tokens)
        if shared:
            # alias the resident prefix: take OUR reference per block AT
            # MATCH TIME — under pressure _evict_for drops index
            # references (release_lru), possibly on these very blocks,
            # and only this pin keeps them (and the `need` arithmetic
            # below) live until admission resolves
            self.pool.share(shared)
            seq.blocks = list(shared)
        need = self.pool.blocks_for_tokens(len(tokens)) - len(shared)
        if not self.pool.can_alloc(need) and \
                not self._evict_for(seq, need, allow_peers=False):
            if shared:
                self.pool.free(shared)   # unpin the aliased prefix
                seq.blocks = []
            return   # stays waiting; capacity frees as others end
        self._waiting.remove(seq)
        if seq.evictions:
            self.metrics.on_resumed()
            obs_trace.instant("resume", cat="decode", parent=seq.ctx,
                              model=self.name, sid=seq.sid)
        if shared:
            # write NOTHING below `matched` — those rows are, byte for
            # byte, what this prompt's prefill would write
            self.metrics.on_prefix_hit(matched, len(shared))
            obs_trace.instant("prefix_hit", cat="decode",
                              parent=seq.ctx, model=self.name,
                              sid=seq.sid, tokens=matched)
        if need:
            seq.blocks = seq.blocks + self.pool.alloc(need)
        t0 = time.monotonic()
        try:
            last_logits, kv_rows = self.model.prefill(tokens)
            self.model.seed_sequence(seq.blocks, kv_rows,
                                     skip_rows=matched)
        except Exception as e:  # noqa: BLE001 — typed + delivered
            self._terminate(seq, error=e if isinstance(
                e, (Overloaded, DeadlineExceeded)) else
                _request_failed(self.name, e))
            return
        dt = time.monotonic() - t0
        self.metrics.on_prefill(len(tokens), dt)
        obs_trace.complete("prefill", dt, cat="decode",
                           parent=seq.ctx, model=self.name,
                           sid=seq.sid, tokens=len(tokens))
        seq.cached_len = len(tokens)
        if self.index is not None:
            # register this sequence's full prompt blocks (decode
            # writes land strictly past the prompt, so they stay
            # immutable while indexed)
            self.index.insert(tokens, seq.blocks)
        tok = int(np.argmax(last_logits))
        seq.generated.append(tok)
        seq.handle._put_token(tok)
        reason = self._finish_reason(seq, tok)
        if reason is not None:
            self._finish(seq, reason)
            return
        free_slots = [i for i in range(self.model.slots)
                      if all(r.slot != i for r in self._running)]
        seq.slot = free_slots[0]
        self._running.append(seq)

    # -- copy-on-write -------------------------------------------------------
    def _cow_for_write(self, seq: Sequence) -> bool:
        """Make the block holding this step's first write position
        (cached_len) exclusively `seq`'s. Only an aliased PARTIAL tail
        block can be hit — every block past the prompt was freshly
        allocated — so at most ONE copy per sequence lifetime. Returns
        False when the sequence had to be preempted for the copy target
        (pool exhausted with no lower-ranked victim): a shared block is
        NEVER written in place."""
        bi = seq.cached_len // self.pool.block_size
        if bi >= len(seq.blocks):
            return True   # the write lands in a to-be-allocated block
        old = seq.blocks[bi]
        if self.pool.refcount(old) <= 1:
            return True   # exclusively owned already
        if not self.pool.can_alloc(1) and \
                not self._evict_for(seq, 1, allow_peers=True):
            self._evict(seq)
            return False
        new = self.pool.alloc(1)[0]
        self.model.copy_block(old, new)
        self.pool.free([old])   # drop OUR reference; other owners keep it
        seq.blocks[bi] = new
        self.metrics.on_cow()
        obs_trace.instant("cow", cat="decode", parent=seq.ctx,
                          model=self.name, sid=seq.sid, block=old)
        return True

    # -- speculation ---------------------------------------------------------
    def _gather_drafts(self, budget: int) -> Dict[int, List[int]]:
        """Ask the drafter for up to spec_k tokens per running sequence,
        bounded by idle slots, the generation budget, and the context
        limit. A drafter crash (chaos site spec_verify) falls back to
        plain decode for that sequence's step — never kills it."""
        out: Dict[int, List[int]] = {}
        for seq in sorted(self._running,
                          key=lambda s: (-s.priority, s.t_submit)):
            if budget <= 0:
                break
            k = min(self.spec_k, budget, seq.remaining - 1,
                    self.model.max_context - seq.cached_len - 1,
                    (self.model.max_blocks_per_seq
                     * self.pool.block_size) - seq.cached_len - 1)
            if k < 1:
                continue
            try:
                faults.crash_point("spec_verify")
                proposed = self.drafter.propose(seq.tokens_so_far, k)
            except Exception:   # noqa: BLE001 — degrade, don't die
                self.metrics.on_spec_fallback()
                obs_trace.instant("spec_fallback", cat="decode",
                                  parent=seq.ctx, model=self.name,
                                  sid=seq.sid)
                continue
            drafts: List[int] = []
            for t in list(proposed)[:k]:
                t = int(t)
                if not 0 <= t < self.model.vocab_size:
                    break   # truncate, don't filter: a chain has no holes
                drafts.append(t)
            if drafts:
                out[seq.sid] = drafts
                budget -= len(drafts)
        return out

    # -- one decode step -----------------------------------------------------
    def _step(self) -> None:
        if not self._running:
            return
        slots = self.model.slots
        drafts: Dict[int, List[int]] = {}
        if self.drafter is not None and self.spec_k > 0:
            drafts = self._gather_drafts(slots - len(self._running))
        # grow block capacity in priority order so the important
        # sequences claim blocks (and pick victims) first
        for seq in sorted(list(self._running),
                          key=lambda s: (-s.priority, s.t_submit)):
            if seq not in self._running:
                drafts.pop(seq.sid, None)
                continue   # evicted by a higher-priority peer this pass
            if not self._cow_for_write(seq):
                drafts.pop(seq.sid, None)
                continue   # preempted hunting a copy target
            # provision the FULL draft window up front — acceptance is
            # variable but the pool must cover the maximum
            g = 1 + len(drafts.get(seq.sid, ()))
            need = (self.pool.blocks_for_tokens(seq.cached_len + g)
                    - len(seq.blocks))
            if need > 0 and g > 1 and not self.pool.can_alloc(need):
                # speculation never evicts a peer: drop the drafts and
                # retry as a plain one-token step
                drafts.pop(seq.sid, None)
                need = (self.pool.blocks_for_tokens(seq.cached_len + 1)
                        - len(seq.blocks))
            if need <= 0:
                continue
            if not self.pool.can_alloc(need) and \
                    not self._evict_for(seq, need, allow_peers=True):
                # no victims rank below it and the pool is dry: preempt
                # ITSELF — resume when capacity frees. Progress is
                # guaranteed: the oldest highest-priority sequence always
                # either allocates or finds victims, so the pool drains
                # toward completion rather than thrashing. (A sequence
                # that can never fit at all was already shed typed at
                # submit by the engine's peak-residency check.)
                drafts.pop(seq.sid, None)
                self._evict(seq)
                continue
            seq.blocks.extend(self.pool.alloc(need))
        active = list(self._running)
        if not active:
            return
        # slot packing: each drafted sequence borrows idle slots — slot
        # j of its chain feeds draft j with context_len L+1+j over the
        # SAME block table, so the step's kv-write phase lays down the
        # whole chain's rows before its attention phase reads them
        free_ids = [i for i in range(slots)
                    if all(r.slot != i for r in active)]
        spec_slots: Dict[int, List[int]] = {}
        for seq in active:
            d = drafts.get(seq.sid)
            if not d:
                continue
            take = free_ids[:len(d)]
            if len(take) < len(d):
                drafts[seq.sid] = d = d[:len(take)]
            if not d:
                drafts.pop(seq.sid, None)
                continue
            spec_slots[seq.sid] = take
            free_ids = free_ids[len(take):]
        tokens = np.zeros(slots, np.int64)
        lens = np.zeros(slots, np.int32)
        tables = np.zeros((slots, self.model.max_blocks_per_seq), np.int32)
        for seq in active:
            row = block_table_row(seq.blocks,
                                  self.model.max_blocks_per_seq)
            tokens[seq.slot] = seq.generated[-1]
            lens[seq.slot] = seq.cached_len + 1
            tables[seq.slot] = row
            for j, (sl, d) in enumerate(zip(spec_slots.get(seq.sid, ()),
                                            drafts.get(seq.sid, ())),
                                        start=1):
                tokens[sl] = d
                lens[sl] = seq.cached_len + 1 + j
                tables[sl] = row
        t0 = time.monotonic()
        logits = self.model.decode_step(tokens, lens, tables)
        dt = time.monotonic() - t0
        self.admission.observe_batch(dt)
        used = len(active) + sum(len(v) for v in spec_slots.values())
        if obs_trace.enabled():
            # one fixed-shape dispatch serving every running sequence:
            # the span records which sids shared it (a single-sequence
            # step adopts that sequence's trace)
            obs_trace.complete(
                "decode_step", dt, cat="decode",
                parent=(active[0].ctx if len(active) == 1 else None),
                model=self.name, n=len(active),
                sids=[s.sid for s in active])
        emitted_total = 0
        for seq in active:
            d = drafts.get(seq.sid, [])
            if d:
                chain = accept_greedy(
                    d, [int(np.argmax(logits[seq.slot]))]
                    + [int(np.argmax(logits[sl]))
                       for sl in spec_slots[seq.sid]])
                self.metrics.on_spec(len(d), len(chain) - 1)
            else:
                chain = [int(np.argmax(logits[seq.slot]))]
            reason = None
            advanced = 0
            for tok in chain:
                seq.generated.append(tok)
                seq.handle._put_token(tok)
                advanced += 1
                reason = self._finish_reason(seq, tok)
                if reason is not None:
                    break
            # every emitted token's K/V row is now resident (the LAST
            # one stays the next step's input, exactly as in plain
            # decode); rejected draft rows sit past cached_len, masked,
            # and are rewritten before the mask ever reaches them
            seq.cached_len += advanced
            emitted_total += advanced
            if reason is not None:
                self._running.remove(seq)
                self._finish(seq, reason)
        self.metrics.on_step(used, slots, dt, emitted_total)


def _request_failed(name: str, cause: BaseException):
    from ..admission import RequestFailed
    return RequestFailed(
        f"decode engine {name!r} failed running prefill: {cause}",
        cause=cause)
