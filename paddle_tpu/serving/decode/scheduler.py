"""Continuous-batching scheduler: prefill/decode split, slot admission,
eviction under KV-pool pressure.

The shape insight (vLLM-style continuous batching, translated to AOT
artifacts): the decode step is ONE fixed-shape dispatch — one token per
slot — so sequences of wildly different lengths share a batch, and a
sequence that finishes frees its slot for a WAITING sequence at the very
next iteration. There is no drain-to-empty barrier: admission happens
into the in-flight batch. The alternative (static batching: admit N,
decode until ALL N finish) wastes every slot whose sequence finished
early — the `decode` bench config measures exactly that gap, and this
scheduler also implements the static mode (`continuous=False`) to BE the
honest baseline.

Split responsibilities:

    prefill   the prompt runs ONCE through the length-bucketed
              full-attention artifacts (the PR-5 ModelVersion, padding
              and all), emitting the first token AND every layer's K/V
              rows, which seed the sequence's pool blocks;
    decode    each iteration advances every RUNNING sequence one token
              through the paged decode-step artifact.

Eviction/preemption: when a sequence needs a KV block and the pool has
none, the lowest-priority (then youngest) victim is preempted — blocks
freed, sequence re-queued at the waiting front. A resumed sequence
re-prefills prompt+generated (greedy decode is a pure function of the
prefix, so the continuation is token-identical — tested). Shedding is
typed through PR-5's admission machinery: `Overloaded` (queue/pool
pressure, retryable) and `DeadlineExceeded` (the remaining-token
estimate — tokens left x EWMA step seconds — says the deadline is
unmeetable, or it already passed).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence as Seq

import numpy as np

from ...obs import trace as obs_trace
from ..admission import (AdmissionController, DeadlineExceeded,
                         ModelUnavailable, Overloaded)
from ..metrics import DecodeMetrics
from .kv_cache import KVBlockPool, PoolExhausted, block_table_row

__all__ = ["GenerationHandle", "Sequence", "DecodeScheduler"]

_TOK, _DONE, _ERR = 0, 1, 2


class GenerationHandle:
    """The caller's view of one generation: a token stream plus a final
    result. Tokens arrive on an internal queue as the scheduler emits
    them; `stream()` yields them live, `result()` blocks to the end.
    Terminal failures (typed serving errors) raise from either."""

    def __init__(self, prompt_len: int):
        self.prompt_len = prompt_len
        self._q: "queue.Queue" = queue.Queue()
        self._done = threading.Event()
        self._result: Optional[dict] = None
        self._error: Optional[BaseException] = None

    # -- scheduler side ------------------------------------------------------
    def _put_token(self, tok: int) -> None:
        self._q.put((_TOK, tok))

    def _finish(self, result: dict) -> None:
        self._result = result
        self._done.set()
        self._q.put((_DONE, result))

    def _fail(self, exc: BaseException) -> None:
        self._error = exc
        self._done.set()
        self._q.put((_ERR, exc))

    # -- caller side ---------------------------------------------------------
    def stream(self, timeout: Optional[float] = None):
        """Yield tokens as they are generated; returns when the sequence
        finishes, raises its typed error if it was shed/failed, raises
        TimeoutError (like result()) when no token arrives in time."""
        while True:
            try:
                kind, val = self._q.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    "generation still in progress") from None
            if kind == _TOK:
                yield val
            elif kind == _DONE:
                return
            else:
                raise val

    def result(self, timeout: Optional[float] = None) -> dict:
        """Block until the sequence finishes; returns {"tokens",
        "finish_reason", "evictions", "prompt_len"}."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in progress")
        if self._error is not None:
            raise self._error
        return dict(self._result)

    def done(self) -> bool:
        return self._done.is_set()


class Sequence:
    """Scheduler-internal state of one generation request."""

    __slots__ = ("sid", "prompt", "max_new", "deadline_t", "priority",
                 "eos_id", "handle", "t_submit", "generated", "blocks",
                 "slot", "cached_len", "evictions", "ctx")

    def __init__(self, sid: int, prompt: List[int], max_new: int,
                 deadline_t: Optional[float], priority: int,
                 eos_id: Optional[int], handle: GenerationHandle):
        self.sid = sid
        self.prompt = prompt
        self.max_new = max_new
        self.deadline_t = deadline_t
        self.priority = priority
        self.eos_id = eos_id
        self.handle = handle
        self.t_submit = time.monotonic()
        self.generated: List[int] = []
        self.blocks: List[int] = []
        self.slot: Optional[int] = None
        #: pool positions holding this sequence's K/V; the LAST generated
        #: token is never cached (it is the next step's input)
        self.cached_len = 0
        self.evictions = 0
        #: submitter's trace context (the HTTP ingress span) — the
        #: scheduler thread parents this sequence's prefill/evict/resume
        #: events under it (obs/trace.py)
        self.ctx = obs_trace.current_context() if obs_trace.enabled() \
            else None

    @property
    def tokens_so_far(self) -> List[int]:
        return self.prompt + self.generated

    @property
    def remaining(self) -> int:
        return self.max_new - len(self.generated)


class DecodeScheduler:
    """One model's generation scheduler: a submission queue drained by
    one scheduler thread that interleaves prefill admission with
    fixed-shape decode steps over the in-flight slot batch.

    model: DecodeModel-like — max_prompt_len, max_context, slots,
    block_size, eos_id, prefill(tokens) -> (last_logits, kv_rows),
    seed_sequence(blocks, kv_rows), decode_step(tokens, lens, tables)
    -> logits [slots, vocab], free capacity given by the injected pool.
    """

    def __init__(self, model, pool: KVBlockPool,
                 admission: AdmissionController,
                 metrics: Optional[DecodeMetrics] = None, *,
                 continuous: bool = True, name: str = "model"):
        self.model = model
        self.pool = pool
        self.admission = admission
        self.metrics = metrics or DecodeMetrics(name)
        self.continuous = continuous
        self.name = name
        self._cv = threading.Condition()
        self._incoming: List[Sequence] = []
        self._waiting: List[Sequence] = []   # scheduler-thread-owned
        self._running: List[Sequence] = []   # scheduler-thread-owned
        self._load = 0                       # live sequences, any state
        self._next_sid = 0
        self._closed = False
        self._drained = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"pt-decode[{name}]")
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def queued(self) -> int:
        with self._cv:
            return self._load

    def submit(self, prompt: Seq[int], max_new: int,
               deadline_ms: Optional[float] = None, priority: int = 0,
               eos_id: Optional[int] = None) -> GenerationHandle:
        """Admit one generation request. Typed admission errors raise
        HERE (reject-fast); later shedding surfaces on the handle."""
        deadline_t = self.admission.deadline_for(deadline_ms)
        handle = GenerationHandle(len(prompt))
        with self._cv:
            if self._closed:
                raise ModelUnavailable(
                    f"decode engine {self.name!r} is shut down")
            try:
                self.admission.admit(self._load, deadline_t,
                                     model=self.name)
            except DeadlineExceeded:
                self.metrics.on_shed("deadline")
                raise
            except Exception:
                self.metrics.on_shed("overload")
                raise
            seq = Sequence(self._next_sid, list(prompt), int(max_new),
                           deadline_t, int(priority),
                           eos_id if eos_id is not None
                           else self.model.eos_id, handle)
            self._next_sid += 1
            self._incoming.append(seq)
            self._load += 1
            self.metrics.on_received()
            self._cv.notify()
        return handle

    def while_idle(self, fn):
        """Run fn() under the scheduler lock with ZERO live sequences —
        submit() blocks on the same lock, so nothing can be admitted (and
        no decode step can start) while fn mutates pool state. Raises if
        any sequence is live in any state (incoming/waiting/running)."""
        with self._cv:
            if self._load:
                raise RuntimeError(
                    f"engine {self.name!r} has {self._load} live "
                    "sequence(s); idle-only maintenance refused")
            return fn()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """drain=True generates every admitted sequence to completion
        first; drain=False fails the backlog fast."""
        with self._cv:
            self._closed = True
            self._drain_on_close = drain
            self._cv.notify()
        self._drained.wait(timeout)
        self._thread.join(timeout)

    # -- scheduler thread ----------------------------------------------------
    def _loop(self) -> None:
        self._drain_on_close = True
        try:
            while True:
                with self._cv:
                    while True:
                        if self._incoming:
                            self._waiting.extend(self._incoming)
                            self._incoming.clear()
                        if self._closed:
                            break
                        if self._waiting or self._running:
                            break
                        self._cv.wait()
                    if self._closed and not self._drain_on_close:
                        self._fail_backlog()
                    if self._closed and not (self._waiting
                                             or self._running):
                        return
                # heavy work outside the lock: only this thread touches
                # _waiting/_running
                self._shed_unmeetable()
                self._admit()
                self._step()
                self._publish_gauges()
        finally:
            self._drained.set()

    def _fail_backlog(self) -> None:
        for seq in self._waiting + self._running:
            self._terminate(seq, error=ModelUnavailable(
                f"decode engine {self.name!r} shut down before "
                "completion"))
        self._waiting.clear()
        self._running.clear()

    def _publish_gauges(self) -> None:
        self.metrics.set_gauges(
            active=len(self._running), waiting=len(self._waiting),
            blocks_in_use=self.pool.blocks_in_use,
            blocks_capacity=self.pool.capacity,
            high_water=self.pool.high_water)

    # -- terminal transitions ------------------------------------------------
    def _terminate(self, seq: Sequence, *, result: Optional[dict] = None,
                   error: Optional[BaseException] = None) -> None:
        """Free-on-finish: every block goes back to the pool, whatever
        the outcome."""
        if seq.blocks:
            self.pool.free(seq.blocks)
            seq.blocks = []
        seq.slot = None
        with self._cv:
            self._load -= 1
        if error is not None:
            self.metrics.on_finished(False)
            seq.handle._fail(error)
        else:
            self.metrics.on_finished(True)
            seq.handle._finish(result)

    def _finish(self, seq: Sequence, reason: str) -> None:
        self._terminate(seq, result={
            "tokens": list(seq.generated), "finish_reason": reason,
            "evictions": seq.evictions, "prompt_len": len(seq.prompt)})

    def _finish_reason(self, seq: Sequence, tok: int) -> Optional[str]:
        if seq.eos_id is not None and tok == seq.eos_id:
            return "eos"
        if len(seq.generated) >= seq.max_new:
            return "length"
        return None

    # -- deadline shedding ---------------------------------------------------
    def _shed_unmeetable(self) -> None:
        """Expired deadlines always shed; un-expired ones shed when the
        remaining-token estimate (tokens left x EWMA step seconds) says
        the deadline cannot be met — the cold engine (no estimate yet)
        never sheds on a guess."""
        now = time.monotonic()
        est = self.admission.estimated_batch_s()
        for lst in (self._waiting, self._running):
            for seq in list(lst):
                if seq.deadline_t is None:
                    continue
                expired = now >= seq.deadline_t
                unmeetable = (est is not None and
                              now + seq.remaining * est > seq.deadline_t)
                if expired or unmeetable:
                    lst.remove(seq)
                    self.metrics.on_shed("deadline")
                    why = ("deadline expired" if expired else
                           f"~{seq.remaining} tokens x {est * 1000:.1f} "
                           "ms/step exceed the deadline")
                    self._terminate(seq, error=DeadlineExceeded(
                        f"sequence shed: {why} (model {self.name!r})"))

    # -- eviction ------------------------------------------------------------
    def _evict(self, victim: Sequence) -> None:
        """Preempt: free blocks+slot, requeue at the waiting FRONT. If
        its grown context can no longer re-prefill (past the largest
        bucket), shed instead — resuming would be impossible."""
        self._running.remove(victim)
        self.pool.free(victim.blocks)
        victim.blocks = []
        victim.slot = None
        victim.cached_len = 0
        victim.evictions += 1
        self.metrics.on_evicted()
        obs_trace.instant("evict", cat="decode", parent=victim.ctx,
                          model=self.name, sid=victim.sid,
                          generated=len(victim.generated))
        if len(victim.tokens_so_far) > self.model.max_prompt_len:
            self.metrics.on_shed("overload")
            self._terminate(victim, error=Overloaded(
                f"evicted under KV-pool pressure and its context "
                f"({len(victim.tokens_so_far)} tokens) exceeds the "
                f"largest prefill bucket {self.model.max_prompt_len} — "
                "cannot resume (model {0!r})".format(self.name)))
        else:
            self._waiting.insert(0, victim)

    def _evict_for(self, seq: Sequence, need: int,
                   allow_peers: bool) -> bool:
        """Evict running sequences until `need` blocks are free. Victims
        must rank strictly below `seq` — lower priority, or (only when
        allow_peers, the mid-decode growth case, which guarantees the
        oldest sequence always progresses) same priority but younger."""

        def rank(s: Sequence):
            return (s.priority, -s.t_submit)   # low priority, young first

        while not self.pool.can_alloc(need):
            victims = [s for s in self._running if s is not seq
                       and (s.priority < seq.priority
                            or (allow_peers
                                and s.priority == seq.priority
                                and s.t_submit > seq.t_submit))]
            if not victims:
                return False
            self._evict(min(victims, key=rank))
        return True

    # -- admission (prefill) -------------------------------------------------
    def _admit(self) -> None:
        if not self._waiting:
            return
        if not self.continuous and self._running:
            return   # the static baseline: drain-to-empty barrier
        # priority first, then arrival order (evictees keep their
        # original t_submit, so they resume before younger peers)
        order = sorted(self._waiting, key=lambda s: (-s.priority,
                                                     s.t_submit))
        for seq in order:
            if len(self._running) >= self.model.slots:
                break
            tokens = seq.tokens_so_far
            need = self.pool.blocks_for_tokens(len(tokens))
            if not self.pool.can_alloc(need) and \
                    not self._evict_for(seq, need, allow_peers=False):
                continue   # stays waiting; capacity frees as others end
            self._waiting.remove(seq)
            if seq.evictions:
                self.metrics.on_resumed()
                obs_trace.instant("resume", cat="decode", parent=seq.ctx,
                                  model=self.name, sid=seq.sid)
            seq.blocks = self.pool.alloc(need)
            t0 = time.monotonic()
            try:
                last_logits, kv_rows = self.model.prefill(tokens)
                self.model.seed_sequence(seq.blocks, kv_rows)
            except Exception as e:  # noqa: BLE001 — typed + delivered
                self._terminate(seq, error=e if isinstance(
                    e, (Overloaded, DeadlineExceeded)) else
                    _request_failed(self.name, e))
                continue
            dt = time.monotonic() - t0
            self.metrics.on_prefill(len(tokens), dt)
            obs_trace.complete("prefill", dt, cat="decode",
                               parent=seq.ctx, model=self.name,
                               sid=seq.sid, tokens=len(tokens))
            seq.cached_len = len(tokens)
            tok = int(np.argmax(last_logits))
            seq.generated.append(tok)
            seq.handle._put_token(tok)
            reason = self._finish_reason(seq, tok)
            if reason is not None:
                self._finish(seq, reason)
                continue
            free_slots = [i for i in range(self.model.slots)
                          if all(r.slot != i for r in self._running)]
            seq.slot = free_slots[0]
            self._running.append(seq)

    # -- one decode step -----------------------------------------------------
    def _step(self) -> None:
        if not self._running:
            return
        # grow block capacity in priority order so the important
        # sequences claim blocks (and pick victims) first
        for seq in sorted(list(self._running),
                          key=lambda s: (-s.priority, s.t_submit)):
            if seq not in self._running:
                continue   # evicted by a higher-priority peer this pass
            need = (self.pool.blocks_for_tokens(seq.cached_len + 1)
                    - len(seq.blocks))
            if need <= 0:
                continue
            if not self.pool.can_alloc(need) and \
                    not self._evict_for(seq, need, allow_peers=True):
                # no victims rank below it and the pool is dry: preempt
                # ITSELF — resume when capacity frees. Progress is
                # guaranteed: the oldest highest-priority sequence always
                # either allocates or finds victims, so the pool drains
                # toward completion rather than thrashing. (A sequence
                # that can never fit at all was already shed typed at
                # submit by the engine's peak-residency check.)
                self._evict(seq)
                continue
            seq.blocks.extend(self.pool.alloc(need))
        active = list(self._running)
        if not active:
            return
        slots = self.model.slots
        tokens = np.zeros(slots, np.int64)
        lens = np.zeros(slots, np.int32)
        tables = np.zeros((slots, self.model.max_blocks_per_seq), np.int32)
        for seq in active:
            tokens[seq.slot] = seq.generated[-1]
            lens[seq.slot] = seq.cached_len + 1
            tables[seq.slot] = block_table_row(
                seq.blocks, self.model.max_blocks_per_seq)
        t0 = time.monotonic()
        logits = self.model.decode_step(tokens, lens, tables)
        dt = time.monotonic() - t0
        self.admission.observe_batch(dt)
        self.metrics.on_step(len(active), slots, dt, len(active))
        if obs_trace.enabled():
            # one fixed-shape dispatch serving every running sequence:
            # the span records which sids shared it (a single-sequence
            # step adopts that sequence's trace)
            obs_trace.complete(
                "decode_step", dt, cat="decode",
                parent=(active[0].ctx if len(active) == 1 else None),
                model=self.name, n=len(active),
                sids=[s.sid for s in active])
        for seq in active:
            tok = int(np.argmax(logits[seq.slot]))
            seq.cached_len += 1
            seq.generated.append(tok)
            seq.handle._put_token(tok)
            reason = self._finish_reason(seq, tok)
            if reason is not None:
                self._running.remove(seq)
                self._finish(seq, reason)


def _request_failed(name: str, cause: BaseException):
    from ..admission import RequestFailed
    return RequestFailed(
        f"decode engine {name!r} failed running prefill: {cause}",
        cause=cause)
