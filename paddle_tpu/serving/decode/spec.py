"""Speculative decoding: a cheap drafter proposes k tokens, the
fixed-shape decode step verifies them in ONE batched dispatch.

The verification trick costs no new artifact. Decode slots are
STATELESS — a slot is a row of the fixed-shape step, and all per-token
state lives in the block tables — so one sequence can occupy g = 1 + k
slots for one step: slot j carries input token x_{L+j} (j = 0 the
pending token, j >= 1 the drafts) with context_len L+1+j and the SAME
block table. The step's paged_kv_write scatters every slot's K/V row
(distinct positions L..L+g-1 of the shared table) before
paged_attention reads the pool, so slot j's attention over
[0, L+1+j) sees slots 0..j's fresh rows: the slot axis doubles as a
draft-chain axis. logits[slot j] then predicts position L+1+j exactly
as a sequential decode would have.

Greedy acceptance keeps the output BIT-IDENTICAL to plain decode:
emit e_0 = argmax(logits[slot 0]) — by construction the token plain
decode would emit — then accept e_j while the drafter's d_j equals
e_{j-1}; the first mismatch ends the chain. Rows written for rejected
positions are garbage but masked (context_len stops at the accepted
length) and rewritten before the mask ever reaches them — the same
argument that makes freed-block reuse safe.

Drafters (PT_SPEC_DRAFT):

    ngram       prompt-lookup decoding: propose the continuation that
                followed the most recent occurrence of the current
                n-gram earlier in the context. Zero extra model, wins
                on repetitive text (code, structured output).
    self        the target bundle's own prefill buckets re-predict the
                next k tokens greedily. Acceptance is 100% by
                construction — the deterministic upper bound the
                identity tests pin. A CORRECTNESS/TESTING harness, not
                a throughput win: each proposal runs k sequential
                full-context prefills on the scheduler thread, each
                costing more than the decode step being accelerated,
                and every peer's token cadence stalls while it drafts.
    <dir>       a separate (smaller) decode bundle loaded through the
                registry's ModelVersion machinery; its prefill side
                drafts greedily. The classic small-drafter setup — use
                this (or ngram) in production.

A drafter that crashes mid-step (chaos site `spec_verify`) degrades to
plain decode for that step — never kills the session.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

__all__ = ["NGramDrafter", "PrefillDrafter", "resolve_drafter",
           "accept_greedy"]


class NGramDrafter:
    """Prompt-lookup drafting: match the last `n` context tokens against
    earlier context; propose the k tokens that followed the most recent
    earlier occurrence. No model, no state."""

    name = "ngram"

    def __init__(self, n: int = 3):
        self.n = max(1, int(n))

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        toks = list(context)
        n = self.n
        if k < 1 or len(toks) <= n:
            return []
        tail = toks[-n:]
        # most recent earlier occurrence wins (locality beats frequency)
        for start in range(len(toks) - n - 1, -1, -1):
            if toks[start:start + n] == tail:
                cont = toks[start + n:start + n + k]
                if cont:
                    return [int(t) for t in cont]
        return []


class PrefillDrafter:
    """Greedy drafting through a prefill-capable model: k sequential
    next-token predictions, each one full-context prefill ON THE
    SCHEDULER THREAD. `model` needs prefill(tokens) ->
    (last_logits, kv_rows) and max_prompt_len — DecodeModel satisfies
    it, so `self` drafting reuses the target bundle (the deterministic
    100%-acceptance harness for identity tests; its drafting costs more
    than the steps it saves, so it is NOT a production speedup) and a
    drafter DIR loads its own smaller bundle, which is."""

    def __init__(self, model, name: str = "prefill"):
        self.model = model
        self.name = name

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        toks = [int(t) for t in context]
        out: List[int] = []
        for _ in range(max(0, int(k))):
            if len(toks) > self.model.max_prompt_len:
                break   # the drafter's buckets cap its reach, not ours
            logits, _ = self.model.prefill(toks)
            tok = int(np.argmax(logits))
            out.append(tok)
            toks.append(tok)
        return out


def resolve_drafter(spec: Optional[str], model):
    """PT_SPEC_DRAFT -> a drafter: '' / None / '0' = off, 'ngram' =
    NGramDrafter, 'self' = the target's own prefill, anything else = a
    decode-bundle directory loaded fresh (warmup skipped — the drafter
    only prefills)."""
    if not spec or spec in ("0", "off", "none"):
        return None
    if spec == "ngram":
        return NGramDrafter()
    if spec == "self":
        return PrefillDrafter(model, name="self")
    from .engine import DecodeModel
    return PrefillDrafter(DecodeModel(spec, warmup=False), name=spec)


def accept_greedy(drafts: Sequence[int],
                  emitted: Sequence[int]) -> List[int]:
    """The acceptance rule, pure for testing. `emitted[j]` is
    argmax(logits[slot j]); `drafts[j]` fed slot j+1. Returns the token
    chain to emit: e_0 always (plain decode's token), then e_{j+1}
    while drafts[j] == e_j."""
    out = [int(emitted[0])]
    for j, d in enumerate(drafts):
        if int(d) != out[-1] or j + 1 >= len(emitted):
            break
        out.append(int(emitted[j + 1]))
    return out
