"""paddle_tpu.serving.fleet — the replica tier over the serving engine.

One ServingEngine is one process's worth of serving; the ROADMAP's
"millions of users" is a FLEET of them. This package is the tier in
front: N engine replicas (pool.py), a priority-admitted router
(router.py + admission.py) doing least-loaded / session-affine
dispatch with crash failover, and a metrics-driven autoscaler
(autoscaler.py) — all reporting as the pt_fleet_* family on the
one-pane exposition (metrics.py).

    ReplicaPool          N ServingEngines; zero-drop scale up/down
                         (build-warm-swap-drain, per replica); crashed
                         replicas rebuilt off to the side
    FleetRouter          WFQ priority admission (lowest-class-first
                         shed), least_loaded / round_robin policies,
                         per-request session affinity (rendezvous
                         hash), RequestFailed failover via RetryPolicy
    Autoscaler           queue-depth + EWMA control loop w/ hysteresis
    FleetMetrics         pt_fleet_* provider on the unified registry

Knobs (constructor args win; declared in paddle_tpu/flags.py):

    PT_FLEET_REPLICAS    initial replica count (default 1)
    PT_FLEET_MIN         scale floor (default 1)
    PT_FLEET_MAX         scale ceiling (default 8)
    PT_FLEET_POLICY      least_loaded (default) | round_robin
    PT_FLEET_AUTOSCALE   1 = make_fleet attaches + starts an Autoscaler

See docs/serving.md "Fleet tier".
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from .admission import PendingRequest, WeightedFairQueue, default_weight
from .autoscaler import Autoscaler
from .metrics import FleetMetrics
from .pool import Replica, ReplicaPool
from .router import POLICIES, FleetRouter, crash_failover

__all__ = ["ReplicaPool", "Replica", "FleetRouter", "Autoscaler",
           "FleetMetrics", "WeightedFairQueue", "PendingRequest",
           "POLICIES", "default_weight", "crash_failover", "make_fleet"]


def make_fleet(loader: Callable, *, replicas: Optional[int] = None,
               policy: Optional[str] = None,
               autoscale: Optional[bool] = None,
               autoscaler_opts: Optional[dict] = None,
               pool_opts: Optional[dict] = None,
               **router_opts) -> FleetRouter:
    """Deployment convenience: pool + router (+ autoscaler when
    PT_FLEET_AUTOSCALE / autoscale=True) in one call. `loader(engine,
    rid)` loads this fleet's models into each fresh replica engine."""
    pool = ReplicaPool(loader, replicas=replicas, **(pool_opts or {}))
    try:
        router = FleetRouter(pool, policy=policy, **router_opts)
    except BaseException:
        # the pool already built+warmed N live engines; a router that
        # refuses (e.g. a typo'd PT_FLEET_POLICY) must not leak their
        # dispatcher threads for the process lifetime
        pool.close(drain=False)
        raise
    if autoscale is None:
        autoscale = os.environ.get("PT_FLEET_AUTOSCALE",
                                   "").strip().lower() in ("1", "true",
                                                           "on", "yes")
    if autoscale:
        router.autoscaler = Autoscaler(pool, metrics=router.metrics,
                                       **(autoscaler_opts or {}))
        router.autoscaler.start()
    return router
