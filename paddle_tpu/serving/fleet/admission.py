"""Fleet admission: priority classes + weighted-fair queueing + strict
lowest-class-first shedding.

The single-engine admission layer (serving/admission.py) answers "can
THIS queue take one more request". The fleet front door answers a
different question: when the whole tier is overloaded, WHO gets served
and WHO gets shed. Two mechanisms, deliberately separate:

  service order   weighted-fair queueing (virtual-time WFQ) across
                  priority classes: when every class is backlogged,
                  class c receives dispatch slots in proportion to its
                  weight (default ``2**c``), so paid traffic is served
                  faster WITHOUT starving the free tier — a pure
                  priority queue would.
  shed order      strictly lowest-class-first: when the router queue is
                  full, the victim is always the NEWEST request of the
                  LOWEST occupied class. An arriving request sheds an
                  already-queued lower-class request (and takes its
                  slot); an arriving request OF the lowest class is
                  itself shed. Free tier always absorbs overload before
                  paid tier — the typed `Overloaded` carries
                  ``shed_class`` so clients and metrics both see which
                  class paid.

The queue stores `PendingRequest`s — the router's unit of dispatch,
carrying the priority class, the optional session key, the deadline,
and the failover bookkeeping (replicas already tried).
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

from ..admission import Overloaded

__all__ = ["PendingRequest", "WeightedFairQueue", "default_weight",
           "MAX_CLASS"]

#: priority classes clamp to [0, MAX_CLASS]: the class is a CLIENT
#: input (HTTP `priority` field), and an unbounded one would overflow
#: the default doubling weight (2.0**2000 -> OverflowError in pop(),
#: killing the dispatcher thread) or starve every lower class behind a
#: 1/2**N virtual clock that never advances. 16 doublings (weight
#: 65536) is already far steeper than any real tiering needs.
MAX_CLASS = 16


def default_weight(cls: int) -> float:
    """Class weight for WFQ service shares: each class up doubles the
    share. Override per-router via class_weights={cls: weight}."""
    return 2.0 ** min(int(cls), MAX_CLASS)


class PendingRequest:
    """One admitted-but-undispatched fleet request."""

    __slots__ = ("model", "feeds", "cls", "session", "deadline_t",
                 "future", "t_enqueue", "tried", "result_retries",
                 "last_error")

    def __init__(self, model: str, feeds, *, cls: int = 0,
                 session: Optional[str] = None,
                 deadline_t: Optional[float] = None):
        self.model = model
        self.feeds = feeds
        self.cls = min(max(0, int(cls)), MAX_CLASS)
        self.session = session
        self.deadline_t = deadline_t
        self.future: Future = Future()
        self.t_enqueue = time.monotonic()
        #: replica ids this request already failed on (failover skips)
        self.tried: set = set()
        #: completed-with-RequestFailed retries consumed (failover cap)
        self.result_retries = 0
        #: the typed error of the newest failed attempt — when every
        #: replica has been tried, the ORIGINAL failure surfaces, never
        #: a "no replica left" wrapper (the retry-layer contract)
        self.last_error: Optional[BaseException] = None


class WeightedFairQueue:
    """Bounded multi-class queue with virtual-time weighted-fair pops.

    Not thread-safe by itself — the router serializes access under its
    own condition variable (the queue is a policy object, not a
    synchronization one).
    """

    def __init__(self, queue_depth: int,
                 class_weights: Optional[Dict[int, float]] = None,
                 weight: Callable[[int], float] = default_weight):
        if queue_depth < 1:
            raise ValueError("queue_depth must be >= 1")
        self.queue_depth = int(queue_depth)
        # coerce NOW: a malformed weight must refuse at construction,
        # typed — not surface as a TypeError inside pop() on the
        # dispatcher thread the first time that class is served
        self._weights = {int(c): float(w)
                         for c, w in (class_weights or {}).items()}
        self._weight_fn = weight
        self._q: Dict[int, deque] = {}
        self._vtime: Dict[int, float] = {}
        self._v0 = 0.0   # virtual time of the most recent pop

    def weight(self, cls: int) -> float:
        w = self._weights.get(cls)
        if w is None:
            w = self._weight_fn(cls)
        return max(float(w), 1e-9)

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

    def depths(self) -> Dict[int, int]:
        return {c: len(q) for c, q in sorted(self._q.items()) if q}

    # -- admission -----------------------------------------------------------
    def offer(self, item: PendingRequest) -> Optional[PendingRequest]:
        """Admit `item`, or decide who sheds. Returns the evicted
        victim (caller fails its future, typed) when a lower-class
        request made room; raises Overloaded(shed_class=item.cls) when
        `item` itself is the lowest class present. Never drops silently.
        """
        victim: Optional[PendingRequest] = None
        if len(self) >= self.queue_depth:
            occupied = [c for c, q in self._q.items() if q]
            low = min(occupied) if occupied else item.cls
            if not occupied or low >= item.cls:
                raise Overloaded(
                    f"fleet queue at capacity ({len(self)}/"
                    f"{self.queue_depth}); class {item.cls} is the "
                    "lowest present — shed", shed_class=item.cls)
            # newest of the lowest class: it has invested the least
            # wait, and the oldest is closest to service
            victim = self._q[low].pop()
            if not self._q[low]:
                del self._q[low]
        q = self._q.get(item.cls)
        if q is None:
            q = self._q[item.cls] = deque()
            # a class waking from idle must not replay its unused
            # history: catch its virtual time up to the active frontier
            self._vtime[item.cls] = max(
                self._vtime.get(item.cls, 0.0), self._v0)
        q.append(item)
        return victim

    def push_front(self, item: PendingRequest) -> None:
        """Return a popped-but-undispatchable request to the head of
        its class (router backpressure: every replica queue is full —
        the request keeps its place, the fleet queue keeps backing up,
        and the shed machinery above engages). May transiently exceed
        queue_depth by the in-flight item; offer() uses >=."""
        q = self._q.get(item.cls)
        if q is None:
            q = self._q[item.cls] = deque()
            self._vtime[item.cls] = max(
                self._vtime.get(item.cls, 0.0), self._v0)
        q.appendleft(item)

    # -- service -------------------------------------------------------------
    def pop(self) -> Optional[PendingRequest]:
        """Next request in weighted-fair order (smallest virtual finish
        time; its class's clock advances by 1/weight)."""
        active = [(self._vtime[c], c) for c, q in self._q.items() if q]
        if not active:
            return None
        vt, cls = min(active)
        item = self._q[cls].popleft()
        if not self._q[cls]:
            del self._q[cls]
        self._v0 = vt
        self._vtime[cls] = vt + 1.0 / self.weight(cls)
        return item

    def drain(self) -> List[PendingRequest]:
        """Everything still queued, service order preserved per class."""
        out: List[PendingRequest] = []
        while True:
            item = self.pop()
            if item is None:
                return out
            out.append(item)
