"""Metrics-driven autoscaling with hysteresis.

The control loop reads the SAME two signals the router dispatches on —
per-replica queue depth and the admission EWMA of batch service time
(pool.health(), i.e. the pt_fleet_replica_* gauges) — and scales the
pool between min and max replicas:

  scale UP fast    pressure (mean queued-per-replica, or mean backlog
                   seconds = depth x EWMA) above the up threshold for
                   `up_after` consecutive ticks (default 2) adds one
                   replica. Sustained depth is the honest signal; a
                   single bursty tick is not.
  scale DOWN slow  pressure below the down threshold for `down_after`
                   consecutive ticks (default 8) — an idle WINDOW, not
                   an idle moment — retires one replica (zero-drop:
                   pool.scale_to drains it). Never below min_replicas.
  hysteresis       the up and down thresholds are far apart, streaks
                   reset on every crossing, and every scale event
                   resets both streaks — an oscillating load that
                   alternates across a single threshold can never flap
                   the pool, which the hysteresis test drives tick by
                   tick with a synthetic health feed.

Every decision is logged as a `trace.instant` (cat="fleet") and counted
in the pt_fleet_scale_events_total metric. Armed in make_fleet by
PT_FLEET_AUTOSCALE=1; PT_FLEET_MIN/PT_FLEET_MAX bound it (pool knobs).
The loop itself is clock- and health-injectable so tests drive the
hysteresis math deterministically, no threads, no sleeps.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from ...obs import trace as obs_trace

__all__ = ["Autoscaler"]


class Autoscaler:
    def __init__(self, pool, *, interval_s: float = 0.5,
                 up_depth: float = 4.0, down_depth: float = 0.5,
                 up_backlog_s: float = 1.0,
                 down_backlog_s: Optional[float] = None,
                 up_after: int = 2, down_after: int = 8,
                 metrics=None,
                 health: Optional[Callable[[], Dict[str, dict]]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if up_depth <= down_depth:
            raise ValueError("up_depth must exceed down_depth "
                             "(hysteresis band)")
        self.pool = pool
        self.interval_s = float(interval_s)
        self.up_depth = float(up_depth)
        self.down_depth = float(down_depth)
        self.up_backlog_s = float(up_backlog_s)
        # the backlog signal needs its OWN band: one shared threshold
        # in both predicates lets a steady load hover across it and
        # flap the pool (scale up spreads the backlog below the line,
        # scale down re-concentrates it above)
        self.down_backlog_s = (self.up_backlog_s / 4.0
                               if down_backlog_s is None
                               else float(down_backlog_s))
        if self.up_backlog_s <= self.down_backlog_s:
            raise ValueError("up_backlog_s must exceed down_backlog_s "
                             "(hysteresis band)")
        self.up_after = max(1, int(up_after))
        self.down_after = max(1, int(down_after))
        self.metrics = metrics
        self._health = health or pool.health
        self._clock = clock
        self._up_streak = 0
        self._down_streak = 0
        self._ticks = 0
        self._decisions = 0
        self._last_pressure = 0.0
        self._last_backlog_s = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the signal ----------------------------------------------------------
    def _read(self) -> None:
        health = [h for h in self._health().values()
                  if h.get("healthy", True)]
        if not health:
            self._last_pressure = 0.0
            self._last_backlog_s = 0.0
            return
        depths = [float(h.get("queue_depth") or 0) for h in health]
        backlog = [d * float(h.get("ewma_ms") or 0.0) / 1e3
                   for d, h in zip(depths, health)]
        self._last_pressure = sum(depths) / len(depths)
        self._last_backlog_s = sum(backlog) / len(backlog)

    # -- the decision (pure math — tests call tick() directly) --------------
    def tick(self) -> Optional[str]:
        """One control iteration. Returns "up" / "down" on a scale
        decision, None on hold."""
        self._ticks += 1
        if self.pool.size() < self.pool.min_replicas:
            # heal first: a pool left below its floor by failed
            # rebuilds reads pressure 0 from its empty health (the
            # hot condition could never fire) — the floor is a
            # contract, not a signal
            if self.pool.ensure_min():
                obs_trace.instant("fleet_scale", cat="fleet",
                                  direction="heal",
                                  replicas=self.pool.size())
        self._read()
        hot = (self._last_pressure >= self.up_depth
               or self._last_backlog_s >= self.up_backlog_s)
        idle = (self._last_pressure <= self.down_depth
                and self._last_backlog_s < self.down_backlog_s)
        if hot:
            self._up_streak += 1
            self._down_streak = 0
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
        else:
            # the hysteresis band is neutral ground: BOTH streaks
            # reset, so a load hovering between the thresholds holds
            # the current size and never accumulates toward a decision
            self._up_streak = 0
            self._down_streak = 0
        n = self.pool.size()
        decision = None
        if (self._up_streak >= self.up_after
                and n < self.pool.max_replicas):
            decision = "up"
            target = n + 1
        elif (self._down_streak >= self.down_after
                and n > self.pool.min_replicas):
            decision = "down"
            target = n - 1
        if decision is None:
            return None
        try:
            ok = self.pool.scale_to(
                target, reason=f"autoscale_{decision}") == target
        except BaseException:   # noqa: BLE001 — a loader failure mid
            # scale-up must not kill the loop OR be recorded as a
            # scale event; streaks stay hot so the retry is immediate
            ok = False
        if not ok:
            return None
        # record only what actually happened: counters, trace, and the
        # streak reset all follow the SUCCESSFUL scale
        self._up_streak = self._down_streak = 0
        self._decisions += 1
        obs_trace.instant(
            "fleet_scale", cat="fleet", direction=decision,
            replicas=target,
            pressure=round(self._last_pressure, 3),
            backlog_s=round(self._last_backlog_s, 4))
        if self.metrics is not None:
            self.metrics.on_scale(decision)
        return decision

    # -- the loop ------------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pt-fleet-autoscaler")
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — a flaky health read
                # must not kill the control loop; the next tick retries
                pass

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(5.0)

    def describe(self) -> dict:
        return {"running": self._thread is not None,
                "interval_s": self.interval_s,
                "min_replicas": self.pool.min_replicas,
                "max_replicas": self.pool.max_replicas,
                "up_depth": self.up_depth,
                "down_depth": self.down_depth,
                "up_backlog_s": self.up_backlog_s,
                "down_backlog_s": self.down_backlog_s,
                "up_after": self.up_after,
                "down_after": self.down_after,
                "ticks": self._ticks,
                "decisions": self._decisions,
                "last_pressure": round(self._last_pressure, 3),
                "last_backlog_s": round(self._last_backlog_s, 4)}
