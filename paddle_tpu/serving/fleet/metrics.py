"""Fleet metrics: the pt_fleet_* family on the one-pane exposition.

One provider per router, registered in the unified MetricsRegistry
(obs/metrics.py) under section "fleet" — so the same scrape that
carries pt_serve_*/pt_decode_*/pt_train_* carries the tier above them:
replica count, per-replica depth/health (pulled LIVE from the pool at
snapshot time — the same queue-depth/EWMA pair the router dispatches
on), dispatch counts per policy, sheds per class, failovers/rebuilds,
and autoscaler decisions.

Counters are recorded by the router/autoscaler; gauges are derived at
snapshot time from weakly-referenced sources (pool, router) so an
abandoned fleet neither pins memory nor keeps reporting — the registry
convention.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable, Dict, Optional

from ...obs.metrics import REGISTRY

__all__ = ["FleetMetrics"]


class FleetMetrics:
    """One fleet's counters + live-derived gauges. Thread-safe: the
    router's dispatcher, the autoscaler loop, and HTTP scrapes all
    touch it concurrently."""

    def __init__(self, name: str = "fleet",
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._pool = None       # weakref, set by the router
        self._router = None     # weakref
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._clock()
            self.dispatched: Dict[str, int] = {}
            self.completed = 0
            self.failed = 0
            self.sheds: Dict[int, int] = {}
            self.sheds_deadline: Dict[int, int] = {}
            self.failovers = 0
            self.rebuilds = 0
            self.scale_up_events = 0
            self.scale_down_events = 0

    # -- wiring --------------------------------------------------------------
    def bind(self, pool=None, router=None) -> None:
        if pool is not None:
            self._pool = weakref.ref(pool)
        if router is not None:
            self._router = weakref.ref(router)

    def register(self) -> None:
        """Join the process-wide exposition (weakref section 'fleet');
        the router holds the strong reference. A second fleet in the
        same process under the same name gets a numeric suffix instead
        of silently shadowing the first (and unregistering the first
        must never take the second off the scrape) — the probe and the
        insert are one atomic registry operation, so concurrently
        constructed fleets can't race past each other either."""
        self.name = REGISTRY.register_unique("fleet", self.name, self)

    def unregister(self) -> None:
        REGISTRY.unregister("fleet", self.name)

    # -- recording -----------------------------------------------------------
    def on_dispatch(self, policy: str) -> None:
        with self._lock:
            self.dispatched[policy] = self.dispatched.get(policy, 0) + 1

    def on_done(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.completed += 1
            else:
                self.failed += 1

    def on_shed(self, cls: int, kind: str = "overload") -> None:
        with self._lock:
            book = (self.sheds if kind == "overload"
                    else self.sheds_deadline)
            book[int(cls)] = book.get(int(cls), 0) + 1

    def on_failover(self) -> None:
        with self._lock:
            self.failovers += 1

    def on_rebuild(self) -> None:
        with self._lock:
            self.rebuilds += 1

    def on_scale(self, direction: str) -> None:
        with self._lock:
            if direction == "up":
                self.scale_up_events += 1
            else:
                self.scale_down_events += 1

    # -- reading -------------------------------------------------------------
    def _live(self, ref) -> Optional[object]:
        return ref() if ref is not None else None

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "name": self.name,
                "completed": self.completed,
                "failed": self.failed,
                "failovers": self.failovers,
                "rebuilds": self.rebuilds,
                "dispatched": dict(self.dispatched),
                "sheds": {str(c): n for c, n in
                          sorted(self.sheds.items())},
                "sheds_deadline": {str(c): n for c, n in
                                   sorted(self.sheds_deadline.items())},
                "scale_events": {"up": self.scale_up_events,
                                 "down": self.scale_down_events},
                "window_s": round(max(self._clock() - self._t0, 1e-9),
                                  3),
            }
        pool = self._live(self._pool)
        if pool is not None:
            out["replicas"] = pool.size()
            out["replica_health"] = pool.health()
        router = self._live(self._router)
        if router is not None:
            out["policy"] = router.policy
            out["queue_depths"] = {str(c): n for c, n in
                                   router.queue_depths().items()}
        return out
