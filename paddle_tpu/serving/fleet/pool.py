"""Replica pool: N worker replicas, each a full ServingEngine.

One `ServingEngine` in one process is one dispatcher thread per model —
correct, but a single engine cannot be "millions of users". The pool
hosts N engines (replicas) side by side in this process, each with its
own batcher/registry/metrics, so a router (fleet/router.py) can spread
independent requests across N concurrent dispatch pipelines. Replicas
are thread-hosted (the engines' own dispatcher threads), so tier-1
exercises the whole tier on CPU.

Health is NOT a second bookkeeping path: a replica's queue depth is the
same `ModelMetrics.queue_depth` gauge its `pt_serve_*` exposition
exports, and its service-time estimate is the same admission EWMA that
deadline shedding uses (serving/admission.py observe_batch). The router
and the autoscaler read the numbers the metrics plane already
maintains — the PR-12 "the router is the metrics plane's first
consumer" contract.

Scale contract (the PR-5 build-warm-swap-drain contract, at replica
granularity):

  scale UP    new engines are built + model-loaded (warmup included)
              entirely off to the side; they join the routing set only
              once serving-ready — a scale-up can slow nothing down.
  scale DOWN  the retiring replica leaves the routing set FIRST, then
              its engine is shut down with drain=True: every request
              already queued on it is served before release. Zero
              in-flight futures are dropped, by construction.
  rebuild     a replica marked unhealthy (router failover on a crashed
              dispatch) leaves the routing set immediately; a fresh
              engine is built off to the side on a background thread
              and swaps into the same replica id (session affinity
              keys on the id, so rebuilt replicas keep their
              sessions). The old engine still drains what it can.

Knob defaults (constructor args win): PT_FLEET_REPLICAS initial size,
PT_FLEET_MIN / PT_FLEET_MAX the scale bounds.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ...obs import trace as obs_trace
from .. import ServingEngine
from ..batcher import env_int

__all__ = ["Replica", "ReplicaPool", "REBUILD_ATTEMPTS"]

#: bounded rebuild retries (short exponential backoff between) before a
#: crashed replica's slot is surrendered
REBUILD_ATTEMPTS = 3

#: least-loaded scoring needs a service-time guess before the first
#: real batch lands (admission's EWMA starts at None); 1 ms keeps the
#: score ordered by queue depth alone until a real estimate exists
DEFAULT_SERVICE_S = 1e-3


class Replica:
    """One worker: a replica id + the ServingEngine it hosts. The id is
    the stable routing identity — a rebuilt replica keeps its id (and
    therefore its affine sessions); the engine behind it is disposable.
    """

    __slots__ = ("rid", "engine", "healthy")

    def __init__(self, rid: str, engine: ServingEngine):
        self.rid = rid
        self.engine = engine
        self.healthy = True

    # -- the health signal (read from the metrics plane) ---------------------
    def signals(self) -> tuple:
        """(queue_depth, ewma_s) in ONE registry walk — the router's
        least-loaded score reads both per candidate per dispatch, so
        the walk (and its per-model lock traffic) happens once. Depth
        is the same per-model queue_depth gauge pt_serve_* exports,
        summed; the estimate is the largest per-model admission EWMA
        of batch service seconds (None until any model has served a
        batch)."""
        depth = 0
        est: Optional[float] = None
        for name in self.engine.registry.names():
            depth += max(0,
                         int(self.engine.metrics.model(name).queue_depth))
            try:
                s = self.engine.registry.get(name).batcher \
                    .service_estimate_s()
            except Exception:   # noqa: BLE001 — racing an unload
                continue
            if s is not None and (est is None or s > est):
                est = s
        return depth, est

    def queue_depth(self) -> int:
        return self.signals()[0]

    def service_estimate_s(self) -> Optional[float]:
        return self.signals()[1]

    def load_score(self) -> float:
        """queue-depth x EWMA-service-time: the least-loaded ranking
        key. +1 on depth so an idle replica with a slow history still
        ranks by its service time, not at exactly zero."""
        depth, est = self.signals()
        return (depth + 1) * (est if est is not None
                              else DEFAULT_SERVICE_S)

    def decode_residency(self) -> Optional[dict]:
        """Shared-KV residency + speculative acceptance, summed over
        this replica's decode engines (None when it hosts none). A
        session's cached prefix is replica-local state the rendezvous
        hash should respect: the router already keys sessions onto
        replica ids; this makes the *value* of that affinity (resident
        shared blocks, warm prefix index) visible next to queue depth
        in the same health dict operators and the autoscaler read."""
        engines = {name: eng
                   for name, eng in self.engine.decode_engines().items()
                   if hasattr(eng, "kv_residency")}  # duck-typed fakes
        if not engines:
            return None
        out = {"kv_blocks_shared": 0, "kv_blocks_in_use": 0,
               "kv_blocks_indexed": 0, "prefix_hits": 0,
               "prefix_hit_tokens": 0}
        drafted = accepted = 0
        for eng in engines.values():
            for key, val in eng.kv_residency().items():
                out[key] = out.get(key, 0) + int(val)
            snap = eng.metrics.snapshot()
            drafted += int(snap.get("spec_drafted", 0) or 0)
            accepted += int(snap.get("spec_accepted", 0) or 0)
        out["spec_acceptance_rate"] = (round(accepted / drafted, 4)
                                       if drafted else None)
        return out

    def health(self) -> dict:
        depth, est = self.signals()
        out = {"queue_depth": depth,
               "ewma_ms": None if est is None else round(est * 1e3, 3),
               "healthy": bool(self.healthy)}
        decode = self.decode_residency()
        if decode is not None:
            out["decode"] = decode
        return out


class ReplicaPool:
    """N replicas behind one build/scale/rebuild lifecycle.

    `loader(engine, rid)` populates a fresh engine with this fleet's
    models (load_model / load_model_object / load_decode_model) — the
    pool stays free of model-source policy, exactly like the registry
    stays free of queueing policy.
    """

    def __init__(self, loader: Callable[[ServingEngine, str], None], *,
                 replicas: Optional[int] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 engine_opts: Optional[dict] = None,
                 metrics=None):
        self._loader = loader
        self._engine_opts = dict(engine_opts or {})
        self.min_replicas = max(1, env_int("PT_FLEET_MIN", 1)
                                if min_replicas is None
                                else int(min_replicas))
        self.max_replicas = max(self.min_replicas,
                                env_int("PT_FLEET_MAX", 8)
                                if max_replicas is None
                                else int(max_replicas))
        n = (env_int("PT_FLEET_REPLICAS", 1) if replicas is None
             else int(replicas))
        n = min(max(n, self.min_replicas), self.max_replicas)
        self.metrics = metrics
        self._lock = threading.Lock()
        #: serializes scale/rebuild transitions (builds run unlocked)
        self._scale_lock = threading.Lock()
        self._replicas: Dict[str, Replica] = {}
        self._next_id = 0
        self._closed = False
        try:
            self.scale_to(n, reason="initial")
        except BaseException:
            # a later replica's build failed mid-scale: the ones
            # already published must not leak their dispatcher threads
            # for the process lifetime (the make_fleet lesson, at pool
            # altitude)
            self.close(drain=False)
            raise

    # -- introspection -------------------------------------------------------
    def replicas(self) -> List[Replica]:
        """Routing candidates: healthy replicas, in stable id order."""
        with self._lock:
            reps = sorted(self._replicas.values(),
                          key=lambda r: int(r.rid[1:]))
        return [r for r in reps if r.healthy]

    def all_replicas(self) -> List[Replica]:
        with self._lock:
            return sorted(self._replicas.values(),
                          key=lambda r: int(r.rid[1:]))

    def get(self, rid: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(rid)

    def size(self) -> int:
        with self._lock:
            return len(self._replicas)

    def health(self) -> Dict[str, dict]:
        return {r.rid: r.health() for r in self.all_replicas()}

    # -- lifecycle -----------------------------------------------------------
    def _build(self, rid: str) -> Replica:
        engine = ServingEngine(**self._engine_opts)
        # namespace this engine's pt_serve_*/pt_decode_* series: two
        # replicas serving the same model name must scrape as distinct
        # series (serving/metrics.py replica label)
        engine.metrics.replica = rid
        try:
            self._loader(engine, rid)
        except BaseException:
            engine.shutdown(drain=False)
            raise
        return Replica(rid, engine)

    def scale_to(self, n: int, reason: str = "") -> int:
        """Grow or shrink to `n` replicas (clamped to [min, max]).
        Returns the resulting size. Scale-down BLOCKS until the retiring
        replicas have drained — callers on a control loop get zero-drop
        for free; nobody races a half-dead replica because it leaves the
        routing set before its drain begins."""
        with self._scale_lock:
            return self._scale_locked(n, reason)

    def _scale_locked(self, n: int, reason: str) -> int:
        """scale_to's body; caller holds _scale_lock."""
        if self._closed:
            return self.size()
        n = min(max(int(n), self.min_replicas), self.max_replicas)
        # -- up: build off to the side, publish when serving-ready
        while self.size() < n:
            with self._lock:
                rid = f"r{self._next_id}"
                self._next_id += 1
            replica = self._build(rid)
            with self._lock:
                self._replicas[rid] = replica
            obs_trace.instant("fleet_replica_up", cat="fleet",
                              replica=rid, reason=reason,
                              replicas=self.size())
        # -- down: newest-first leaves routing, then drains
        retiring: List[Replica] = []
        with self._lock:
            while len(self._replicas) > n:
                rid = max(self._replicas,
                          key=lambda r: int(r[1:]))
                rep = self._replicas.pop(rid)
                rep.healthy = False
                retiring.append(rep)
        for rep in retiring:
            rep.engine.shutdown(drain=True)
            obs_trace.instant("fleet_replica_down", cat="fleet",
                              replica=rep.rid, reason=reason,
                              replicas=self.size())
        return self.size()

    def ensure_min(self) -> bool:
        """Heal toward min_replicas: a pool left below the floor by
        crash-surrendered slots (every rebuild attempt failed) mints
        fresh replicas as soon as the loader works again. Returns True
        when replicas were actually added; False when nothing was
        needed, the loader is still refusing, or a scale operation is
        already in flight. NEVER blocks on the scale lock: callers
        include replica dispatcher threads (router failover), and a
        blocking wait could deadlock against a scale-down draining
        that very dispatcher's engine — try-acquire, or step aside."""
        if self._closed or self.size() >= self.min_replicas:
            return False
        if not self._scale_lock.acquire(blocking=False):
            return False
        before = self.size()
        try:
            self._scale_locked(self.min_replicas, "heal_min")
        except BaseException:   # noqa: BLE001 — loader still (or
            # partially) down; anything that DID publish before the
            # failure still counts below, and the next request (or
            # autoscaler tick) retries the rest
            pass
        finally:
            self._scale_lock.release()
        return self.size() > before

    def mark_unhealthy(self, rid: str, cause: str = "",
                       replica: Optional[Replica] = None) -> bool:
        """Failover path: take `rid` out of routing NOW and rebuild its
        engine off to the side on a background thread. Idempotent —
        concurrent failovers on the same replica rebuild once. Callers
        holding the Replica object pass it: the slot is only condemned
        if it still holds THAT replica, so a straggler failure from an
        already-replaced engine (a late future off the drained old
        dispatcher) can never tear down the freshly rebuilt one."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or not rep.healthy or self._closed:
                return False
            if replica is not None and rep is not replica:
                return False    # stale failure: the slot moved on
            rep.healthy = False
        obs_trace.instant("fleet_replica_unhealthy", cat="fleet",
                          replica=rid, cause=cause)
        if self.metrics is not None:
            self.metrics.on_rebuild()
        t = threading.Thread(target=self._rebuild, args=(rid, rep),
                             daemon=True, name=f"pt-fleet-rebuild[{rid}]")
        t.start()
        return True

    def _rebuild(self, rid: str, dead: Replica) -> None:
        fresh: Optional[Replica] = None
        for attempt in range(REBUILD_ATTEMPTS):
            try:
                fresh = self._build(rid)
                break
            except BaseException as e:  # noqa: BLE001 — a failed
                # rebuild must not kill the pool; bounded retries ride
                # out transient loader failures, each visible on the
                # trace
                obs_trace.instant("fleet_rebuild_failed", cat="fleet",
                                  replica=rid, attempt=attempt,
                                  error=f"{type(e).__name__}")
                time.sleep(0.05 * (2.0 ** attempt))
        with self._lock:
            if self._closed or self._replicas.get(rid) is not dead:
                # the slot moved on (scale-down raced us): discard
                published = False
            elif fresh is None:
                # every attempt failed: give the slot up so size()
                # tells the operator the truth (an unhealthy zombie
                # counted as capacity would mask a dead fleet) — the
                # autoscaler's next scale-up mints a fresh slot
                del self._replicas[rid]
                published = False
            else:
                self._replicas[rid] = fresh
                published = True
        if published:
            obs_trace.instant("fleet_replica_rebuilt", cat="fleet",
                              replica=rid)
        elif fresh is not None:
            fresh.engine.shutdown(drain=False)
        else:
            obs_trace.instant("fleet_replica_lost", cat="fleet",
                              replica=rid, replicas=self.size())
        try:
            # the dead engine may still hold queued work — drain it on
            # EVERY path: its dispatcher survives batch crashes (the
            # per-batch containment contract), so queued futures get
            # served or failed typed, never stranded
            dead.engine.shutdown(drain=True)
        except Exception:   # noqa: BLE001 — it was already dead
            pass

    def close(self, drain: bool = True) -> None:
        with self._scale_lock:
            with self._lock:
                self._closed = True
                reps = list(self._replicas.values())
                self._replicas.clear()
            for rep in reps:
                rep.engine.shutdown(drain=drain)
