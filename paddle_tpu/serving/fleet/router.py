"""Fleet router: one front door over N replicas.

Dispatch policies (PT_FLEET_POLICY picks the default for sessionless
traffic; a request carrying a session key ALWAYS routes affine):

  least_loaded    min over healthy replicas of queue-depth x
                  EWMA-service-time (pool.Replica.load_score — the same
                  two numbers the pt_serve_* metrics export). Skewed
                  fleets (one slow replica) self-balance: the slow
                  replica's depth and EWMA both grow, so its score does.
  round_robin     rotate over healthy replicas — the baseline policy
                  the bench A/B compares least_loaded against.
  session affine  rendezvous (highest-random-weight) hash of the
                  session key over healthy replica ids: a session keeps
                  hitting the replica that holds its paged KV blocks,
                  and a scale event only remaps the sessions whose
                  replica actually changed (adding a replica moves
                  ~1/n of sessions; removing one moves only ITS
                  sessions). Replica ids are stable across rebuilds, so
                  a rebuilt replica keeps its sessions.

Failover: a dispatch that dies with `RequestFailed` (the replica's
dispatcher crashed running the batch) retries once on the next-best
replica — the retry budget and the what-is-retryable predicate both
live on an injectable resilience.RetryPolicy — and the dead replica is
marked unhealthy and rebuilt off to the side (pool.mark_unhealthy).
Submit-time refusals (Overloaded / ModelUnavailable from a replica that
is draining, and the `router_dispatch` chaos site's injected crash)
roll to the next healthy replica immediately. A request is never failed
while an untried healthy replica remains, and never retried on a
replica it already failed on.

Priority admission is the WeightedFairQueue (fleet/admission.py): one
router-level queue, weighted-fair service across classes, strict
lowest-class-first shedding under overload.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from ...obs import trace as obs_trace
from ...resilience import faults
from ...resilience.retry import RetryPolicy
from ..admission import (ModelUnavailable, Overloaded, RequestFailed,
                         ServingError)
from .admission import PendingRequest, WeightedFairQueue
from .metrics import FleetMetrics
from .pool import Replica, ReplicaPool

__all__ = ["FleetRouter", "POLICIES", "crash_failover"]

POLICIES = ("least_loaded", "round_robin")


def crash_failover(exc: BaseException) -> bool:
    """The default failover predicate: retry a request whose batch died
    with the dispatcher (RequestFailed) — never a typed rejection that
    would deterministically repeat (InvalidRequest) and never a result
    the client already owns."""
    return isinstance(exc, RequestFailed)


def _rendezvous(session: str, candidates: List[Replica]) -> Replica:
    """Highest-random-weight hash: stable per (session, rid), minimal
    remap under membership change."""
    def score(r: Replica) -> int:
        h = hashlib.blake2b(f"{session}|{r.rid}".encode(),
                            digest_size=8)
        return int.from_bytes(h.digest(), "big")
    return max(candidates, key=score)


class FleetRouter:
    """Priority-admitted, policy-routed front door over a ReplicaPool.

    >>> pool = ReplicaPool(loader, replicas=4)
    >>> router = FleetRouter(pool)
    >>> fut = router.submit("ranker", {"x": ex}, priority=1,
    ...                     session="user-42")
    >>> router.predict("ranker", {"x": ex})          # blocking
    """

    def __init__(self, pool: ReplicaPool, *,
                 policy: Optional[str] = None,
                 queue_depth: int = 1024,
                 class_weights: Optional[Dict[int, float]] = None,
                 default_deadline_ms: float = 0.0,
                 failover: Optional[RetryPolicy] = None,
                 metrics: Optional[FleetMetrics] = None,
                 name: str = "fleet"):
        if policy is None:
            policy = os.environ.get("PT_FLEET_POLICY", "").strip() \
                or "least_loaded"
        if policy not in POLICIES:
            raise ValueError(f"unknown fleet policy {policy!r} "
                             f"(choose from {POLICIES} — session "
                             "affinity is per-request, via session=)")
        self.pool = pool
        self.policy = policy
        self.name = name
        self.default_deadline_ms = float(default_deadline_ms)
        self.failover = failover or RetryPolicy(retries=1,
                                                retry_on=crash_failover)
        self.metrics = metrics or FleetMetrics(name)
        self.metrics.bind(pool=pool, router=self)
        self.metrics.register()
        # registration may have suffixed the name (two fleets in one
        # process): the router follows, so status/scrape/traces agree
        self.name = self.metrics.name
        pool.metrics = self.metrics
        self.autoscaler = None   # attached by make_fleet / caller
        self._wfq = WeightedFairQueue(queue_depth,
                                      class_weights=class_weights)
        self._cv = threading.Condition()
        self._rr = 0
        self._closed = False
        self._loop_done = False   # set under _cv at dispatcher exit
        self._drained = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"pt-fleet[{name}]")
        self._thread.start()

    # -- client side ---------------------------------------------------------
    def queue_depths(self) -> Dict[int, int]:
        with self._cv:
            return self._wfq.depths()

    def _deadline_t(self, deadline_ms: Optional[float]) -> Optional[float]:
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if not deadline_ms or deadline_ms <= 0:
            return None
        return time.monotonic() + float(deadline_ms) / 1e3

    def submit(self, model: str, feeds, *, priority: int = 0,
               session: Optional[str] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Admit one request into the fleet queue; returns a Future.
        Overloaded raises HERE when this request is the shed victim
        (reject-fast, shed_class attached); a queued lower-class victim
        it displaced gets the same typed error on its Future."""
        item = PendingRequest(model, feeds, cls=priority,
                              session=session,
                              deadline_t=self._deadline_t(deadline_ms))
        self._model_of(model)   # reject-fast: unknown names never queue
        with self._cv:
            if self._closed or self._loop_done:
                # _loop_done without _closed = the dispatcher died
                # abnormally; queueing would hang the client forever
                raise ModelUnavailable(
                    f"fleet {self.name!r} is shut down")
            try:
                victim = self._wfq.offer(item)
            except Overloaded:
                self.metrics.on_shed(item.cls)
                raise
            self._cv.notify()
        if victim is not None:
            self.metrics.on_shed(victim.cls)
            if not victim.future.done():
                victim.future.set_exception(Overloaded(
                    f"shed from the fleet queue by a class-"
                    f"{item.cls} arrival (lowest-class-first)",
                    shed_class=victim.cls))
        return item.future

    def predict(self, model: str, feeds, *, priority: int = 0,
                session: Optional[str] = None,
                deadline_ms: Optional[float] = None,
                timeout: Optional[float] = None) -> Dict:
        fut = self.submit(model, feeds, priority=priority,
                          session=session, deadline_ms=deadline_ms)
        if timeout is None and deadline_ms:
            timeout = deadline_ms / 1e3 + 30.0
        return fut.result(timeout=timeout)

    def generate(self, model: str, prompt_ids, *,
                 session: Optional[str] = None, **kw):
        """Route one generation request (decode plane). Session-affine
        when a session key rides along — decode sessions keep hitting
        the replica that holds their paged KV blocks; the decode
        engine's own continuous-batching admission takes it from there.
        Dispatch-time refusals fail over to the next-best replica."""
        tried: set = set()
        busy: Optional[Overloaded] = None
        crashed: Optional[RequestFailed] = None
        healed = False
        while True:
            replica = self._pick_for(session, tried)
            if replica is None:
                if not healed and self.pool.ensure_min():
                    healed = True   # crash-emptied pool re-grown
                    continue
                if busy is not None:
                    raise busy   # every replica full — typed, retryable
                if crashed is not None:
                    raise crashed   # exhaustion surfaces the ORIGINAL
                raise ModelUnavailable(
                    f"no healthy replica can serve {model!r}")
            try:
                faults.crash_point("router_dispatch")
                handle = replica.engine.generate(model, prompt_ids, **kw)
            except faults.FaultInjected as e:
                self._replica_crashed(replica, e)
                tried.add(replica.rid)
                crashed = RequestFailed(
                    f"replica {replica.rid!r} crashed dispatching a "
                    f"generation to {model!r}: {e}", cause=e)
                continue
            except Overloaded as e:
                busy = e
                tried.add(replica.rid)
                continue
            except ModelUnavailable:
                tried.add(replica.rid)
                continue
            self.metrics.on_dispatch(
                "session_affine" if session is not None else self.policy)
            return handle

    # -- routing -------------------------------------------------------------
    def _pick_for(self, session: Optional[str],
                  excluded: set) -> Optional[Replica]:
        candidates = [r for r in self.pool.replicas()
                      if r.rid not in excluded]
        if not candidates:
            return None
        if session is not None:
            return _rendezvous(session, candidates)
        if self.policy == "round_robin":
            self._rr += 1
            return candidates[self._rr % len(candidates)]
        return min(candidates, key=lambda r: r.load_score())


    # -- dispatcher side -----------------------------------------------------
    def _loop(self) -> None:
        backoff = False
        try:
            while True:
                with self._cv:
                    if backoff:
                        # every replica queue was full a moment ago:
                        # poll — replica slots free when their batches
                        # complete (no cross-engine notification)
                        self._cv.wait(0.002)
                        backoff = False
                    while True:
                        item = self._wfq.pop()
                        if item is not None:
                            break
                        if self._closed:
                            # flagged under _cv so a late failover
                            # requeue can never land in a queue no
                            # thread will pop again
                            self._loop_done = True
                            return
                        self._cv.wait(0.5)
                try:
                    requeue = not self._dispatch(item)
                except BaseException as e:  # noqa: BLE001 — contained
                    # per-request containment, the batcher's lesson at
                    # router altitude: one poisoned request fails ITS
                    # future typed; the dispatcher thread keeps serving
                    if not item.future.done():
                        item.future.set_exception(RequestFailed(
                            f"fleet dispatch failed for "
                            f"{item.model!r}: {e}", cause=e))
                    self.metrics.on_done(False)
                    continue
                if requeue:
                    with self._cv:
                        self._wfq.push_front(item)
                    backoff = True
        finally:
            # on EVERY exit path — including an abnormal death the
            # per-item containment didn't cover — flag the loop done
            # under the cv, so submit() refuses new work and _requeue
            # fails over typed instead of feeding a queue nothing pops
            with self._cv:
                self._loop_done = True
            self._drained.set()

    def _requeue(self, item: PendingRequest) -> None:
        with self._cv:
            if not self._loop_done:
                self._wfq.push_front(item)
                self._cv.notify()
                return
        # the dispatcher already exited (shutdown raced this failover):
        # stranding the future in a dead queue would hang the client
        # forever — fail typed and retryable instead
        if not item.future.done():
            item.future.set_exception(Overloaded(
                f"fleet {self.name!r} shut down while failing over "
                f"{item.model!r}", shed_class=item.cls))
        self.metrics.on_done(False)

    def _dispatch(self, item: PendingRequest) -> bool:
        """Route one request to a replica; called from the dispatcher
        loop AND from failover callbacks (replica dispatcher threads).
        Returns False when the whole fleet is momentarily saturated
        (every healthy replica refused Overloaded) — the caller
        re-queues the request at the head of its class, so backpressure
        backs the FLEET queue up and the shed machinery engages there;
        a request is never failed over a transient full queue."""
        now = time.monotonic()
        if item.deadline_t is not None and now >= item.deadline_t:
            self.metrics.on_shed(item.cls, kind="deadline")
            if not item.future.done():
                from ..admission import DeadlineExceeded
                item.future.set_exception(DeadlineExceeded(
                    f"request spent {(now - item.t_enqueue) * 1e3:.1f} "
                    "ms in the fleet queue, past its deadline"))
            return True
        refused: set = set()
        busy = False
        healed = False
        while True:
            replica = self._pick_for(item.session,
                                     item.tried | refused)
            if replica is None:
                if busy:
                    return False    # saturated, not dead: requeue
                if not healed and self.pool.ensure_min():
                    # a crash-surrendered pool below its floor just
                    # minted fresh replicas (new ids, never in tried)
                    healed = True
                    continue
                if not item.future.done():
                    # exhaustion re-raises the ORIGINAL typed error
                    # (a single-replica fleet whose dispatcher crashed
                    # surfaces RequestFailed, never a 404 wrapper)
                    item.future.set_exception(
                        item.last_error if item.last_error is not None
                        else ModelUnavailable(
                            f"no healthy replica left to serve "
                            f"{item.model!r} "
                            f"(tried {sorted(item.tried)})"))
                self.metrics.on_done(False)
                return True
            remaining_ms = None
            if item.deadline_t is not None:
                remaining_ms = max(
                    (item.deadline_t - time.monotonic()) * 1e3, 1.0)
            try:
                faults.crash_point("router_dispatch")
                fut = replica.engine.submit(item.model, item.feeds,
                                            deadline_ms=remaining_ms)
            except faults.FaultInjected as e:
                # the chaos harness's deterministic replica crash at
                # dispatch: treat exactly like a dead dispatcher (the
                # typed surface a real dispatch crash would carry)
                self._replica_crashed(replica, e)
                item.tried.add(replica.rid)
                item.last_error = RequestFailed(
                    f"replica {replica.rid!r} crashed dispatching to "
                    f"model {item.model!r}: {e}", cause=e)
                continue
            except Overloaded:
                # this replica's queue is full — it is healthy, just
                # busy; never counts against the failover budget
                refused.add(replica.rid)
                busy = True
                continue
            except ModelUnavailable:
                # draining or mid-swap: roll to the next replica
                refused.add(replica.rid)
                continue
            except ServingError as e:
                if not item.future.done():
                    item.future.set_exception(e)
                self.metrics.on_done(False)
                return True
            self.metrics.on_dispatch(
                "session_affine" if item.session is not None
                else self.policy)
            fut.add_done_callback(
                lambda f, it=item, r=replica: self._on_result(it, r, f))
            return True

    def _replica_crashed(self, replica: Replica, exc: BaseException):
        self.metrics.on_failover()
        # pass the exact object: a straggler failure surfacing after
        # this slot was already rebuilt must not condemn the new engine
        self.pool.mark_unhealthy(replica.rid,
                                 cause=f"{type(exc).__name__}: {exc}",
                                 replica=replica)

    def _on_result(self, item: PendingRequest, replica: Replica,
                   fut: Future) -> None:
        exc = fut.exception()
        if exc is None:
            if not item.future.done():
                item.future.set_result(fut.result())
            self.metrics.on_done(True)
            return
        if (self.failover.should_retry(exc)
                and item.result_retries < self.failover.retries):
            # the replica's dispatcher died running this batch: mark it
            # unhealthy (rebuilt off to the side) and retry once on the
            # next-best replica
            item.result_retries += 1
            item.tried.add(replica.rid)
            item.last_error = exc
            self._replica_crashed(replica, exc)
            obs_trace.instant("fleet_failover", cat="fleet",
                              model=item.model, replica=replica.rid)
            if not self._dispatch(item):
                self._requeue(item)
            return
        if not item.future.done():
            item.future.set_exception(exc)
        self.metrics.on_done(False)

    # -- front-end surface (http.py serves a fleet like an engine) ----------
    is_fleet = True

    def models(self) -> Dict[str, dict]:
        for replica in self.pool.replicas():
            return replica.engine.models()
        return {}

    def _model_of(self, model: str):
        """The loaded model object behind `model` on any replica, or
        raise ModelUnavailable — the fleet keeps the single-engine
        reject-fast contract: a name no replica serves must never
        consume a queue slot (or shed a real request). Unhealthy
        replicas still count as catalog (a fleet mid-rebuild knows
        what it serves; the request queues and waits, it isn't a
        404)."""
        replicas = self.pool.all_replicas()
        if not replicas and self.pool.ensure_min():
            # a crash-emptied pool has no catalog to consult: heal to
            # the floor first — "the loader is down" must read as a
            # recoverable outage, not model-not-found
            replicas = self.pool.all_replicas()
        for replica in replicas:
            try:
                return replica.engine.registry.get(model).model
            except ModelUnavailable:
                continue
        raise ModelUnavailable(
            f"no replica of fleet {self.name!r} serves {model!r}")

    def model_info(self, model: str) -> tuple:
        """(feed_dtypes, version) in ONE catalog walk — the HTTP
        predict path needs both per request; walking the pool twice
        (plus submit's own reject-fast walk) would triple the registry
        lock traffic for the same answer."""
        m = self._model_of(model)
        fd = getattr(m, "feed_dtypes", None)
        return (fd() if callable(fd) else {},
                getattr(m, "version", None))

    def feed_dtypes(self, model: str) -> dict:
        return self.model_info(model)[0]

    def model_version(self, model: str) -> Optional[int]:
        return self.model_info(model)[1]

    def load_model(self, name: str, model_dir: str, **kw) -> int:
        """Fleet-wide (hot) reload: every replica swaps, each under the
        single-engine zero-drop contract."""
        ver = 0
        for replica in self.pool.all_replicas():
            ver = replica.engine.load_model(name, model_dir, **kw)
        return ver

    def status(self) -> dict:
        out = {
            "name": self.name,
            "policy": self.policy,
            "replicas": self.pool.health(),
            "min_replicas": self.pool.min_replicas,
            "max_replicas": self.pool.max_replicas,
            "queue": {str(c): n for c, n in
                      self.queue_depths().items()},
        }
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.describe()
        return out

    def metrics_snapshot(self) -> dict:
        """One pane for the whole tier: the fleet section + every
        replica's serving sections, namespaced by replica id (the
        multi-replica scrape stays duplicate-series-free), + the
        process-wide registry sections merged ONCE."""
        from ...obs.metrics import REGISTRY
        out: Dict[str, dict] = {"models": {}, "decode": {}}
        for replica in self.pool.all_replicas():
            # each snapshot already carries its replica id — the pool
            # stamps engine.metrics.replica at build; ONE mechanism
            snap = replica.engine.metrics.snapshot(merge_registry=False)
            for section in ("models", "decode"):
                for mname, msnap in snap.get(section, {}).items():
                    out[section][f"{replica.rid}/{mname}"] = msnap
        if not out["decode"]:
            del out["decode"]
        for section, snaps in REGISTRY.snapshot().items():
            if snaps:
                out.setdefault(section, snaps)
        return out

    # -- shutdown ------------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        if self.autoscaler is not None:
            self.autoscaler.stop()
        with self._cv:
            if self._closed:
                return
            self._closed = True
            backlog = [] if drain else self._wfq.drain()
            self._cv.notify()
        for item in backlog:
            if not item.future.done():
                item.future.set_exception(ModelUnavailable(
                    f"fleet {self.name!r} shut down before dispatch"))
        self._drained.wait(30.0)
        self._thread.join(5.0)
        self.pool.close(drain=drain)
        self.metrics.unregister()

    shutdown = close
