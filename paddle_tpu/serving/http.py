"""Thin stdlib HTTP front end over the ServingEngine.

Deliberately ThreadingHTTPServer, not a framework: the container ships
no web dependencies, and the engine already does the hard part — each
handler thread blocks on its request's Future while the dispatcher
coalesces across ALL handler threads, so concurrency here is free
batching there. One handler thread per in-flight request is exactly the
concurrency model the micro-batcher wants.

Routes (JSON in/out):

    POST /v1/models/<name>:predict   {"feeds": {name: nested-list},
                                      "deadline_ms": optional}
         -> {"fetches": {name: {"data","shape","dtype"}}, "model_version"}
    POST /v1/models/<name>:generate  {"prompt_ids": [ints],
                                      "max_new_tokens", "deadline_ms",
                                      "priority", "eos_id",
                                      "stream": bool (default true)}
         stream=true  -> chunked application/x-ndjson: one
                         {"token": t, "index": i} line per generated
                         token, then {"done": true, "tokens": [...],
                         "finish_reason": ...}
         stream=false -> one JSON body with the final result
    POST /v1/models/<name>:reload    {"model_dir": path} -> {"version": N}
    GET  /v1/models                  registry description
    GET  /v1/fleet                   fleet-tier status (replica health,
                                     queue depths per class, autoscaler
                                     state) — 404 on a single engine
    GET  /v1/metrics                 metrics snapshot (JSON)
    GET  /v1/metrics?format=prometheus
         (also /metrics)             Prometheus text exposition of the
                                     same snapshot — both serving planes
                                     (one-shot + decode) in one scrape

The same server fronts a fleet router (serving/fleet/FleetRouter) —
anything with the engine surface plus `is_fleet` serves the extra
tier: requests may carry a `priority` field (body) and an
`X-PT-Session` affinity header, so a session keeps hitting the replica
that holds its paged KV blocks, and paid-tier traffic classes ahead of
free-tier in the fleet queue.

Typed serving errors map to their http_status (429 Overloaded, 504
DeadlineExceeded, 404 ModelUnavailable, 400 InvalidRequest, 500
RequestFailed) with a JSON body naming the error type, so clients can
key retry policy off the type exactly like in-process callers do
(admission.retryable). A fleet Overloaded response body additionally
carries `shed_class` — which priority class was shed. A typed error that fires MID-STREAM (a sequence
shed after its first tokens went out) arrives as a terminal
{"error": type, "message": ...} NDJSON line — the status line already
shipped, so the error type rides in-band.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from ..obs import trace as obs_trace
from .admission import InvalidRequest, ServingError
from .metrics import render_prometheus

__all__ = ["make_server", "start_http_server"]


class _Handler(BaseHTTPRequestHandler):
    # the engine rides on the server object (make_server sets it)
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # tests must stay quiet
        pass

    # -- helpers -------------------------------------------------------------
    def _send(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_error_typed(self, exc: BaseException) -> None:
        status = getattr(exc, "http_status", 500)
        body = {"error": type(exc).__name__, "message": str(exc)}
        if getattr(exc, "shed_class", None) is not None:
            body["shed_class"] = exc.shed_class
        self._send(status, body)

    def _read_json(self) -> dict:
        n = int(self.headers.get("Content-Length", 0) or 0)
        raw = self.rfile.read(n) if n else b""
        if not raw:
            return {}
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise InvalidRequest(f"request body is not JSON: {e}") from e
        if not isinstance(body, dict):
            raise InvalidRequest("request body must be a JSON object")
        return body

    def _model_route(self, suffix: str) -> Optional[Tuple[str, str]]:
        prefix = "/v1/models/"
        if not self.path.startswith(prefix) or \
                not self.path.endswith(suffix):
            return None
        name = self.path[len(prefix):-len(suffix)]
        return (name, suffix) if name else None

    # -- routes --------------------------------------------------------------
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        engine = self.server.engine
        split = urllib.parse.urlsplit(self.path)
        query = urllib.parse.parse_qs(split.query)
        try:
            if split.path == "/v1/models":
                self._send(200, {"models": engine.models()})
            elif split.path == "/v1/fleet":
                if getattr(engine, "is_fleet", False):
                    self._send(200, engine.status())
                else:
                    self._send(404, {"error": "NotFound",
                                     "message": "no fleet tier — this "
                                     "is a single serving engine"})
            elif split.path in ("/v1/metrics", "/metrics"):
                if query.get("format", [""])[0] == "prometheus":
                    body = render_prometheus(
                        engine.metrics_snapshot()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._send(200, engine.metrics_snapshot())
            else:
                self._send(404, {"error": "NotFound",
                                 "message": self.path})
        except Exception as e:  # noqa: BLE001 — typed error boundary
            self._send_error_typed(e)

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        engine = self.server.engine
        try:
            route = self._model_route(":predict")
            if route is not None:
                return self._predict(engine, route[0])
            route = self._model_route(":generate")
            if route is not None:
                return self._generate(engine, route[0])
            route = self._model_route(":reload")
            if route is not None:
                body = self._read_json()
                model_dir = body.get("model_dir")
                if not model_dir:
                    raise InvalidRequest("reload needs {'model_dir': …}")
                ver = engine.load_model(route[0], model_dir,
                                        version=body.get("version"))
                return self._send(200, {"model": route[0],
                                        "version": ver})
            self._send(404, {"error": "NotFound", "message": self.path})
        except ServingError as e:
            self._send_error_typed(e)
        except Exception as e:  # noqa: BLE001 — boundary: never a 200
            self._send_error_typed(e)

    def _predict(self, engine, name: str) -> None:
        # the ingress span is the request's trace ROOT: engine.submit
        # runs on this handler thread, so the batcher's Request captures
        # this context and the dispatcher parents the queue/batch spans
        # under it — "why was this request slow" reads as one trace
        with obs_trace.span("http_request", cat="serve",
                            route="predict", model=name):
            body = self._read_json()
            feeds_in = body.get("feeds")
            if not isinstance(feeds_in, dict) or not feeds_in:
                raise InvalidRequest(
                    "predict needs {'feeds': {name: value}}")
            fleet = getattr(engine, "is_fleet", False)
            if fleet:
                # one catalog walk for both (ModelUnavailable -> 404,
                # reject-fast — parity with the single-engine branch)
                dtypes, version = engine.model_info(name)
            else:
                # one routing read, public surface only
                # (ModelUnavailable -> 404)
                model = engine.registry.get(name).model
                # dtype-faithful conversion: the model's feed dtypes
                # win over whatever JSON number type the client sent
                dtypes = model.feed_dtypes()
                version = model.version
            feeds = {}
            for k, v in feeds_in.items():
                try:
                    feeds[k] = (np.asarray(v, dtype=dtypes[k])
                                if k in dtypes else np.asarray(v))
                except (TypeError, ValueError) as e:
                    raise InvalidRequest(
                        f"feed {k!r} is not coercible: {e}") from e
            if fleet:
                # the router-level surface: priority classes the fleet
                # queue serves weighted-fair, and a session key that
                # pins this client to its affine replica
                try:
                    priority = int(body.get("priority") or 0)
                except (TypeError, ValueError) as e:
                    raise InvalidRequest(
                        f"priority {body.get('priority')!r} is not an "
                        "integer class") from e
                fut = engine.submit(
                    name, feeds, priority=priority,
                    session=self.headers.get("X-PT-Session"),
                    deadline_ms=body.get("deadline_ms"))
            else:
                fut = engine.submit(name, feeds,
                                    deadline_ms=body.get("deadline_ms"))
            result = fut.result()   # engine deadline machinery bounds this
            fetches = {
                k: {"data": v.tolist(), "shape": list(v.shape),
                    "dtype": v.dtype.name}
                for k, v in result.items()}
            self._send(200, {"fetches": fetches,
                             "model_version": version})

    def _generate(self, engine, name: str) -> None:
        body = self._read_json()
        prompt = body.get("prompt_ids")
        if not isinstance(prompt, list) or not prompt:
            raise InvalidRequest(
                "generate needs {'prompt_ids': [int, ...]}")
        kw = {}
        for key in ("max_new_tokens", "deadline_ms", "priority",
                    "eos_id"):
            if body.get(key) is not None:
                kw[key] = body[key]
        # typed admission errors raise BEFORE any response bytes -> they
        # map to their status like every other route. The ingress span
        # roots the request's trace: the decode scheduler parents its
        # prefill/decode/evict/resume events under this context. For
        # non-streaming requests it also covers the result() wait (the
        # full wall time, like _predict); a streaming response's span
        # necessarily closes at submit — its duration lives in the
        # scheduler's per-sequence events instead.
        if getattr(engine, "is_fleet", False):
            # decode sessions are stateful (paged KV blocks live on ONE
            # replica): the affinity header keeps a session's turns on
            # the replica that holds them
            session = self.headers.get("X-PT-Session")
            if session is not None:
                kw["session"] = session
        with obs_trace.span("http_request", cat="serve",
                            route="generate", model=name):
            handle = engine.generate(name, prompt, **kw)
            if not body.get("stream", True):
                result = handle.result()
                return self._send(200, result)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def chunk(payload: dict) -> None:
            data = (json.dumps(payload) + "\n").encode()
            self.wfile.write(f"{len(data):X}\r\n".encode() + data
                             + b"\r\n")
            self.wfile.flush()

        try:
            i = 0
            for tok in handle.stream():
                chunk({"token": int(tok), "index": i})
                i += 1
            result = handle.result()
            result["done"] = True
            chunk(result)
        except OSError:
            # client hung up mid-stream: the status line already went
            # out, so nothing more may be written to this socket (a
            # second status line would be protocol garbage) — close
            self.close_connection = True
            return
        except Exception as e:  # noqa: BLE001 — in-band terminal error
            try:
                chunk({"error": type(e).__name__, "message": str(e)})
            except OSError:
                self.close_connection = True
                return
        try:
            self.wfile.write(b"0\r\n\r\n")
        except OSError:
            self.close_connection = True


def make_server(engine, host: str = "127.0.0.1",
                port: int = 0) -> ThreadingHTTPServer:
    """Build (without starting) the HTTP server; `server.engine` is set.
    port=0 binds an ephemeral port (tests)."""
    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    server.engine = engine
    return server


def start_http_server(engine, host: str = "127.0.0.1", port: int = 0):
    """Start serving on a daemon thread. Returns (server, thread); stop
    with server.shutdown()."""
    server = make_server(engine, host, port)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="pt-serve-http")
    thread.start()
    return server, thread
