"""Serving metrics: per-model QPS, batch-fill ratio, queue depth, and
phase-split latency percentiles.

The four request phases mirror the training hot path's PhaseTimer
attribution (core/async_fetch.py) translated to the serving request
lifecycle:

    queue    submit -> the dispatcher picks the request's batch
    pad      gathering + zero-padding the batch into its bucket shape
    device   the compiled bucket executable, incl. host materialization
    scatter  splitting per-request rows back out of the batch outputs

pad/device/scatter are per-BATCH costs; every request in the batch is
charged the same share (the phases answer "where does a request's wall
time go", not "what does a request marginally cost"). Percentiles come
from a bounded ring of recent samples (default 2048) — a serving process
must not grow memory with request count, and "recent p99" is the number
an operator actually wants.

Snapshots are plain dicts (json-able) so tests assert on them and
bench.py embeds them verbatim in the BENCH artifact.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..core.async_fetch import PhaseTimer
from ..obs.metrics import REGISTRY, percentiles
from ..obs.metrics import render_prometheus  # noqa: F401 — re-export:
# the ONE exposition renderer now lives on the unified metrics plane
# (obs/metrics.py); existing importers keep working unchanged.

__all__ = ["ServingPhaseTimer", "ModelMetrics", "DecodeMetrics",
           "ServingMetrics", "PHASES", "render_prometheus"]

PHASES = ("queue", "pad", "device", "scatter")

#: per-phase ring size for percentile estimation
RESERVOIR = 2048


class ServingPhaseTimer(PhaseTimer):
    """PhaseTimer (same span()/add() surface as the executor's) over the
    serving request phases. snapshot() is re-derived here: the training
    timer's host_overhead_pct reads training-phase keys that do not
    exist on this axis. Emitted trace spans land under the "serve"
    category (one timing source, two views — see PhaseTimer.add)."""

    PHASES = PHASES
    trace_cat = "serve"

    def snapshot(self, reset: bool = False) -> dict:
        with self._lock:
            out = {f"{p}_s": round(self._s[p], 6) for p in self.PHASES}
            out["batches"] = self._runs
            if reset:
                self._s = {p: 0.0 for p in self.PHASES}
                self._runs = 0
        return out


#: p50/p95/p99 by nearest-rank, in ms — shared with the train-plane
#: family (obs/metrics.py owns the one implementation now)
_percentiles = percentiles


class ModelMetrics:
    """One model's counters + phase timer + latency reservoirs.
    Thread-safe: submitters, the dispatcher, and HTTP scrapes all touch
    it concurrently."""

    def __init__(self, name: str,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self.timer = ServingPhaseTimer()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._clock()
            self.received = 0
            self.completed = 0
            self.failed = 0
            self.shed_overload = 0
            self.shed_deadline = 0
            self.batches = 0
            self.batch_slots_used = 0
            self.batch_slots_total = 0
            self.queue_depth = 0
            self.reloads = 0
            self._lat: Dict[str, deque] = {
                p: deque(maxlen=RESERVOIR) for p in PHASES}
            self._lat["total"] = deque(maxlen=RESERVOIR)
        self.timer.reset()

    # -- recording ----------------------------------------------------------
    def on_received(self, queue_depth: int) -> None:
        with self._lock:
            self.received += 1
            self.queue_depth = queue_depth

    def on_shed(self, kind: str) -> None:
        with self._lock:
            if kind == "overload":
                self.shed_overload += 1
            else:
                self.shed_deadline += 1

    def on_batch(self, used: int, capacity: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_slots_used += used
            self.batch_slots_total += capacity

    def on_done(self, ok: bool, queue_depth: int,
                phase_s: Optional[Dict[str, float]] = None,
                total_s: Optional[float] = None) -> None:
        with self._lock:
            if ok:
                self.completed += 1
            else:
                self.failed += 1
            self.queue_depth = queue_depth
            if phase_s:
                for p, s in phase_s.items():
                    if p in self._lat:
                        self._lat[p].append(s)
            if total_s is not None:
                self._lat["total"].append(total_s)

    def on_reload(self) -> None:
        with self._lock:
            self.reloads += 1

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            elapsed = max(self._clock() - self._t0, 1e-9)
            fill = (self.batch_slots_used / self.batch_slots_total
                    if self.batch_slots_total else None)
            out = {
                "model": self.name,
                "received": self.received,
                "completed": self.completed,
                "failed": self.failed,
                "shed_overload": self.shed_overload,
                "shed_deadline": self.shed_deadline,
                "queue_depth": self.queue_depth,
                "reloads": self.reloads,
                "batches": self.batches,
                "batch_fill_ratio": round(fill, 4) if fill is not None
                else None,
                "qps": round(self.completed / elapsed, 2),
                "window_s": round(elapsed, 3),
                "latency": {k: _percentiles(list(v))
                            for k, v in self._lat.items()},
            }
        out["phases"] = self.timer.snapshot()
        return out


class DecodeMetrics:
    """One decode engine's counters: sequences, tokens, continuous-batch
    slot occupancy, and KV-pool pressure. The decode axis is different
    enough from the request/batch axis that it gets its own type —
    tokens/s and slot occupancy are THE numbers for a generation engine,
    where QPS and batch fill are the numbers for a one-shot one."""

    def __init__(self, name: str,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._clock()
            self.received = 0
            self.completed = 0
            self.failed = 0
            self.shed_overload = 0
            self.shed_deadline = 0
            self.evictions = 0
            self.resumes = 0
            self.prefills = 0
            self.prefill_tokens = 0
            self.steps = 0
            self.tokens_out = 0
            self.slots_used_sum = 0
            self.slots_capacity_sum = 0
            self.prefill_s = 0.0
            self.decode_s = 0.0
            self.active = 0
            self.waiting = 0
            self.kv_blocks_in_use = 0
            self.kv_blocks_capacity = 0
            self.kv_high_water = 0
            # KV economics (decode/prefix.py + decode/spec.py)
            self.kv_shared_hits = 0
            self.kv_shared_tokens = 0
            self.kv_cow_copies = 0
            self.kv_blocks_shared = 0
            self.kv_blocks_indexed = 0
            self.spec_steps = 0
            self.spec_drafted = 0
            self.spec_accepted = 0
            self.spec_fallbacks = 0

    # -- recording ----------------------------------------------------------
    def on_received(self) -> None:
        with self._lock:
            self.received += 1

    def on_finished(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self.completed += 1
            else:
                self.failed += 1

    def on_shed(self, kind: str) -> None:
        with self._lock:
            if kind == "overload":
                self.shed_overload += 1
            else:
                self.shed_deadline += 1

    def on_evicted(self) -> None:
        with self._lock:
            self.evictions += 1

    def on_resumed(self) -> None:
        with self._lock:
            self.resumes += 1

    def on_prefill(self, tokens: int, seconds: float) -> None:
        with self._lock:
            self.prefills += 1
            self.prefill_tokens += tokens
            self.prefill_s += seconds

    def on_step(self, used: int, capacity: int, seconds: float,
                tokens: int) -> None:
        with self._lock:
            self.steps += 1
            self.slots_used_sum += used
            self.slots_capacity_sum += capacity
            self.decode_s += seconds
            self.tokens_out += tokens

    def on_prefix_hit(self, tokens: int, blocks: int) -> None:
        with self._lock:
            self.kv_shared_hits += 1
            self.kv_shared_tokens += tokens

    def on_cow(self) -> None:
        with self._lock:
            self.kv_cow_copies += 1

    def on_spec(self, drafted: int, accepted: int) -> None:
        with self._lock:
            self.spec_steps += 1
            self.spec_drafted += drafted
            self.spec_accepted += accepted

    def on_spec_fallback(self) -> None:
        with self._lock:
            self.spec_fallbacks += 1

    def set_gauges(self, *, active: int, waiting: int, blocks_in_use: int,
                   blocks_capacity: int, high_water: int,
                   blocks_shared: int = 0,
                   blocks_indexed: int = 0) -> None:
        with self._lock:
            self.active = active
            self.waiting = waiting
            self.kv_blocks_in_use = blocks_in_use
            self.kv_blocks_capacity = blocks_capacity
            self.kv_high_water = high_water
            self.kv_blocks_shared = blocks_shared
            self.kv_blocks_indexed = blocks_indexed

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            elapsed = max(self._clock() - self._t0, 1e-9)
            occ = (self.slots_used_sum / self.slots_capacity_sum
                   if self.slots_capacity_sum else None)
            return {
                "model": self.name,
                "received": self.received,
                "completed": self.completed,
                "failed": self.failed,
                "shed_overload": self.shed_overload,
                "shed_deadline": self.shed_deadline,
                "evictions": self.evictions,
                "resumes": self.resumes,
                "prefills": self.prefills,
                "prefill_tokens": self.prefill_tokens,
                "decode_steps": self.steps,
                "tokens_out": self.tokens_out,
                "tokens_per_sec": round(self.tokens_out / elapsed, 2),
                "slot_occupancy": round(occ, 4) if occ is not None
                else None,
                "active": self.active,
                "waiting": self.waiting,
                "kv_blocks_in_use": self.kv_blocks_in_use,
                "kv_blocks_capacity": self.kv_blocks_capacity,
                "kv_high_water": self.kv_high_water,
                "kv_shared_hits": self.kv_shared_hits,
                "kv_shared_tokens": self.kv_shared_tokens,
                "kv_cow_copies": self.kv_cow_copies,
                "kv_blocks_shared": self.kv_blocks_shared,
                "kv_blocks_indexed": self.kv_blocks_indexed,
                "spec_steps": self.spec_steps,
                "spec_drafted": self.spec_drafted,
                "spec_accepted": self.spec_accepted,
                "spec_fallbacks": self.spec_fallbacks,
                "spec_acceptance_rate": (
                    round(self.spec_accepted / self.spec_drafted, 4)
                    if self.spec_drafted else None),
                "prefill_s": round(self.prefill_s, 6),
                "decode_s": round(self.decode_s, 6),
                "window_s": round(elapsed, 3),
            }


class ServingMetrics:
    """The engine-wide registry: one ModelMetrics per model NAME (metrics
    deliberately survive hot reloads — a reload is an event on the
    model's timeline, not a new timeline). Decode engines report through
    the same registry under their own axis (`decode(name)`), so ONE
    snapshot — and one Prometheus scrape — covers both serving planes.

    Multi-engine processes (the fleet tier, serving/fleet/): `replica`
    namespaces this engine's series — every model/decode snapshot
    carries a `replica` key the Prometheus renderer turns into a
    `replica="<id>"` label, so two replicas serving the SAME model name
    scrape as distinct series instead of duplicates (validate_exposition
    rejects the duplicate). The pre-fleet single-engine assumption —
    one engine per process, model name alone identifies a series — is
    exactly what this parameter retires."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 replica: Optional[str] = None):
        self._clock = clock
        self.replica = replica
        self._lock = threading.Lock()
        self._models: Dict[str, ModelMetrics] = {}
        self._decode: Dict[str, DecodeMetrics] = {}

    def model(self, name: str) -> ModelMetrics:
        with self._lock:
            m = self._models.get(name)
            if m is None:
                m = self._models[name] = ModelMetrics(name,
                                                      clock=self._clock)
            return m

    def decode(self, name: str) -> DecodeMetrics:
        with self._lock:
            m = self._decode.get(name)
            if m is None:
                m = self._decode[name] = DecodeMetrics(name,
                                                       clock=self._clock)
            return m

    def snapshot(self, merge_registry: bool = True) -> dict:
        with self._lock:
            models = list(self._models.values())
            decode = list(self._decode.values())
        out = {"models": {m.name: m.snapshot() for m in models}}
        if decode:
            out["decode"] = {m.name: m.snapshot() for m in decode}
        if self.replica is not None:
            for sec in ("models", "decode"):
                for snap in out.get(sec, {}).values():
                    snap["replica"] = self.replica
        # every other plane reports through the same snapshot (and so
        # the same Prometheus scrape) via the unified MetricsRegistry
        # (obs/metrics.py): live input pipelines (pt_data_*), the
        # training loop (pt_train_*), and the predicted-vs-measured
        # drift monitor (pt_model_*) all ride along — one scrape, one
        # observability plane. A fleet router merging N replica
        # snapshots passes merge_registry=False per replica and merges
        # the registry sections ONCE — the one-engine-per-process
        # assumption the fleet satellite fix retires.
        if merge_registry:
            for section, snaps in REGISTRY.snapshot().items():
                if snaps:
                    out.setdefault(section, snaps)
        return out


# The Prometheus text renderer lived here until the obs consolidation
# (obs/metrics.py render_prometheus is the ONE renderer for every
# family — pt_serve_*/pt_decode_*/pt_data_*/pt_train_*/pt_model_*);
# it is re-exported above so importers keep working.
