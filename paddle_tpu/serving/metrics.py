"""Serving metrics: per-model QPS, batch-fill ratio, queue depth, and
phase-split latency percentiles.

The four request phases mirror the training hot path's PhaseTimer
attribution (core/async_fetch.py) translated to the serving request
lifecycle:

    queue    submit -> the dispatcher picks the request's batch
    pad      gathering + zero-padding the batch into its bucket shape
    device   the compiled bucket executable, incl. host materialization
    scatter  splitting per-request rows back out of the batch outputs

pad/device/scatter are per-BATCH costs; every request in the batch is
charged the same share (the phases answer "where does a request's wall
time go", not "what does a request marginally cost"). Percentiles come
from a bounded ring of recent samples (default 2048) — a serving process
must not grow memory with request count, and "recent p99" is the number
an operator actually wants.

Snapshots are plain dicts (json-able) so tests assert on them and
bench.py embeds them verbatim in the BENCH artifact.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..core.async_fetch import PhaseTimer

__all__ = ["ServingPhaseTimer", "ModelMetrics", "ServingMetrics",
           "PHASES"]

PHASES = ("queue", "pad", "device", "scatter")

#: per-phase ring size for percentile estimation
RESERVOIR = 2048


class ServingPhaseTimer(PhaseTimer):
    """PhaseTimer (same span()/add() surface as the executor's) over the
    serving request phases. snapshot() is re-derived here: the training
    timer's host_overhead_pct reads training-phase keys that do not
    exist on this axis."""

    PHASES = PHASES

    def snapshot(self, reset: bool = False) -> dict:
        with self._lock:
            out = {f"{p}_s": round(self._s[p], 6) for p in self.PHASES}
            out["batches"] = self._runs
            if reset:
                self._s = {p: 0.0 for p in self.PHASES}
                self._runs = 0
        return out


def _percentiles(samples: List[float]) -> Dict[str, float]:
    """p50/p95/p99 by nearest-rank over a sorted copy, in milliseconds."""
    if not samples:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None}
    s = sorted(samples)
    n = len(s)

    def rank(q: float) -> float:
        i = min(n - 1, max(0, int(round(q * (n - 1)))))
        return round(s[i] * 1000.0, 3)

    return {"p50_ms": rank(0.50), "p95_ms": rank(0.95),
            "p99_ms": rank(0.99)}


class ModelMetrics:
    """One model's counters + phase timer + latency reservoirs.
    Thread-safe: submitters, the dispatcher, and HTTP scrapes all touch
    it concurrently."""

    def __init__(self, name: str,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self.timer = ServingPhaseTimer()
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self._t0 = self._clock()
            self.received = 0
            self.completed = 0
            self.failed = 0
            self.shed_overload = 0
            self.shed_deadline = 0
            self.batches = 0
            self.batch_slots_used = 0
            self.batch_slots_total = 0
            self.queue_depth = 0
            self.reloads = 0
            self._lat: Dict[str, deque] = {
                p: deque(maxlen=RESERVOIR) for p in PHASES}
            self._lat["total"] = deque(maxlen=RESERVOIR)
        self.timer.reset()

    # -- recording ----------------------------------------------------------
    def on_received(self, queue_depth: int) -> None:
        with self._lock:
            self.received += 1
            self.queue_depth = queue_depth

    def on_shed(self, kind: str) -> None:
        with self._lock:
            if kind == "overload":
                self.shed_overload += 1
            else:
                self.shed_deadline += 1

    def on_batch(self, used: int, capacity: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_slots_used += used
            self.batch_slots_total += capacity

    def on_done(self, ok: bool, queue_depth: int,
                phase_s: Optional[Dict[str, float]] = None,
                total_s: Optional[float] = None) -> None:
        with self._lock:
            if ok:
                self.completed += 1
            else:
                self.failed += 1
            self.queue_depth = queue_depth
            if phase_s:
                for p, s in phase_s.items():
                    if p in self._lat:
                        self._lat[p].append(s)
            if total_s is not None:
                self._lat["total"].append(total_s)

    def on_reload(self) -> None:
        with self._lock:
            self.reloads += 1

    # -- reading ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            elapsed = max(self._clock() - self._t0, 1e-9)
            fill = (self.batch_slots_used / self.batch_slots_total
                    if self.batch_slots_total else None)
            out = {
                "model": self.name,
                "received": self.received,
                "completed": self.completed,
                "failed": self.failed,
                "shed_overload": self.shed_overload,
                "shed_deadline": self.shed_deadline,
                "queue_depth": self.queue_depth,
                "reloads": self.reloads,
                "batches": self.batches,
                "batch_fill_ratio": round(fill, 4) if fill is not None
                else None,
                "qps": round(self.completed / elapsed, 2),
                "window_s": round(elapsed, 3),
                "latency": {k: _percentiles(list(v))
                            for k, v in self._lat.items()},
            }
        out["phases"] = self.timer.snapshot()
        return out


class ServingMetrics:
    """The engine-wide registry: one ModelMetrics per model NAME (metrics
    deliberately survive hot reloads — a reload is an event on the
    model's timeline, not a new timeline)."""

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._models: Dict[str, ModelMetrics] = {}

    def model(self, name: str) -> ModelMetrics:
        with self._lock:
            m = self._models.get(name)
            if m is None:
                m = self._models[name] = ModelMetrics(name,
                                                      clock=self._clock)
            return m

    def snapshot(self) -> dict:
        with self._lock:
            models = list(self._models.values())
        return {"models": {m.name: m.snapshot() for m in models}}
